"""Big-model inference: shape-only init, device maps, offload, streaming forward
(reference ``big_modeling.py`` L6 + ``hooks.py`` offload engine).

Reference mechanism: meta-device init (``init_empty_weights``,
``big_modeling.py:56``), greedy device-map packing (``infer_auto_device_map``),
checkpoint dispatch (``load_checkpoint_and_dispatch``, ``:499``) and per-forward
weight streaming via ``AlignDevicesHook`` (``hooks.py:322-389``).

TPU-native re-design:

* meta init ≡ ``jax.eval_shape`` — abstract trees with zero allocation;
* when the model fits in pooled HBM, ``device_map="sharded"`` places every
  weight with a ``NamedSharding`` over the mesh and one jitted apply runs it —
  GSPMD inserts the collectives; no hooks, no python in the hot loop;
* for the overflow case, :class:`StreamingTransformer` is the AlignDevicesHook
  analog: per-layer jitted compute (ONE executable reused by every layer — all
  decoder layers share shapes) with double-buffered host→HBM transfers: layer
  ``i+1``'s weights stream while layer ``i`` computes.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .utils.modeling import (
    DeviceId,
    SEP,
    compute_module_sizes,
    flatten_tree,
    get_balanced_memory,
    get_max_layer_size,
    infer_auto_device_map,
    top_level_modules,
    unflatten_tree,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict


# --------------------------------------------------------------------- init
def init_empty_weights(model, *args, method: str = "init", rng=None, **kwargs):
    """Abstract (shape-only) parameter tree — the ``init_empty_weights`` analog
    (reference ``big_modeling.py:56-166``; here no monkey-patching: JAX's
    abstract interpretation is first-class via ``jax.eval_shape``)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    fn = getattr(model, method)
    shapes = jax.eval_shape(lambda: fn(rng, *args, **kwargs))
    return shapes["params"] if isinstance(shapes, dict) and "params" in shapes else shapes


def init_params_on_host(model, *args, method: str = "init", rng=None, **kwargs):
    """Materialize freshly initialized parameters directly into pinned host
    memory — the creation path for bigger-than-HBM training states.

    Random init on-device would leave a full-precision parameter tree resident
    in HBM while ``create_train_state`` builds the working copy and the
    (host-offloaded) optimizer chunks; emitting the init program's outputs to
    host memory keeps the HBM peak at transients only.  Falls back to plain
    device init on backends without host memory support (CPU test rigs).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from .parallel.sharding import supports_host_offload
    from .state import PartialState

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    fn = getattr(model, method)

    def run():
        out = fn(rng, *args, **kwargs)
        return out["params"] if isinstance(out, dict) and "params" in out else out

    mesh = PartialState().mesh
    if not supports_host_offload(mesh):
        return jax.jit(run)()
    host = NamedSharding(mesh, PartitionSpec(), memory_kind="pinned_host")
    shapes = jax.eval_shape(run)
    jitted = jax.jit(run, out_shardings=jax.tree_util.tree_map(lambda _: host, shapes))
    placed = jitted()
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if isinstance(x, jax.Array) else x, placed
    )
    # drop the init executable's HBM plan before training compiles — scoped to
    # this program only (a global clear_caches would invalidate any steps the
    # caller already compiled)
    jitted.clear_cache()
    return placed


def checkpoint_shapes(
    checkpoint: str, files: Optional[Dict[str, str]] = None
) -> Dict[str, jax.ShapeDtypeStruct]:
    """Flat {path: ShapeDtypeStruct} read from safetensors headers — no
    tensor bytes are touched (the on-disk analog of meta init)."""
    from safetensors import safe_open

    flat: Dict[str, jax.ShapeDtypeStruct] = {}
    by_file: Dict[str, list] = {}
    for key, fname in (files if files is not None else _checkpoint_files(checkpoint)).items():
        by_file.setdefault(fname, []).append(key)
    for fname, keys in by_file.items():  # one open + header parse per file
        if fname.endswith(".bin"):
            entries = _bin_entries(fname)
            for key in keys:
                t = entries[key]
                flat[key] = jax.ShapeDtypeStruct(tuple(t.shape), _torch_np_dtype(t.dtype))
            continue
        with safe_open(fname, framework="np") as f:
            for key in keys:
                sl = f.get_slice(key)
                flat[key] = jax.ShapeDtypeStruct(
                    tuple(sl.get_shape()), _SAFETENSORS_DTYPES[sl.get_dtype()]
                )
    return flat


_SAFETENSORS_DTYPES = {
    "BOOL": np.dtype(np.bool_),
    "U8": np.dtype(np.uint8), "I8": np.dtype(np.int8),
    "U16": np.dtype(np.uint16), "I16": np.dtype(np.int16),
    "U32": np.dtype(np.uint32), "I32": np.dtype(np.int32),
    "U64": np.dtype(np.uint64), "I64": np.dtype(np.int64),
    "F16": np.dtype(np.float16), "F32": np.dtype(np.float32), "F64": np.dtype(np.float64),
    "BF16": jnp.bfloat16,
}


def _checkpoint_files(checkpoint: str) -> Dict[str, str]:
    """{tensor_name: file path} for a single-file or sharded checkpoint.

    Safetensors is the native format; torch-pickle ``.bin`` checkpoints
    (``pytorch_model.bin`` / ``pytorch_model.bin.index.json``) are read as a
    fallback via torch-cpu (reference ``load_checkpoint_in_model`` handles
    both, ``utils/modeling.py:1608-1830``).
    """
    import json

    if os.path.isfile(checkpoint):
        files = [checkpoint]
    else:
        for index_name in ("model.safetensors.index.json", "pytorch_model.bin.index.json"):
            index_path = os.path.join(checkpoint, index_name)
            if os.path.isfile(index_path):
                with open(index_path) as f:
                    index = json.load(f)
                return {
                    key: os.path.join(checkpoint, fname)
                    for key, fname in index["weight_map"].items()
                }
        for single_name in ("model.safetensors", "pytorch_model.bin"):
            single = os.path.join(checkpoint, single_name)
            if os.path.isfile(single):
                files = [single]
                break
        else:
            raise FileNotFoundError(
                f"No checkpoint found at {checkpoint} (looked for model.safetensors[.index.json] "
                "and pytorch_model.bin[.index.json])"
            )
    mapping: Dict[str, str] = {}
    for fname in files:
        if fname.endswith(".bin"):
            for key in _bin_entries(fname):
                mapping[key] = fname
        else:
            from safetensors import safe_open

            with safe_open(fname, framework="np") as f:
                for key in f.keys():
                    mapping[key] = fname
    return mapping


_BIN_CACHE: Dict[Any, Dict[str, Any]] = {}
_BIN_CACHE_MAX = 16  # bounds pinned shards; keyed on (path, mtime, size) so a
                     # rewritten checkpoint is never served stale


def _bin_entries(fname: str) -> Dict[str, Any]:
    """Lazily torch.load a ``.bin`` shard (mmap'd, cpu) -> {key: torch tensor}.

    Cached because torch-pickle has no header-only read: the one load serves
    both shape inspection and tensor reads (mmap keeps RSS bounded where the
    format allows).  LRU-capped, invalidated by file mtime/size.
    """
    stat = os.stat(fname)
    key = (fname, stat.st_mtime_ns, stat.st_size)
    cached = _BIN_CACHE.get(key)
    if cached is None:
        import torch

        try:
            cached = torch.load(fname, map_location="cpu", mmap=True, weights_only=True)
        except (TypeError, RuntimeError):  # older formats: no mmap / zipfile
            cached = torch.load(fname, map_location="cpu", weights_only=True)
        # drop superseded versions of this file, then cap total entries
        for k in [k for k in _BIN_CACHE if k[0] == fname]:
            del _BIN_CACHE[k]
        while len(_BIN_CACHE) >= _BIN_CACHE_MAX:
            del _BIN_CACHE[next(iter(_BIN_CACHE))]
        _BIN_CACHE[key] = cached
    return cached


def _torch_to_numpy(t) -> np.ndarray:
    import torch

    if t.dtype == torch.bfloat16:
        return t.view(torch.uint16).numpy().view(jnp.bfloat16)
    return t.numpy()


def _torch_np_dtype(td):
    import torch

    if td == torch.bfloat16:
        return jnp.bfloat16
    return np.dtype(str(td).replace("torch.", ""))


# ----------------------------------------------------------------- dispatch
def _validate_device_map(device_map: Dict[str, DeviceId], modules, what: str = "model") -> None:
    """An explicit device_map must cover exactly the top-level modules —
    silently defaulting uncovered layers to device 0 would defeat the offload
    the caller asked for (or OOM)."""
    known = set(modules)
    unknown = [k for k in device_map if k not in known]
    missing = [m for m in known if m not in device_map]
    if unknown:
        raise ValueError(
            f"device_map keys {unknown} are not modules of this {what} "
            f"(modules: {sorted(known)}). To pass per-device byte budgets use "
            "max_memory=... with device_map='auto'."
        )
    if missing:
        raise ValueError(
            f"device_map does not cover modules {sorted(missing)}; every top-level "
            "module needs a placement (device index, 'cpu', or 'disk')."
        )


def dispatch_params(
    params,
    device_map: Dict[str, DeviceId],
    offload_folder: Optional[str] = None,
) -> Tuple[Any, Optional[OffloadedWeightsLoader]]:
    """Place each top-level module's weights per ``device_map`` (reference
    ``dispatch_model``, ``big_modeling.py:305-496``).

    Device-mapped modules go to HBM (``jax.device_put``); ``"cpu"`` modules
    stay as host numpy arrays; ``"disk"`` modules are written to
    ``offload_folder`` memory-maps and dropped from RAM.  Returns the placed
    tree (disk leaves become ``None``) plus the weights loader covering
    cpu+disk entries for streaming.
    """
    _validate_device_map(device_map, top_level_modules(params))
    devices = jax.devices()
    placed: Dict[str, Any] = {}
    host_entries: Dict[str, Any] = {}
    disk_flat: Dict[str, Any] = {}
    for mod in top_level_modules(params):
        target = device_map[mod]
        sub = params[mod]
        if target == "disk":
            if offload_folder is None:
                raise ValueError("device_map places modules on 'disk' but no offload_folder was given.")
            disk_flat.update(flatten_tree(sub, mod))
            placed[mod] = None
        elif target == "cpu":
            sub = jax.tree_util.tree_map(np.asarray, sub)
            host_entries.update(flatten_tree(sub, mod))
            placed[mod] = sub
        else:
            placed[mod] = jax.device_put(sub, devices[int(target)])
    loader = None
    if disk_flat:
        offload_state_dict(offload_folder, {k: np.asarray(v) for k, v in disk_flat.items()})
        loader = OffloadedWeightsLoader(state_dict=host_entries, save_folder=offload_folder)
    elif host_entries:
        loader = OffloadedWeightsLoader(state_dict=host_entries)
    return placed, loader


def shard_params_for_inference(params, mesh=None, axis: Optional[str] = None):
    """Pooled-HBM placement: shard every weight's largest divisible dim over the
    mesh and let GSPMD handle the rest — the TPU answer to ``device_map`` when
    the model fits in aggregate HBM (SURVEY §7.10)."""
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        from .state import PartialState

        mesh = PartialState().mesh
    axes = list(mesh.shape.keys()) if axis is None else [axis]
    sizes = {a: mesh.shape[a] for a in axes}
    total = int(np.prod(list(sizes.values())))

    def place(x):
        x = jnp.asarray(x)
        best_dim, best_axes = None, ()
        for d, dim_size in enumerate(x.shape):
            if dim_size % total == 0:
                best_dim, best_axes = d, tuple(axes)
                break
        if best_dim is None:
            for d, dim_size in enumerate(x.shape):
                for a in axes:
                    if dim_size % sizes[a] == 0:
                        best_dim, best_axes = d, (a,)
                        break
                if best_dim is not None:
                    break
        spec = [None] * jnp.ndim(x)
        if best_dim is not None:
            spec[best_dim] = best_axes if len(best_axes) > 1 else best_axes[0]
        return jax.device_put(x, NamedSharding(mesh, PartitionSpec(*spec)))

    return jax.tree_util.tree_map(place, params)


def cpu_offload(params, exec_device_map: Optional[Dict[str, DeviceId]] = None):
    """Everything on host, streamed per-forward (reference ``cpu_offload``,
    ``big_modeling.py:169-211``)."""
    device_map = {mod: "cpu" for mod in top_level_modules(params)}
    if exec_device_map:
        device_map.update(exec_device_map)
    return dispatch_params(params, device_map)


def disk_offload(params, offload_folder: str):
    """Everything on disk memory-maps (reference ``disk_offload``,
    ``big_modeling.py:214-260``)."""
    device_map = {mod: "disk" for mod in top_level_modules(params)}
    return dispatch_params(params, device_map, offload_folder=offload_folder)


# ------------------------------------------------- checkpoint → dispatched
def load_checkpoint_and_dispatch(
    model,
    checkpoint: str,
    device_map: Union[str, Dict[str, DeviceId]] = "auto",
    max_memory: Optional[Dict[DeviceId, int]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    mesh=None,
    quantization=None,
):
    """Load a safetensors checkpoint with placement decided *before* any tensor
    is read (reference ``load_checkpoint_and_dispatch``, ``big_modeling.py:499-628``).

    ``device_map``:
      * ``"sharded"`` — shard into pooled HBM via NamedSharding (TPU-preferred);
      * ``"auto"``/``"balanced"`` — greedy packing over device budgets, spilling
        to cpu/disk;
      * explicit dict — your placement.

    ``quantization`` (a :class:`~accelerate_tpu.ops.quantization.QuantizationConfig`,
    e.g. ``Int8Config()``) quantizes eligible kernels as they are read — the
    ``load_and_quantize_model`` analog (reference ``utils/bnb.py:44-467``):
    placement budgets see the quantized (4x/8x smaller) sizes, and the returned
    tree matches a model built with ``TransformerConfig(quantization=bits)``.

    Returns ``(params, device_map, weights_loader)``; disk-mapped tensors are
    NOT copied — the loader reads them zero-copy from the checkpoint itself.

    A raw HF model directory (config.json with a mapped ``model_type``, HF key
    naming) is auto-converted into ``<dir>/_atpu_native`` first — see
    :mod:`accelerate_tpu.models.hf_compat` — so a downloaded ``gpt2``/Llama
    snapshot loads directly.
    """
    from .models.hf_compat import convert_hf_checkpoint, is_hf_checkpoint

    if os.path.isdir(checkpoint) and is_hf_checkpoint(checkpoint):
        checkpoint = convert_hf_checkpoint(checkpoint, dtype=dtype)
    files = _checkpoint_files(checkpoint)
    flat_shapes = checkpoint_shapes(checkpoint, files=files)
    quantize_flat = None
    if quantization is not None:
        from .ops.quantization import quantize_flat_tree as quantize_flat

        flat_shapes = quantize_flat(flat_shapes, quantization, sep=SEP)
    abstract = unflatten_tree(flat_shapes)

    def read(keys, host: bool = False):
        flat = _read_tensors(files, keys, dtype)
        if quantize_flat is not None:
            if host:
                # cpu-targeted modules must quantize on the host: the jnp ops in
                # quantize() otherwise commit qweight/scales to the default
                # accelerator device, putting the whole "bigger than HBM" model
                # in HBM during load — and jax.Array leaves would also disable
                # the StreamingExecutor's packed host-transfer path.
                import contextlib

                try:
                    cpu = jax.local_devices(backend="cpu")[0]
                    ctx = jax.default_device(cpu)
                except RuntimeError:
                    ctx = contextlib.nullcontext()
                with ctx:
                    flat = quantize_flat(flat, quantization, sep=SEP)
                flat = {k: np.asarray(v) for k, v in flat.items()}
            else:
                flat = quantize_flat(flat, quantization, sep=SEP)
        return flat

    if device_map == "sharded":
        flat = read(list(files.keys()))
        params = shard_params_for_inference(unflatten_tree(flat), mesh=mesh)
        return params, "sharded", None

    if isinstance(device_map, str):
        if device_map not in ("auto", "balanced", "balanced_low_0"):
            raise ValueError(f"Unknown device_map {device_map!r}")
        budgets = get_balanced_memory(
            abstract, max_memory, dtype=dtype, low_zero=device_map == "balanced_low_0"
        )
        device_map = infer_auto_device_map(abstract, budgets, dtype=dtype)

    _validate_device_map(device_map, top_level_modules(abstract), what="checkpoint")
    devices = jax.devices()
    placed: Dict[str, Any] = {}
    host_entries: Dict[str, Any] = {}
    safetensors_refs: Dict[str, str] = {}
    for mod in top_level_modules(abstract):
        target = device_map[mod]
        keys = [k for k in files if k == mod or k.startswith(mod + SEP)]
        if target == "disk":
            if quantization is not None:
                raise ValueError(
                    "quantization with disk-mapped modules is not supported: disk "
                    "entries are zero-copy references into the fp checkpoint. Raise "
                    "max_memory (quantized weights are 4-8x smaller) or use 'cpu'."
                )
            # zero-copy: leave bytes in the checkpoint, remember the file
            for k in keys:
                safetensors_refs[k] = files[k]
            placed[mod] = None
        elif target == "cpu":
            flat = read(keys, host=True)
            host_entries.update(flat)
            placed[mod] = _strip_prefix(flat, mod)
        else:
            flat = read(keys)
            placed[mod] = jax.device_put(_strip_prefix(flat, mod), devices[int(target)])
    loader = None
    if host_entries or safetensors_refs:
        loader = OffloadedWeightsLoader(state_dict=host_entries, safetensors_files=safetensors_refs)
    return placed, device_map, loader


def _strip_prefix(flat: Dict[str, Any], mod: str):
    """Subtree under ``mod`` — a root-level leaf (key == mod) IS the value."""
    if set(flat) == {mod}:
        return flat[mod]
    return unflatten_tree({k[len(mod) + 1:]: v for k, v in flat.items()})


def _read_tensors(files: Dict[str, str], keys, dtype=None) -> Dict[str, np.ndarray]:
    from safetensors import safe_open

    by_file: Dict[str, list] = {}
    for k in keys:
        by_file.setdefault(files[k], []).append(k)
    out: Dict[str, np.ndarray] = {}
    for fname, ks in by_file.items():
        if fname.endswith(".bin"):
            entries = _bin_entries(fname)
            for k in ks:
                t = _torch_to_numpy(entries[k])
                out[k] = t.astype(jnp.dtype(dtype)) if dtype is not None else t
            continue
        with safe_open(fname, framework="np") as f:
            for k in ks:
                t = f.get_tensor(k)
                if dtype is not None:
                    t = t.astype(jnp.dtype(dtype))
                out[k] = t
    return out


# ------------------------------------------------------- streaming executor
class StageHook:
    """Public extension protocol for :class:`StreamingExecutor` — the
    TPU-native analog of the reference's ``ModelHook`` / ``add_hook_to_module``
    (``/root/reference/src/accelerate/hooks.py:36-217``).

    The reference patches ``nn.Module.forward`` per submodule; here the
    natural interception point is the **stage boundary** of the streaming
    plan (everything inside a stage is one fused XLA executable).  Subclass
    and override any of:

    * :meth:`fetch_weights` — replace where a stage's weights come from (a
      bespoke offload tier, a pinned-in-HBM cache, decryption, ...).  Return
      ``None`` to fall through to the executor's params/loader resolution.
    * :meth:`pre_stage` / :meth:`post_stage` — observe or transform the
      carry at stage entry/exit (timing, logging, activation edits).  Return
      ``None`` to keep the carry unchanged; these run at the host-level
      stage boundary, outside jit, so any python is allowed.

    Attach with ``StreamingExecutor(..., hooks=[...])`` or
    :meth:`StreamingExecutor.add_hook`.  Hooks run in attach order;
    ``fetch_weights`` uses the first non-``None`` result.

    See ``examples/by_feature/streaming_hooks.py`` for a worked custom
    offload policy + stage profiler.
    """

    def fetch_weights(self, executor: "StreamingExecutor", stage_index: int, source):
        """Return the stage's host/device param tree, or ``None`` for default."""
        return None

    def pre_stage(self, executor: "StreamingExecutor", stage_index: int, carry: tuple):
        """Return a replacement carry tuple, or ``None`` to keep ``carry``."""
        return None

    def post_stage(self, executor: "StreamingExecutor", stage_index: int, carry: tuple):
        """Return a replacement carry tuple, or ``None`` to keep ``carry``."""
        return None


class StreamingExecutor:
    """Generic layer-plan streaming forward — the model-agnostic
    ``AlignDevicesHook`` engine (reference ``hooks.py:219-396``) redesigned TPU-first.

    The reference hooks *any* ``nn.Module`` tree by patching each submodule's
    forward to fault its weights in from a weights map.  Here the same
    capability is a **plan**: an ordered list of ``(params_source, fn)`` stages,
    where ``fn(stage_params, *carry) -> carry`` is any jittable function and
    ``params_source`` is a module name resolved against ``params`` /
    ``weights_loader`` (or a callable returning the stage's host params).  The
    executor then runs the classic streaming schedule:

    * ONE jitted executable per distinct ``fn`` (all decoder layers share
      shapes, so N layers compile once);
    * double buffering: stage ``i+1``'s ``jax.device_put`` (async DMA) is
      issued before stage ``i``'s compute, overlapping transfer with the MXU;
    * stages already resident on the exec device skip the transfer.

    Works for any stacked-layer architecture — build a plan with
    :func:`make_layer_plan` or hand-roll one; :class:`StreamingTransformer`
    is the flagship-model adapter.
    """

    def __init__(
        self,
        plan,
        params=None,
        weights_loader=None,
        exec_device=None,
        pack_transfers: bool = True,
        hooks=None,
    ):
        self.plan = list(plan)
        if not self.plan:
            raise ValueError("StreamingExecutor needs a non-empty plan")
        self.params = params
        self.loader = weights_loader
        self.hooks = list(hooks) if hooks else []
        self.device = exec_device if exec_device is not None else jax.devices()[0]
        # Pack each host-resident stage into ONE contiguous buffer per dtype
        # before transfer: a decoder layer is ~10 leaves, and 10 small
        # device_puts pay 10x the DMA-issue/tunnel latency of one big one
        # (measured 12x effective-bandwidth loss unpacked).  The stage fn then
        # slices the buffer back apart on-device (HBM-to-HBM, fused by XLA).
        self.pack_transfers = pack_transfers
        self._jit_cache: Dict[Any, Callable] = {}
        self._packed_cache: Dict[int, Any] = {}
        # (dtype, leaf-ids) -> (pinned leaf refs, packed host buffer); deduped
        # across stages so shared modules (tied embeddings) snapshot once
        self._buffer_registry: Dict[Any, Any] = {}

    # -- hooks -------------------------------------------------------------
    def add_hook(self, hook: StageHook) -> None:
        """Append a :class:`StageHook`.  Weights-affecting hooks compose with
        the packed-transfer cache via leaf identity: returning NEW arrays is
        picked up automatically; mutating host arrays in place still requires
        :meth:`invalidate_cache` (same contract as ``params``)."""
        self.hooks.append(hook)

    def remove_hook(self, hook: StageHook) -> None:
        self.hooks.remove(hook)

    def _hook_carry(self, method: str, i: int, carry: tuple) -> tuple:
        for h in self.hooks:
            out = getattr(h, method)(self, i, carry)
            if out is not None:
                carry = out if isinstance(out, tuple) else (out,)
        return carry

    # -- module weight access ---------------------------------------------
    def _stage_params(self, source, stage_index: Optional[int] = None):
        if stage_index is not None:
            for h in self.hooks:
                tree = h.fetch_weights(self, stage_index, source)
                if tree is not None:
                    return tree
        if callable(source):
            return source()
        return self._module_params(source)

    def _module_params(self, name: str):
        sub = self.params.get(name) if isinstance(self.params, dict) else None
        if sub is not None:
            return sub
        if self.loader is None:
            raise KeyError(f"No weights for module {name!r}")
        flat = {
            k[len(name) + 1:]: self.loader[k]
            for k in self.loader
            if k.startswith(name + SEP)
        }
        if not flat:
            raise KeyError(f"No weights for module {name!r}")
        return unflatten_tree(flat)

    def _to_device(self, tree):
        def put(x):
            if isinstance(x, jax.Array) and x.committed and x.devices() == {self.device}:
                return x
            return jax.device_put(x, self.device)

        return jax.tree_util.tree_map(put, tree)

    def _jitted(self, fn):
        cached = self._jit_cache.get(fn)
        if cached is None:
            cached = self._jit_cache[fn] = jax.jit(fn)
        return cached

    # -- packed transfer ----------------------------------------------------
    def invalidate_cache(self) -> None:
        """Drop cached packed host buffers.  Call after mutating host weights
        in place — packed stages are *snapshots* taken at first transfer.
        (Rebinding ``params`` to NEW arrays is detected automatically: cache
        validity is leaf *identity*, and cached entries pin their source
        leaves so ids cannot be recycled.)"""
        self._packed_cache.clear()
        self._buffer_registry.clear()

    def _packed_buffer(self, dtype, group_leaves):
        """Snapshot one dtype-group into a contiguous host buffer, deduped
        across stages: modules shared between stages (e.g. a tied embedding
        table used by both the embed and head stages) pack ONCE.

        The registry entry pins the source leaf objects, which both keeps the
        id-based key sound (no id recycling while cached) and makes a params
        rebind an automatic cache miss.
        """
        gkey = (np.dtype(dtype), tuple(id(x) for x in group_leaves))
        entry = self._buffer_registry.get(gkey)
        if entry is not None and all(a is b for a, b in zip(entry[0], group_leaves)):
            return entry[1]
        arrs = [np.asarray(x).reshape(-1) for x in group_leaves]
        # pack_buffers = multithreaded native gather when libatpu_runtime is
        # built, np.concatenate otherwise; either way the result is a snapshot
        # copy, never a live view of caller memory
        from .utils import _native

        buffer = _native.pack_buffers(arrs)
        self._buffer_registry[gkey] = (tuple(group_leaves), buffer)
        return buffer

    def _prepare_stage(self, i: int, transfer_cache: Optional[Dict[int, Any]] = None):
        """Resolve stage ``i``'s params and issue its (async) transfer.

        Returns ``(device_operand, spec_key, treedef)`` where ``spec_key`` is
        None for the unpacked path, else the static unpack layout.

        Packing applies only to stages whose every leaf is true host data
        (numpy etc., as produced by loaders/checkpoint reads) — jax Arrays are
        already device-resident (or one cheap device_put away) and take the
        unpacked path.  Packed buffers are consistent SNAPSHOTS keyed on leaf
        identity (sources pinned, so identity is sound); in-place host
        mutations require :meth:`invalidate_cache`.  ``transfer_cache`` dedupes
        H2D transfers of the same buffer within one forward (tied modules).
        """
        tree = self._stage_params(self.plan[i][0], stage_index=i)
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        host = self.pack_transfers and leaves and not any(
            isinstance(x, jax.Array) for x in leaves
        )
        if not host:
            return self._to_device(tree), None, None

        cached = self._packed_cache.get(i)
        if cached is None or len(cached[0]) != len(leaves) or not all(
            a is b for a, b in zip(cached[0], leaves)
        ):
            # group leaves by dtype; one deduped contiguous buffer per group
            groups: Dict[Any, list] = {}
            placements = []
            for leaf in leaves:
                arr = np.asarray(leaf)
                g = groups.setdefault(arr.dtype, [])
                offset = sum(a.size for _, a in g)
                g.append((leaf, arr))
                placements.append((arr.dtype, offset, arr.size, arr.shape))
            dtypes = list(groups)
            buffers = [
                self._packed_buffer(d, [leaf for leaf, _ in groups[d]]) for d in dtypes
            ]
            spec = tuple(
                (dtypes.index(d), off, size, shape) for (d, off, size, shape) in placements
            )
            replaced = cached is not None
            self._packed_cache[i] = cached = (tuple(leaves), buffers, spec)
            if replaced:
                # a rebind superseded the old snapshot: drop registry entries no
                # stage references anymore, or every swap leaks a model copy
                live = {
                    id(b) for (_, bufs, _) in self._packed_cache.values() for b in bufs
                }
                self._buffer_registry = {
                    k: v for k, v in self._buffer_registry.items() if id(v[1]) in live
                }
        _, buffers, spec = cached
        dev_buffers = []
        for b in buffers:
            dev = transfer_cache.get(id(b)) if transfer_cache is not None else None
            if dev is None:
                dev = jax.device_put(b, self.device)
                if transfer_cache is not None:
                    transfer_cache[id(b)] = dev
            dev_buffers.append(dev)
        return dev_buffers, spec, treedef

    def _run_stage(self, fn, operand, spec, treedef, carry):
        if spec is None:
            return self._jitted(fn)(operand, *carry)
        cache_key = (fn, spec, treedef)
        wrapped = self._jit_cache.get(cache_key)
        if wrapped is None:
            def unpacked(buffers, *args):
                leaves = [
                    jax.lax.slice(buffers[g], (off,), (off + size,)).reshape(shape)
                    for (g, off, size, shape) in spec
                ]
                return fn(jax.tree_util.tree_unflatten(treedef, leaves), *args)

            wrapped = self._jit_cache[cache_key] = jax.jit(unpacked)
        return wrapped(operand, *carry)

    # -- forward -----------------------------------------------------------
    def __call__(self, *inputs):
        carry: Tuple[Any, ...] = inputs
        transfer_cache: Dict[int, Any] = {}  # per-call H2D dedupe (tied modules)
        current = self._prepare_stage(0, transfer_cache)
        for i, (source, fn) in enumerate(self.plan):
            nxt = None
            if i + 1 < len(self.plan):
                # async transfer of stage i+1 issued before stage i computes
                nxt = self._prepare_stage(i + 1, transfer_cache)
            operand, spec, treedef = current
            carry = self._hook_carry("pre_stage", i, carry)
            out = self._run_stage(fn, operand, spec, treedef, carry)
            carry = out if isinstance(out, tuple) else (out,)
            carry = self._hook_carry("post_stage", i, carry)
            current = nxt
        return carry[0] if len(carry) == 1 else carry


def make_layer_plan(embed, layers, head):
    """Convenience plan builder for the embed → N x layer → head shape that
    covers every decoder-only/encoder stack.

    ``embed``/``head`` are ``(params_source, fn)``; ``layers`` is an iterable of
    them (typically the SAME fn object for every layer so they share one
    compiled executable).
    """
    return [embed, *layers, head]


class StreamingTransformer(StreamingExecutor):
    """Flagship-Transformer adapter over :class:`StreamingExecutor`.

    Handles both parameter layouts (``layers_{i}`` modules, or the single
    stacked ``layers`` module of ``scan_layers=True`` — streamed by slicing),
    tied embeddings, and quantized weights (the stage fns run whatever the
    config dictates, including :class:`~accelerate_tpu.ops.quantization.QuantizedDense`).
    """

    def __init__(
        self,
        config,
        params,
        device_map: Optional[Dict[str, DeviceId]] = None,
        weights_loader=None,
        exec_device=None,
        layers_per_stage: int = 1,
        hooks=None,
    ):
        from .models.transformer import DecoderLayer, make_norm

        cfg = config
        self.config = config
        self.device_map = device_map or {}
        # scan_layers=True models store ONE stacked "layers" module (axis 0 =
        # depth, models/transformer.py) instead of layers_{i}; stream by
        # slicing the stack per layer.
        self._scan_layout = bool(getattr(cfg, "scan_layers", False)) or (
            isinstance(params, dict) and "layers" in params and "layers_0" not in params
        )
        self._stack_cache = None  # cached scanned-layer stack (invalidate_cache resets)
        self._stack_src = None    # identity of the params["layers"] subtree the cache came from
        self._slice_cache: Dict[int, Any] = {}  # per-layer slice trees of the stack
        # layers_per_stage > 1 amortizes per-dispatch/per-transfer fixed costs
        # (dominant on high-latency transports) over bigger chunks; choose so
        # ~2 chunks fit in free HBM alongside activations.
        k = max(1, int(layers_per_stage))

        def layer_fn(chunk_params, x, positions):
            for lp in chunk_params:  # static K iterations, one executable per chunk SIZE
                x = DecoderLayer(cfg).apply({"params": lp}, x, positions)
            return x, positions

        def cached_layer_fn(chunk_params, x, positions, ks, vs, index):
            # decode-mode stage: each layer reads/writes its own (k, v) cache
            # at the shared position index; caches stay in HBM across tokens —
            # only the weights stream.
            new_ks, new_vs = [], []
            for lp, k_c, v_c in zip(chunk_params, ks, vs):
                x, (nk, nv) = DecoderLayer(cfg).apply(
                    {"params": lp}, x, positions, cache=(k_c, v_c, index)
                )
                new_ks.append(nk)
                new_vs.append(nv)
            return x, tuple(new_ks), tuple(new_vs)

        has_embed_norm = getattr(cfg, "embed_norm", False)
        has_learned_pos = getattr(cfg, "positional", "rope") == "learned"

        def embed_fn(stage_params, ids, positions):
            import flax.linen as nn

            from .models.transformer import scale_embed

            embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
            parts = list(stage_params) if isinstance(stage_params, tuple) else [stage_params]
            x = scale_embed(cfg, embed.apply({"params": parts.pop(0)}, ids))
            if has_embed_norm:  # BLOOM: LayerNorm right after the embedding
                x = make_norm(cfg, None).apply({"params": parts.pop(0)}, x)
            if has_learned_pos:
                offset = getattr(cfg, "pos_offset", 0)
                pos = nn.Embed(
                    cfg.max_seq_len + offset, cfg.hidden_size,
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                )
                x = x + pos.apply({"params": parts.pop(0)}, positions + offset)
            return x, positions

        def head_fn(stage_params, x, positions):
            import flax.linen as nn

            norm_params, head_params = stage_params
            # same norm module the monolithic model uses (rmsnorm or layernorm)
            x = make_norm(cfg, None).apply({"params": norm_params}, x)
            if cfg.tie_word_embeddings:
                # exact monolithic semantics: embed.attend promotes to cfg.dtype
                embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
                logits = embed.apply({"params": head_params}, x.astype(cfg.param_dtype), method="attend")
                return logits.astype(jnp.float32)
            logits = x @ head_params["kernel"].astype(cfg.dtype)
            if getattr(cfg, "lm_head_bias", False):
                logits = logits + head_params["bias"].astype(cfg.dtype)
            return logits.astype(jnp.float32)

        head_source = "embed_tokens" if cfg.tie_word_embeddings else "lm_head"
        chunks = [
            tuple(range(start, min(start + k, cfg.num_layers)))
            for start in range(0, cfg.num_layers, k)
        ]
        self._chunks = chunks
        self._embed_fn = embed_fn
        self._head_fn = head_fn
        self._cached_layer_fn = cached_layer_fn
        embed_modules = ["embed_tokens"]
        if has_embed_norm:
            embed_modules.append("embed_norm")
        if has_learned_pos:
            embed_modules.append("pos_embed")
        embed_source = (
            "embed_tokens" if embed_modules == ["embed_tokens"]
            else (lambda: tuple(self._module_params(m) for m in embed_modules))
        )
        plan = make_layer_plan(
            embed=(embed_source, embed_fn),
            layers=[
                # bind per-chunk via default arg (a bare lambda would late-bind
                # every stage to the last chunk)
                (lambda c=chunk: tuple(self._layer_params(i) for i in c), layer_fn)
                for chunk in chunks
            ],
            head=(
                lambda: (self._module_params("final_norm"), self._module_params(head_source)),
                head_fn,
            ),
        )
        super().__init__(
            plan, params=params, weights_loader=weights_loader, exec_device=exec_device,
            hooks=hooks,
        )

    def invalidate_cache(self) -> None:
        self._stack_cache = None
        self._stack_src = None
        self._slice_cache = {}
        super().invalidate_cache()

    def _layer_params(self, i: int):
        if not self._scan_layout:
            return self._module_params(f"layers_{i}")
        # fetch the stacked module once (a loader read is a full eager
        # deserialize — O(layers) re-reads would defeat the streaming), and
        # keep the per-layer slice trees across calls: stable slice identity
        # is what lets the executor's packed cache hit instead of re-packing
        # the whole model every forward.  Swapping self.params requires
        # invalidate_cache(), same as every packed-cache path.
        stack_src = self.params.get("layers") if isinstance(self.params, dict) else None
        if self._stack_cache is None or self._stack_src is not stack_src:
            self._stack_cache = self._module_params("layers")["layer"]
            self._stack_src = stack_src
            self._slice_cache = {}
        cached = self._slice_cache.get(i)
        if cached is None:
            cached = self._slice_cache[i] = jax.tree_util.tree_map(
                lambda x: x[i], self._stack_cache
            )
        return cached

    def __call__(self, input_ids, positions=None):
        input_ids = jnp.asarray(input_ids)
        if self._scan_layout and not (isinstance(self.params, dict) and "layers" in self.params):
            # loader-backed stacks have no identity to validate against — the
            # loader may serve different bytes each call, so refetch per forward
            self._stack_cache = None
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1])[None, :], input_ids.shape)
        return super().__call__(input_ids, positions)

    # -- autoregressive decode (weights stream per token, cache stays in HBM) --
    def init_cache(self, batch_size: int, max_len: int, dtype=None,
                   per_lane_index: bool = False):
        """Per-chunk KV caches on the exec device: ``{"chunks": [(ks, vs), ...],
        "index": scalar}`` where ks/vs are per-layer ``[B, max_len, Hkv, D]``.

        Unlike the monolithic :class:`~accelerate_tpu.models.transformer.KVCache`
        (stacked over depth), chunk-grained caches keep ONE decode executable
        per chunk size and let each stage carry only its own slice.

        ``per_lane_index=True`` makes ``index`` a ``[B]`` vector — each lane
        decodes at its own position, the same masked-step contract the
        continuous-batching slot pool (:mod:`accelerate_tpu.serving`) drives,
        so a host scheduler can run in-flight admission over streaming weights.
        """
        cfg = self.config
        dtype = dtype if dtype is not None else getattr(cfg, "dtype", jnp.bfloat16)
        hd = cfg.resolved_head_dim
        shape = (batch_size, max_len, cfg.num_kv_heads, hd)
        chunks = []
        for c in self._chunks:
            ks = tuple(jax.device_put(jnp.zeros(shape, dtype), self.device) for _ in c)
            vs = tuple(jax.device_put(jnp.zeros(shape, dtype), self.device) for _ in c)
            chunks.append((ks, vs))
        index_shape = (batch_size,) if per_lane_index else ()
        return {
            "chunks": chunks,
            "index": jax.device_put(jnp.zeros(index_shape, jnp.int32), self.device),
        }

    def forward_with_cache(self, input_ids, cache):
        """Incremental forward (prefill S>1 or decode S=1) with the streaming
        schedule: stage ``i+1``'s weights transfer while stage ``i`` computes.
        Returns ``(logits [B,S,V], new_cache)``."""
        input_ids = jnp.asarray(input_ids)
        if self._scan_layout and not (isinstance(self.params, dict) and "layers" in self.params):
            self._stack_cache = None
        index = cache["index"]
        s = input_ids.shape[1]
        # scalar index: lockstep decode; [B] per-lane index: each lane at its
        # own position (the serving masked-step contract — Attention writes
        # per-lane and cached_attention masks per-lane)
        offset = index[:, None] if jnp.ndim(index) else index
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], input_ids.shape) + offset
        transfer_cache: Dict[int, Any] = {}
        n = len(self.plan)
        current = self._prepare_stage(0, transfer_cache)
        x = pos = logits = None
        new_chunks = []
        for i in range(n):
            nxt = self._prepare_stage(i + 1, transfer_cache) if i + 1 < n else None
            operand, spec, treedef = current
            if i == 0:
                carry = self._hook_carry("pre_stage", i, (input_ids, positions))
                x, pos = self._hook_carry(
                    "post_stage", i, self._run_stage(self._embed_fn, operand, spec, treedef, carry)
                )
            elif i == n - 1:
                carry = self._hook_carry("pre_stage", i, (x, pos))
                logits = self._run_stage(self._head_fn, operand, spec, treedef, carry)
                (logits,) = self._hook_carry("post_stage", i, (logits,))
            else:
                ks, vs = cache["chunks"][i - 1]
                carry = self._hook_carry("pre_stage", i, (x, pos, ks, vs, index))
                x, nks, nvs = self._hook_carry(
                    "post_stage", i,
                    self._run_stage(self._cached_layer_fn, operand, spec, treedef, carry),
                )
                new_chunks.append((nks, nvs))
            current = nxt
        return logits, {"chunks": new_chunks, "index": index + s}

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 128,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        rng=None,
        cache=None,
    ) -> np.ndarray:
        """Host-driven token loop over :meth:`forward_with_cache` — the
        reference's published benchmark workload (generation under CPU/disk
        offload, ``benchmarks/big_model_inference.py:108-139``): every token
        streams the weights once, double-buffered against compute.

        Returns ``[B, S + max_new_tokens]`` numpy token ids (EOS lanes padded).
        """
        from .models.generation import make_sampler

        input_ids = jnp.asarray(input_ids)
        b, s = input_ids.shape
        if cache is None:
            cache = self.init_cache(b, s + max_new_tokens)
        else:
            idx = jax.device_get(cache["index"])
            used = int(idx.max()) if getattr(idx, "ndim", 0) else int(idx)
            max_len = cache["chunks"][0][0][0].shape[1]
            if used + s + max_new_tokens > max_len:
                raise ValueError(
                    f"cache max_len {max_len} < {used} already written + prompt {s} + "
                    f"max_new_tokens {max_new_tokens}; init_cache with max_len >= "
                    f"{used + s + max_new_tokens} (dynamic_update_slice would clamp "
                    "out-of-range writes and silently corrupt the cache)"
                )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        sample = make_sampler(
            do_sample=do_sample, temperature=temperature, top_k=top_k, top_p=top_p
        )
        logits, cache = self.forward_with_cache(input_ids, cache)
        rng, sub = jax.random.split(rng)
        tok = np.asarray(sample(logits[:, -1], sub))
        done = np.zeros(b, dtype=bool)
        if eos_token_id is not None:
            done |= tok == eos_token_id
        toks = [tok]
        for _ in range(max_new_tokens - 1):
            if done.all():
                toks.append(np.full((b,), pad_token_id, dtype=tok.dtype))
                continue
            logits, cache = self.forward_with_cache(jnp.asarray(toks[-1])[:, None], cache)
            rng, sub = jax.random.split(rng)
            nxt = np.asarray(sample(logits[:, -1], sub))
            nxt = np.where(done, pad_token_id, nxt)
            if eos_token_id is not None:
                done |= nxt == eos_token_id
            toks.append(nxt)
        return np.concatenate([np.asarray(input_ids), np.stack(toks, axis=1)], axis=1)



"""accelerate_tpu — a TPU-native training/inference orchestration framework.

A ground-up JAX/XLA re-design with the capabilities of HuggingFace Accelerate
(the reference at ``/root/reference``, v0.32.0.dev0): one ``Accelerator`` façade
over device meshes, sharded data loading, compiled train steps, mixed precision,
gradient accumulation, FSDP/ZeRO-as-sharding, checkpointing, trackers, a launch
CLI and big-model inference — built TPU-first (SPMD meshes, pjit, pallas) rather
than as a port of the torch wrapper design.
"""

__version__ = "0.1.0"

from .accelerator import Accelerator
from .data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoaderDispatcher,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SimpleDataLoader,
    default_collate,
    prepare_data_loader,
    skip_first_batches,
)
from .big_modeling import (
    StageHook,
    StreamingExecutor,
    StreamingTransformer,
    cpu_offload,
    disk_offload,
    dispatch_params,
    init_empty_weights,
    init_params_on_host,
    load_checkpoint_and_dispatch,
    make_layer_plan,
    shard_params_for_inference,
)
from .launchers import debug_launcher, notebook_launcher
from .models import (
    BertConfig,
    BertEncoder,
    T5,
    T5Config,
    ViTConfig,
    ViTEncoder,
    Whisper,
    WhisperConfig,
    GenerationConfig,
    KVCache,
    config_from_hf,
    convert_hf_checkpoint,
    generate,
    load_hf_bert,
    load_hf_checkpoint,
    load_hf_t5,
    load_hf_vit,
    load_hf_whisper,
    make_decode_step,
    make_prefill_step,
    sample_tokens,
    to_scan_layout,
)
from .ops import (
    Int4Config,
    Int8Config,
    QuantizationConfig,
    quantize_model_params,
)
from .serving import ServingEngine
from . import telemetry
from .telemetry import (
    MetricsRegistry,
    RecompileWatchdog,
    Tracer,
    get_registry,
    get_tracer,
    span,
    watch_recompiles,
)
from .local_sgd import LocalSGD
from .optimizer import AcceleratedOptimizer
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .train_state import DynamicLossScale, TrainState
from .utils import (
    CollectiveKwargs,
    CompilationConfig,
    DataLoaderConfiguration,
    DistributedType,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradScalerKwargs,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    MeshConfig,
    ModelParallelPlugin,
    PrecisionPolicy,
    ProjectConfiguration,
    ZeroPlugin,
    find_executable_batch_size,
    optax_from_ds_config,
    release_memory,
)
from .utils.random import set_seed

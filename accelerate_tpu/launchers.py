"""In-process launchers: ``notebook_launcher`` and ``debug_launcher``
(reference ``launchers.py:38-296``).

The reference's notebook launcher must ``xmp.spawn`` 8 TPU processes or build a
torchelastic agent; in JAX one process drives every local chip, so on TPU the
"launch" is simply calling the function after (optionally) initializing
multi-host rendezvous.  Multi-process launching remains for the CPU debug rig:
``debug_launcher`` forks N processes that rendezvous over localhost with gloo
CPU collectives — the analog of the reference's ``start_processes`` + gloo
path used throughout its test suite (``launchers.py:263-296``).
"""

from __future__ import annotations

import multiprocessing
import os
import socket
import traceback
from typing import Any, Callable, Tuple


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker(fn: Callable, args: Tuple, rank: int, num_processes: int, port: int, error_queue) -> None:
    # Env must be set before any JAX backend initialization in this fresh
    # interpreter (spawn start method ⇒ jax is imported but uninitialized).
    os.environ["ACCELERATE_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["ACCELERATE_NUM_PROCESSES"] = str(num_processes)
    os.environ["ACCELERATE_PROCESS_ID"] = str(rank)
    os.environ["ACCELERATE_LOCAL_PROCESS_ID"] = str(rank)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("ACCELERATE_USE_CPU", "true")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax: collectives impl picked automatically
        # Rendezvous before user code, like the reference's PrepareForLaunch
        # bootstrap (utils/launch.py:585-627) — fn() then sees the full world
        # whether or not it constructs a PartialState.
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=num_processes,
            process_id=rank,
        )
        fn(*args)
    except Exception:
        error_queue.put(f"rank {rank}:\n{traceback.format_exc()}")
        raise


def debug_launcher(function: Callable, args: Tuple = (), num_processes: int = 2) -> None:
    """Launch ``function`` in ``num_processes`` CPU processes with a real
    cross-process JAX runtime (reference ``debug_launcher``, ``launchers.py:263-296``).

    Each worker gets ``ACCELERATE_COORDINATOR_ADDRESS``/``_PROCESS_ID`` env so a
    plain ``Accelerator()``/``PartialState()`` inside ``function`` performs the
    multi-host rendezvous exactly as it would on a pod.
    """
    port = _free_port()
    ctx = multiprocessing.get_context("spawn")
    error_queue = ctx.SimpleQueue()
    procs = []
    for rank in range(num_processes):
        p = ctx.Process(target=_worker, args=(function, args, rank, num_processes, port, error_queue))
        p.start()
        procs.append(p)
    failed = []
    for rank, p in enumerate(procs):
        p.join()
        if p.exitcode != 0:
            failed.append(rank)
    if failed:
        errors = []
        while not error_queue.empty():
            errors.append(error_queue.get())
        raise RuntimeError(
            f"debug_launcher workers {failed} failed:\n" + "\n".join(errors)
        )


def notebook_launcher(
    function: Callable,
    args: Tuple = (),
    num_processes: int = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
) -> Any:
    """Launch training from a notebook (reference ``launchers.py:38-260``).

    On TPU the JAX runtime is single-process-per-host and already owns every
    local chip, so unlike torch_xla there is nothing to spawn: the function is
    invoked directly after setting the requested precision/topology env.  With
    ``num_processes > 1`` on CPU this degrades to :func:`debug_launcher` (the
    reference's CPU fork path).
    """
    import jax

    if mixed_precision not in ("no", "bf16", "fp16"):
        raise ValueError(f"Unknown mixed_precision mode: {mixed_precision}")
    os.environ["ACCELERATE_MIXED_PRECISION"] = mixed_precision
    if num_nodes > 1:
        # Multi-host notebook: rendezvous with the pod's coordinator.
        os.environ["ACCELERATE_COORDINATOR_ADDRESS"] = f"{master_addr}:{use_port}"
        os.environ["ACCELERATE_NUM_PROCESSES"] = str(num_nodes)
        os.environ["ACCELERATE_PROCESS_ID"] = str(node_rank)
        return function(*args)
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    if platform == "cpu" and num_processes and num_processes > 1:
        return debug_launcher(function, args, num_processes)
    if num_processes and num_processes > 1:
        raise ValueError(
            "On TPU one JAX process drives all local chips — num_processes > 1 is only "
            "meaningful on CPU (debug) or across hosts (num_nodes)."
        )
    return function(*args)

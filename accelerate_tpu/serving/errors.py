"""Typed admission refusals for the serving stack.

Every layer that submits into a :class:`~accelerate_tpu.serving.engine.
ServingEngine` — the :class:`~accelerate_tpu.serving.router.ReplicaRouter`
failover ladder, the HTTP front door (:mod:`accelerate_tpu.serving.api`),
benches — used to match refusals on ``ValueError`` and, implicitly, on the
message text when it needed to tell "queue full, retry" apart from "this
prompt can never fit".  :class:`AdmissionError` makes the distinction a
type + fields:

* ``retriable=True`` — transient backpressure (queue at ``max_queue``):
  retrying the same request later can succeed.  The API layer maps it to
  HTTP 429 with a ``Retry-After`` derived from ``retry_after_s``.
* ``retriable=False`` — a capacity refusal (prompt longer than this
  engine's ``max_prompt_len`` / slot budget): retrying the same request on
  the SAME engine can never succeed, but another replica with different
  geometry might take it — exactly what the router's failover ladder does.
  The API layer maps it to HTTP 400.

``AdmissionError`` subclasses ``ValueError`` so pre-existing callers that
catch the old stringly refusals keep working unchanged.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["AdmissionError", "DeadlineExceeded"]


class AdmissionError(ValueError):
    """An engine refused to admit a request.

    Parameters
    ----------
    message: human-readable refusal reason (the old ``ValueError`` text).
    queue_depth: requests queued or mid-prefill on the refusing engine at
        refusal time — the load signal a front door can surface.
    retry_after_s: hint for when the same submit could succeed (``None``
        when no estimate makes sense, e.g. capacity refusals).
    retriable: ``True`` for transient backpressure (queue full), ``False``
        for capacity refusals that can never succeed on this engine.
    """

    def __init__(
        self,
        message: str,
        *,
        queue_depth: int = 0,
        retry_after_s: Optional[float] = None,
        retriable: bool = True,
    ):
        super().__init__(message)
        self.queue_depth = int(queue_depth)
        self.retry_after_s = retry_after_s
        self.retriable = bool(retriable)

    def __repr__(self) -> str:  # refusals land in logs; make them greppable
        return (
            f"AdmissionError({str(self)!r}, queue_depth={self.queue_depth}, "
            f"retry_after_s={self.retry_after_s}, retriable={self.retriable})"
        )


class DeadlineExceeded(RuntimeError):
    """A running request blew its ``deadline_s`` and was cancelled by the
    engine's deadline sweep.  The API layer maps this to HTTP 504 — the
    request was admitted and partially served, unlike an
    :class:`AdmissionError` shed (429) where nothing ran.  ``elapsed_s`` is
    how long the request had been in flight when the sweep caught it."""

    def __init__(self, message: str, *, deadline_s: float = 0.0,
                 elapsed_s: float = 0.0):
        super().__init__(message)
        self.deadline_s = float(deadline_s)
        self.elapsed_s = float(elapsed_s)

"""Host-side request scheduling for the continuous-batching engine.

The device side (:mod:`.pool`) is a fixed set of compiled executables; the
scheduler is everything dynamic: a FCFS request queue, per-request
:class:`~accelerate_tpu.models.generation.GenerationConfig`, chunked-prefill
progress, and an admission policy bounded by a **prefill-token budget per
engine step** — the Orca/Sarathi knob that keeps decode-step latency jitter
bounded while new prompts stream in.

With a :class:`~accelerate_tpu.serving.prefix_cache.PrefixCache` attached, the
scheduler also resolves prefix reuse: ``submit`` walks the radix tree for the
longest cached chunk-aligned prefix (pinning the matched nodes so eviction
cannot pull them out from under the queued request), ``start_next`` refreshes
the walk — requests admitted earlier may have populated chunks this request
can now reuse — and ``take_chunk`` charges cached chunks at ZERO cost against
the prefill-token budget, so every hit also frees budget for cold prompts in
the same engine step.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..models.generation import GenerationConfig
from ..telemetry import get_flight_recorder
from .errors import AdmissionError
from .pool import plan_chunks


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class Request:
    """One serving request: prompt + per-request generation config + progress.

    ``on_token(request, token)`` streams each generated token as the engine
    observes it (window granularity); ``tokens`` accumulates the final
    generated ids (EOS included when hit, never the post-EOS padding).
    """

    rid: int
    prompt: np.ndarray                      # [S] int32
    config: GenerationConfig
    on_token: Optional[Callable[["Request", int], None]] = None
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    # chunked-prefill progress
    chunks: Tuple[Tuple[int, int], ...] = ()
    next_chunk: int = 0
    # prefix-cache state: the first ``cached_chunks`` entries of ``chunks``
    # are CACHED (replayed from retained KV slabs instead of prefilled);
    # ``cache_nodes`` holds the pinned radix nodes backing them plus any nodes
    # this request itself populates (released on insertion or cancel), and
    # ``cache_chain_broken`` stops population once a chunk could not be
    # retained (a later chunk without its ancestors would be unreachable).
    cache_prefix: bool = True
    # per-request speculative-decoding opt-out: when False the engine never
    # drafts for this request's lane even with ``speculate_k > 0`` (it still
    # rides along in verify windows other lanes trigger — with pad drafts,
    # which verification simply rejects)
    speculate: bool = True
    cached_chunks: int = 0
    cache_nodes: List[Any] = dataclasses.field(default_factory=list)
    cache_chain_broken: bool = False
    submit_step: int = -1
    finish_step: int = -1
    # wall-clock stamps (time.perf_counter) for TTFT / per-token latency
    submit_time: float = 0.0
    last_token_time: float = 0.0
    # replica index a :class:`~accelerate_tpu.serving.router.ReplicaRouter`
    # placed this request on (None when submitted straight to an engine)
    replica: Optional[int] = None
    # stable replica identity: unlike ``replica`` (a position in
    # ``router.engines``, which shifts when an earlier replica detaches),
    # this id survives elastic add/drain — cancel resolves through it first
    replica_id: Optional[int] = None
    # SLO deadline in seconds from submit (None = no deadline).  Admission
    # sheds when the queue-depth estimate says it is unmeetable; the engine's
    # deadline sweep cancels a running lane that blows it and sets
    # ``deadline_exceeded`` so the API layer can answer 504 instead of 500
    deadline_s: Optional[float] = None
    deadline_exceeded: bool = False
    # traffic-class label ("chat", "batch", ...) for per-class TTFT
    # histograms; None stays out of the per-class series entirely
    request_class: Optional[str] = None
    # tenant attribution label (X-Tenant header / API-key prefix at the front
    # door).  Rides the Request through preemption, export_inflight, and
    # failover ``adopt`` exactly like ``trace`` does, so per-tenant counters
    # stay exact across replays; None stays out of every per-tenant family
    tenant: Optional[str] = None
    # per-request latency waterfall (telemetry.reqtrace.RequestTrace; None
    # when tracing is off).  The SAME object rides through preemption,
    # export_inflight, and failover adoption, so the waterfall spans replicas
    # instead of restarting — ``adopt`` appends a ``failover`` phase to it.
    trace: Optional[Any] = dataclasses.field(default=None, repr=False)

    @property
    def done(self) -> bool:
        return self.state is RequestState.DONE

    @property
    def output_ids(self) -> np.ndarray:
        """Prompt + generated tokens (the ``generate`` row, pad tail trimmed)."""
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def prefill_tokens(self) -> np.ndarray:
        """What prefill must process for this request *now*: the prompt, plus
        — after a preemption — every token already generated and streamed.
        Replay re-prefills the whole effective prompt (ideally via prefix-cache
        hits on the chunks this request populated in its first life) and
        generation resumes exactly where it stopped; ``tokens`` is never
        re-emitted.  Identical to ``prompt`` for a never-preempted request."""
        if not self.tokens:
            return self.prompt
        return self.output_ids

    def emit(self, token: int) -> None:
        self.tokens.append(int(token))
        if self.on_token is not None:
            self.on_token(self, int(token))

    def finished(self, token: int) -> bool:
        """Would emitting ``token`` complete this request?"""
        eos = self.config.eos_token_id
        return (eos is not None and int(token) == eos) or (
            len(self.tokens) + 1 >= self.config.max_new_tokens
        )


class Scheduler:
    """FCFS admission with a per-step prefill-token budget.

    By default one request prefills at a time (the legacy scratch cache is
    batch-1); its chunks are charged against ``prefill_token_budget`` each
    engine step, so a long prompt spreads across steps instead of stalling
    every running request for its whole prefill (chunked prefill,
    Sarathi-style).

    ``max_prefills > 1`` (the interleaved paged engine) keeps several
    requests mid-prefill at once: admission is still FCFS, but
    :meth:`take_chunk` picks the chunk to run each step
    shortest-remaining-first among the open prefills, so a short chat prompt
    arriving behind a 100k-token prompt finishes its one chunk next step
    instead of waiting out the giant — iteration-level scheduling on the
    prefill side, with the budget still the single jitter bound.
    """

    def __init__(self, prefill_buckets: Sequence[int], prefill_token_budget: int,
                 prefix_cache=None, recorder=None,
                 max_queue: Optional[int] = None, max_prefills: int = 1):
        self.buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
        if not self.buckets:
            raise ValueError("need at least one prefill bucket")
        self.budget = int(prefill_token_budget)
        if self.budget < self.buckets[0]:
            raise ValueError(
                f"prefill_token_budget {self.budget} cannot fit the smallest "
                f"bucket {self.buckets[0]} — no prompt would ever be admitted"
            )
        # admission backpressure: with ``max_queue`` set, a submit that would
        # push the waiting line past it raises a *retriable* AdmissionError —
        # the signal the HTTP front door maps to 429 and the router's failover
        # ladder uses to try a less-loaded replica.  None = unbounded (the
        # in-process benches/tests drive their own queue depth).
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_prefills = int(max_prefills)
        if self.max_prefills < 1:
            raise ValueError(f"max_prefills must be >= 1, got {max_prefills}")
        self.queue: deque = deque()
        # requests mid-prefill, in admission order; bounded by max_prefills
        self._prefills: List[Request] = []
        # did a forward-pass chunk dispatch since begin_step? (the first one
        # per cycle is exempt from the joint budget — anti-starvation)
        self._chunk_this_step = False
        self.prefix_cache = prefix_cache
        # request-lifecycle events for post-mortems (a no-op ring append when
        # telemetry is disabled); the engine passes the process recorder
        self.recorder = recorder if recorder is not None else get_flight_recorder()

    @property
    def prefills(self) -> Tuple[Request, ...]:
        """Every request currently mid-prefill, admission order."""
        return tuple(self._prefills)

    @property
    def prefilling(self) -> Optional[Request]:
        """The oldest open prefill (the only one under ``max_prefills=1``) —
        kept for the single-prefill callers; multi-prefill code should use
        :attr:`prefills`."""
        return self._prefills[0] if self._prefills else None

    @prefilling.setter
    def prefilling(self, req: Optional[Request]) -> None:
        self._prefills = [] if req is None else [req]

    def take_prefills(self) -> List[Request]:
        """Detach and return every open prefill (replica export: the engine
        hands them to the router for replay on a survivor)."""
        out, self._prefills = self._prefills, []
        return out

    def _match_prefix(self, request: Request) -> None:
        """(Re)walk the radix tree for ``request``'s longest cached prefix and
        pin the matched chain.  Pins taken by an earlier walk are released
        *after* the new chain is acquired — the old nodes are still resident
        during the re-walk, so the fresh match can only be equal or longer."""
        if self.prefix_cache is None or not request.cache_prefix:
            return
        nodes = self.prefix_cache.match(request.prefill_tokens, request.chunks)
        self.prefix_cache.acquire(nodes)
        if request.cache_nodes:
            self.prefix_cache.release(request.cache_nodes)
        request.cache_nodes = list(nodes)
        request.cached_chunks = len(nodes)

    def submit(self, request: Request) -> None:
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            # retry hint: the queue drains one request per freed slot; a rough
            # half-second per queued request is deliberately conservative —
            # callers treat it as "not before", not as a promise
            depth = self.queue_depth
            raise AdmissionError(
                f"admission queue full ({len(self.queue)} >= max_queue "
                f"{self.max_queue})",
                queue_depth=depth,
                retry_after_s=min(30.0, 0.5 * depth),
                retriable=True,
            )
        request.chunks = plan_chunks(len(request.prefill_tokens), self.buckets)
        self._match_prefix(request)
        self.queue.append(request)
        self.recorder.record(
            "serve/submit", rid=request.rid, prompt_len=len(request.prompt),
            chunks=len(request.chunks), cached_chunks=request.cached_chunks,
            queue_depth=len(self.queue),
        )

    def requeue(self, request: Request) -> None:
        """Put a preempted RUNNING request back at the FRONT of the queue for
        replay (it already waited its FCFS turn once).  Its effective prompt
        is ``prefill_tokens`` — original prompt plus everything generated —
        re-planned into chunks and re-matched against the prefix cache, so
        replay aliases/reuses whatever this request populated in its first
        life instead of recomputing it."""
        request.state = RequestState.QUEUED
        request.slot = None
        request.chunks = plan_chunks(len(request.prefill_tokens), self.buckets)
        request.next_chunk = 0
        request.cached_chunks = 0
        request.cache_chain_broken = False
        self._match_prefix(request)
        self.queue.appendleft(request)
        if request.trace is not None:
            request.trace.annotate(
                "requeue", effective_len=len(request.prefill_tokens),
                cached_chunks=request.cached_chunks,
            )
        self.recorder.record(
            "serve/requeue", rid=request.rid,
            effective_len=len(request.prefill_tokens),
            cached_chunks=request.cached_chunks, queue_depth=len(self.queue),
        )

    def drop_cache_pins(self) -> int:
        """Release every *queued* request's prefix-cache pins (the paged
        engine's last-resort page reclaim: pinned nodes block eviction, and a
        queued request can always re-match at admission).  Returns how many
        requests were unpinned."""
        dropped = 0
        if self.prefix_cache is None:
            return 0
        for req in self.queue:
            if req.cache_nodes:
                self.prefix_cache.release(req.cache_nodes)
                req.cache_nodes = []
                req.cached_chunks = 0
                dropped += 1
        return dropped

    def cancel(self, rid: int) -> Optional[Request]:
        """Drop a still-QUEUED request (not yet prefilling) from the queue.

        Returns the cancelled :class:`Request` (state ``CANCELLED``, its
        pinned prefix-cache nodes released) or ``None`` when ``rid`` is not
        queued — already prefilling, running, done, or unknown.  Cancelling
        before admission is the cheap case worth optimizing: the request has
        consumed no prefill budget and holds no slot.
        """
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                del self.queue[i]
                if self.prefix_cache is not None and req.cache_nodes:
                    self.prefix_cache.release(req.cache_nodes)
                    req.cache_nodes = []
                req.state = RequestState.CANCELLED
                self.recorder.record("serve/cancel", rid=rid)
                return req
        return None

    @property
    def has_queued(self) -> bool:
        return bool(self.queue) or bool(self._prefills)

    @property
    def queue_depth(self) -> int:
        """Requests waiting or mid-prefill — the ``serve/queue_depth`` gauge
        and the router's load signal.  Admission runs against the engine's
        HOST lane state, which under the pipelined loop (``async_depth=1``)
        is authoritative even while a window is in flight: a lane retired at
        drain frees its slot immediately, one step after the sync loop would
        have (the documented EOS lag), so queue depth can read one step
        higher than ``async_depth=0`` under churn — never lower."""
        return len(self.queue) + len(self._prefills)

    def begin_step(self, decode_tokens: int = 0) -> int:
        """Fresh prefill-token budget for this engine step.

        ``decode_tokens`` is what the decode window already dispatched this
        cycle (interleaved mode: occupied lanes x window width).  Decode and
        prefill share one per-cycle token budget — the Sarathi/Orca joint
        bound — so a busy pool shrinks what prefill may add on top, keeping
        total step latency flat.  Anti-starvation lives in
        :meth:`take_chunk`, not here: the first forward-pass chunk of each
        cycle dispatches even over budget (or a chunk wider than the
        post-decode remainder could never run while any lane decodes, and a
        full pool under a long prompt livelocks admission); the budget
        throttles every chunk after it."""
        self._chunk_this_step = False
        if decode_tokens <= 0:
            return self.budget
        return max(self.budget - int(decode_tokens), 0)

    def start_next(self, slot: int) -> Optional[Request]:
        """Pop the FCFS head into PREFILL state, bound for ``slot``.  Up to
        ``max_prefills`` requests may be mid-prefill at once; admission order
        stays FCFS even though :meth:`take_chunk` picks among them SRTF."""
        if len(self._prefills) >= self.max_prefills or not self.queue:
            return None
        req = self.queue.popleft()
        req.state = RequestState.PREFILL
        req.slot = slot
        # refresh the prefix match: requests admitted since submit may have
        # populated exactly the chunks this one needs (the batch-submit case)
        self._match_prefix(req)
        self._prefills.append(req)
        self.recorder.record(
            "serve/prefill_start", rid=req.rid, slot=slot,
            chunks=len(req.chunks), cached_chunks=req.cached_chunks,
        )
        return req

    @staticmethod
    def _remaining_compute(req: Request) -> int:
        """Tokens still needing a forward pass: cached chunks replay for
        free, so they don't count toward shortest-remaining-first."""
        skip = max(req.next_chunk, req.cached_chunks)
        return sum(v for _, v in req.chunks[skip:])

    def take_chunk(self, budget: int, ready=None,
                   ) -> Optional[Tuple[Request, int, int, int, bool]]:
        """Next prefill chunk fitting ``budget``:
        ``(request, bucket_len, valid_len, start, cached)`` or None.

        With several open prefills the pick is shortest-remaining-first
        (remaining *compute* tokens; FCFS rid breaks ties) among those whose
        next chunk fits the budget — a chat prompt's single chunk lands ahead
        of a mega-prompt's hundredth without starving it (every candidate
        stays eligible each step).  ``ready`` is an optional per-request
        gate — the paged engine passes its page-reservation check, so a
        request short on pages this step doesn't block a smaller one that
        fits.

        A CACHED chunk (``cached=True``: covered by a pinned prefix-cache
        node) charges nothing against the budget — replaying retained KV is
        one ``dynamic_update_slice``, not a forward pass — so hits both skip
        compute and leave the whole budget to cold prompts this step.

        The FIRST forward-pass chunk since :meth:`begin_step` ignores the
        budget check: the joint decode+prefill bound may leave a remainder
        smaller than the pending bucket every single cycle, and without this
        carve-out such a chunk would starve until the pool idles.
        """
        best = None
        best_key = None
        for req in self._prefills:
            if req.next_chunk >= len(req.chunks):
                continue
            bucket, _ = req.chunks[req.next_chunk]
            cached = req.next_chunk < req.cached_chunks
            if not cached and bucket > budget and self._chunk_this_step:
                continue
            if ready is not None and not ready(req):
                continue
            key = (self._remaining_compute(req), req.rid)
            if best_key is None or key < best_key:
                best, best_key = req, key
        if best is None:
            return None
        bucket, valid = best.chunks[best.next_chunk]
        cached = best.next_chunk < best.cached_chunks
        start = sum(v for _, v in best.chunks[: best.next_chunk])
        best.next_chunk += 1
        if not cached:
            self._chunk_this_step = True
        return best, bucket, valid, start, cached

    def finish_prefill(self) -> Optional[Request]:
        """If an open prefill has run every chunk, hand it over for insertion
        and clear its prefill lane (at most one per call — the engine installs
        each finished request before taking the next chunk)."""
        for i, req in enumerate(self._prefills):
            if req.next_chunk >= len(req.chunks):
                del self._prefills[i]
                return req
        return None

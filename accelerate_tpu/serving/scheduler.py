"""Host-side request scheduling for the continuous-batching engine.

The device side (:mod:`.pool`) is a fixed set of compiled executables; the
scheduler is everything dynamic: a FCFS request queue, per-request
:class:`~accelerate_tpu.models.generation.GenerationConfig`, chunked-prefill
progress, and an admission policy bounded by a **prefill-token budget per
engine step** — the Orca/Sarathi knob that keeps decode-step latency jitter
bounded while new prompts stream in.

With a :class:`~accelerate_tpu.serving.prefix_cache.PrefixCache` attached, the
scheduler also resolves prefix reuse: ``submit`` walks the radix tree for the
longest cached chunk-aligned prefix (pinning the matched nodes so eviction
cannot pull them out from under the queued request), ``start_next`` refreshes
the walk — requests admitted earlier may have populated chunks this request
can now reuse — and ``take_chunk`` charges cached chunks at ZERO cost against
the prefill-token budget, so every hit also frees budget for cold prompts in
the same engine step.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..models.generation import GenerationConfig
from ..telemetry import get_flight_recorder
from .errors import AdmissionError
from .pool import plan_chunks


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class Request:
    """One serving request: prompt + per-request generation config + progress.

    ``on_token(request, token)`` streams each generated token as the engine
    observes it (window granularity); ``tokens`` accumulates the final
    generated ids (EOS included when hit, never the post-EOS padding).
    """

    rid: int
    prompt: np.ndarray                      # [S] int32
    config: GenerationConfig
    on_token: Optional[Callable[["Request", int], None]] = None
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    # chunked-prefill progress
    chunks: Tuple[Tuple[int, int], ...] = ()
    next_chunk: int = 0
    # prefix-cache state: the first ``cached_chunks`` entries of ``chunks``
    # are CACHED (replayed from retained KV slabs instead of prefilled);
    # ``cache_nodes`` holds the pinned radix nodes backing them plus any nodes
    # this request itself populates (released on insertion or cancel), and
    # ``cache_chain_broken`` stops population once a chunk could not be
    # retained (a later chunk without its ancestors would be unreachable).
    cache_prefix: bool = True
    # per-request speculative-decoding opt-out: when False the engine never
    # drafts for this request's lane even with ``speculate_k > 0`` (it still
    # rides along in verify windows other lanes trigger — with pad drafts,
    # which verification simply rejects)
    speculate: bool = True
    cached_chunks: int = 0
    cache_nodes: List[Any] = dataclasses.field(default_factory=list)
    cache_chain_broken: bool = False
    submit_step: int = -1
    finish_step: int = -1
    # wall-clock stamps (time.perf_counter) for TTFT / per-token latency
    submit_time: float = 0.0
    last_token_time: float = 0.0
    # replica index a :class:`~accelerate_tpu.serving.router.ReplicaRouter`
    # placed this request on (None when submitted straight to an engine)
    replica: Optional[int] = None
    # stable replica identity: unlike ``replica`` (a position in
    # ``router.engines``, which shifts when an earlier replica detaches),
    # this id survives elastic add/drain — cancel resolves through it first
    replica_id: Optional[int] = None
    # SLO deadline in seconds from submit (None = no deadline).  Admission
    # sheds when the queue-depth estimate says it is unmeetable; the engine's
    # deadline sweep cancels a running lane that blows it and sets
    # ``deadline_exceeded`` so the API layer can answer 504 instead of 500
    deadline_s: Optional[float] = None
    deadline_exceeded: bool = False

    @property
    def done(self) -> bool:
        return self.state is RequestState.DONE

    @property
    def output_ids(self) -> np.ndarray:
        """Prompt + generated tokens (the ``generate`` row, pad tail trimmed)."""
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def prefill_tokens(self) -> np.ndarray:
        """What prefill must process for this request *now*: the prompt, plus
        — after a preemption — every token already generated and streamed.
        Replay re-prefills the whole effective prompt (ideally via prefix-cache
        hits on the chunks this request populated in its first life) and
        generation resumes exactly where it stopped; ``tokens`` is never
        re-emitted.  Identical to ``prompt`` for a never-preempted request."""
        if not self.tokens:
            return self.prompt
        return self.output_ids

    def emit(self, token: int) -> None:
        self.tokens.append(int(token))
        if self.on_token is not None:
            self.on_token(self, int(token))

    def finished(self, token: int) -> bool:
        """Would emitting ``token`` complete this request?"""
        eos = self.config.eos_token_id
        return (eos is not None and int(token) == eos) or (
            len(self.tokens) + 1 >= self.config.max_new_tokens
        )


class Scheduler:
    """FCFS admission with a per-step prefill-token budget.

    One request prefills at a time (the scratch cache is batch-1); its chunks
    are charged against ``prefill_token_budget`` each engine step, so a long
    prompt spreads across steps instead of stalling every running request for
    its whole prefill (chunked prefill, Sarathi-style).
    """

    def __init__(self, prefill_buckets: Sequence[int], prefill_token_budget: int,
                 prefix_cache=None, recorder=None,
                 max_queue: Optional[int] = None):
        self.buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
        if not self.buckets:
            raise ValueError("need at least one prefill bucket")
        self.budget = int(prefill_token_budget)
        if self.budget < self.buckets[0]:
            raise ValueError(
                f"prefill_token_budget {self.budget} cannot fit the smallest "
                f"bucket {self.buckets[0]} — no prompt would ever be admitted"
            )
        # admission backpressure: with ``max_queue`` set, a submit that would
        # push the waiting line past it raises a *retriable* AdmissionError —
        # the signal the HTTP front door maps to 429 and the router's failover
        # ladder uses to try a less-loaded replica.  None = unbounded (the
        # in-process benches/tests drive their own queue depth).
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.queue: deque = deque()
        self.prefilling: Optional[Request] = None
        self.prefix_cache = prefix_cache
        # request-lifecycle events for post-mortems (a no-op ring append when
        # telemetry is disabled); the engine passes the process recorder
        self.recorder = recorder if recorder is not None else get_flight_recorder()

    def _match_prefix(self, request: Request) -> None:
        """(Re)walk the radix tree for ``request``'s longest cached prefix and
        pin the matched chain.  Pins taken by an earlier walk are released
        *after* the new chain is acquired — the old nodes are still resident
        during the re-walk, so the fresh match can only be equal or longer."""
        if self.prefix_cache is None or not request.cache_prefix:
            return
        nodes = self.prefix_cache.match(request.prefill_tokens, request.chunks)
        self.prefix_cache.acquire(nodes)
        if request.cache_nodes:
            self.prefix_cache.release(request.cache_nodes)
        request.cache_nodes = list(nodes)
        request.cached_chunks = len(nodes)

    def submit(self, request: Request) -> None:
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            # retry hint: the queue drains one request per freed slot; a rough
            # half-second per queued request is deliberately conservative —
            # callers treat it as "not before", not as a promise
            depth = self.queue_depth
            raise AdmissionError(
                f"admission queue full ({len(self.queue)} >= max_queue "
                f"{self.max_queue})",
                queue_depth=depth,
                retry_after_s=min(30.0, 0.5 * depth),
                retriable=True,
            )
        request.chunks = plan_chunks(len(request.prefill_tokens), self.buckets)
        self._match_prefix(request)
        self.queue.append(request)
        self.recorder.record(
            "serve/submit", rid=request.rid, prompt_len=len(request.prompt),
            chunks=len(request.chunks), cached_chunks=request.cached_chunks,
            queue_depth=len(self.queue),
        )

    def requeue(self, request: Request) -> None:
        """Put a preempted RUNNING request back at the FRONT of the queue for
        replay (it already waited its FCFS turn once).  Its effective prompt
        is ``prefill_tokens`` — original prompt plus everything generated —
        re-planned into chunks and re-matched against the prefix cache, so
        replay aliases/reuses whatever this request populated in its first
        life instead of recomputing it."""
        request.state = RequestState.QUEUED
        request.slot = None
        request.chunks = plan_chunks(len(request.prefill_tokens), self.buckets)
        request.next_chunk = 0
        request.cached_chunks = 0
        request.cache_chain_broken = False
        self._match_prefix(request)
        self.queue.appendleft(request)
        self.recorder.record(
            "serve/requeue", rid=request.rid,
            effective_len=len(request.prefill_tokens),
            cached_chunks=request.cached_chunks, queue_depth=len(self.queue),
        )

    def drop_cache_pins(self) -> int:
        """Release every *queued* request's prefix-cache pins (the paged
        engine's last-resort page reclaim: pinned nodes block eviction, and a
        queued request can always re-match at admission).  Returns how many
        requests were unpinned."""
        dropped = 0
        if self.prefix_cache is None:
            return 0
        for req in self.queue:
            if req.cache_nodes:
                self.prefix_cache.release(req.cache_nodes)
                req.cache_nodes = []
                req.cached_chunks = 0
                dropped += 1
        return dropped

    def cancel(self, rid: int) -> Optional[Request]:
        """Drop a still-QUEUED request (not yet prefilling) from the queue.

        Returns the cancelled :class:`Request` (state ``CANCELLED``, its
        pinned prefix-cache nodes released) or ``None`` when ``rid`` is not
        queued — already prefilling, running, done, or unknown.  Cancelling
        before admission is the cheap case worth optimizing: the request has
        consumed no prefill budget and holds no slot.
        """
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                del self.queue[i]
                if self.prefix_cache is not None and req.cache_nodes:
                    self.prefix_cache.release(req.cache_nodes)
                    req.cache_nodes = []
                req.state = RequestState.CANCELLED
                self.recorder.record("serve/cancel", rid=rid)
                return req
        return None

    @property
    def has_queued(self) -> bool:
        return bool(self.queue) or self.prefilling is not None

    @property
    def queue_depth(self) -> int:
        """Requests waiting or mid-prefill — the ``serve/queue_depth`` gauge
        and the router's load signal.  Admission runs against the engine's
        HOST lane state, which under the pipelined loop (``async_depth=1``)
        is authoritative even while a window is in flight: a lane retired at
        drain frees its slot immediately, one step after the sync loop would
        have (the documented EOS lag), so queue depth can read one step
        higher than ``async_depth=0`` under churn — never lower."""
        return len(self.queue) + (self.prefilling is not None)

    def begin_step(self) -> int:
        """Fresh prefill-token budget for this engine step."""
        return self.budget

    def start_next(self, slot: int) -> Optional[Request]:
        """Pop the FCFS head into PREFILL state, bound for ``slot``."""
        if self.prefilling is not None or not self.queue:
            return None
        req = self.queue.popleft()
        req.state = RequestState.PREFILL
        req.slot = slot
        # refresh the prefix match: requests admitted since submit may have
        # populated exactly the chunks this one needs (the batch-submit case)
        self._match_prefix(req)
        self.prefilling = req
        self.recorder.record(
            "serve/prefill_start", rid=req.rid, slot=slot,
            chunks=len(req.chunks), cached_chunks=req.cached_chunks,
        )
        return req

    def take_chunk(self, budget: int) -> Optional[Tuple[Request, int, int, int, bool]]:
        """Next prefill chunk fitting ``budget``:
        ``(request, bucket_len, valid_len, start, cached)`` or None.

        A CACHED chunk (``cached=True``: covered by a pinned prefix-cache
        node) charges nothing against the budget — replaying retained KV is
        one ``dynamic_update_slice``, not a forward pass — so hits both skip
        compute and leave the whole budget to cold prompts this step.
        """
        req = self.prefilling
        if req is None or req.next_chunk >= len(req.chunks):
            return None
        bucket, valid = req.chunks[req.next_chunk]
        cached = req.next_chunk < req.cached_chunks
        if not cached and bucket > budget:
            return None
        start = sum(v for _, v in req.chunks[: req.next_chunk])
        req.next_chunk += 1
        return req, bucket, valid, start, cached

    def finish_prefill(self) -> Optional[Request]:
        """If the in-flight request has prefilled every chunk, hand it over
        for insertion and clear the prefill lane."""
        req = self.prefilling
        if req is not None and req.next_chunk >= len(req.chunks):
            self.prefilling = None
            return req
        return None

"""The HTTP edge: OpenAI-style routes on a stdlib ``ThreadingHTTPServer``.

Routes (all JSON unless noted):

- ``POST /v1/completions`` / ``POST /v1/chat/completions`` — generate;
  ``"stream": true`` switches the response to SSE (``text/event-stream``,
  OpenAI chunk objects, ``data: [DONE]`` terminator).
- ``GET /v1/models`` — the served model plus one entry per live weights
  version (the A/B surface; pin with ``"model": "<name>@<version>"``).
- ``DELETE /v1/requests/<id>`` — cancel by response id (``cmpl-…`` /
  ``chatcmpl-…`` / bare rid), queued or running.
- ``GET /metrics`` | ``/healthz`` | ``/debug/flight`` | ``/debug/stacks`` |
  ``/debug/requests[/<id>]`` | ``/debug/slo`` — the telemetry surface,
  muxed onto this port through the shared
  :class:`~accelerate_tpu.telemetry.server.TelemetryEndpoints` (one process,
  one scrape target).  ``/healthz`` additionally aggregates per-replica
  router health: any stuck replica flips it to 503 (and, with
  ``slo_healthz=True``, so does any fast-burning SLO).

Tenant attribution: generation requests are attributed to a tenant taken
from the ``X-Tenant`` header, falling back to the API-key prefix of an
``Authorization: Bearer <tenant>-...`` token.  The resolved tenant rides
:class:`CompletionCall` into the engine (per-tenant metric families) and is
echoed back as ``X-Tenant`` on every response that carries
``X-Request-Id``, so callers can verify which bucket they billed.

Status mapping: malformed body → 400 (``invalid_request_error``); unknown
model → 404; queue-full backpressure (retriable
:class:`~accelerate_tpu.serving.errors.AdmissionError`) → 429 with a
``Retry-After`` header; capacity refusals → 400; stale heartbeat → 503 on
``/healthz``.  A client that disconnects mid-stream gets its request
cancelled (running lanes included) so its slot and KV pages free
immediately.

Every handler thread crosses into the engine only through the
:class:`~accelerate_tpu.serving.api.frontdoor.FrontDoor` ticket API — a
contract the ``handler-blocking`` lint rule enforces on this module.
"""

from __future__ import annotations

import json
import os
import random
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlsplit

from ...logging import get_logger
from ...telemetry import (
    MetricsRegistry,
    TelemetryEndpoints,
    get_registry,
    get_reqtrace,
)
from .. import faults
from ..errors import AdmissionError, DeadlineExceeded
from .frontdoor import FrontDoor
from .protocol import (
    SSE_DONE,
    ChatTemplate,
    CompletionCall,
    ValidationError,
    completion_chunk,
    completion_response,
    error_body,
    parse_chat_request,
    parse_completion_request,
    sse_frame,
)

logger = get_logger(__name__)

__all__ = ["ApiServer"]

#: Max accepted request body (token-id prompts are compact; 8 MiB is ample).
MAX_BODY_BYTES = 8 << 20

#: Tenant labels become metric-name segments (``serve/*_tenant_<t>_total``),
#: so the charset is the metric-name charset — anything else is dropped
#: rather than half-sanitized into a colliding label.
_TENANT_RE = re.compile(r"[A-Za-z0-9_]{1,64}")


def _tenant_from_headers(headers) -> Optional[str]:
    """Resolve the tenant for one request from gateway-controlled headers.

    ``X-Tenant`` wins; otherwise the prefix of an
    ``Authorization: Bearer <tenant>-<secret>`` API key is used (the common
    key-minting convention).  Returns ``None`` — unattributed — when neither
    yields a well-formed label; never raises.
    """
    raw = headers.get("X-Tenant")
    if raw and _TENANT_RE.fullmatch(raw.strip()):
        return raw.strip().lower()
    auth = headers.get("Authorization") or ""
    if auth.startswith("Bearer "):
        prefix = auth[len("Bearer "):].strip().split("-", 1)[0]
        if prefix and _TENANT_RE.fullmatch(prefix):
            return prefix.lower()
    return None


def _retry_after(seconds: float) -> str:
    """``Retry-After`` header value with +-25% jitter: a flood refused in the
    same instant must not retry in the same instant — synchronized retries
    would re-flood admission exactly one hint later."""
    return str(max(1, int(seconds * (0.75 + 0.5 * random.random()) + 0.5)))


def _request_id(call: CompletionCall, rid: int) -> str:
    return f"{'chatcmpl' if call.chat else 'cmpl'}-{rid}"


def _parse_request_id(raw: str) -> Optional[int]:
    for prefix in ("chatcmpl-", "cmpl-"):
        if raw.startswith(prefix):
            raw = raw[len(prefix):]
            break
    try:
        return int(raw)
    except ValueError:
        return None


class _ApiHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("api server: " + fmt % args)

    @property
    def api(self) -> "ApiServer":
        return self.server.api_server  # type: ignore[attr-defined]

    # ----------------------------------------------------------- plumbing
    def _send(self, code: int, body: Dict[str, Any],
              extra_headers: Optional[Dict[str, str]] = None) -> None:
        payload = json.dumps(body, indent=1).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _send_text(self, code: int, content_type: str, text: str) -> None:
        payload = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _read_body(self) -> Any:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise ValidationError("request body is required")
        if length > MAX_BODY_BYTES:
            raise ValidationError(f"request body exceeds {MAX_BODY_BYTES} bytes")
        raw = self.rfile.read(length)
        try:
            return json.loads(raw)
        except (ValueError, UnicodeDecodeError) as exc:
            raise ValidationError(f"body is not valid JSON: {exc}") from exc

    # ------------------------------------------------------------- routes
    def do_GET(self) -> None:  # noqa: N802 - http.server API
        api = self.api
        api.http_requests.inc()
        parts = urlsplit(self.path)
        try:
            if parts.path == "/v1/models":
                self._send(200, api.models_body())
            elif parts.path == "/":
                self._send_text(
                    200, "text/plain; charset=utf-8",
                    "accelerate_tpu serving front door\n"
                    "endpoints: /v1/completions /v1/chat/completions "
                    "/v1/models /metrics /healthz /debug/flight "
                    "/debug/stacks /debug/requests /debug/slo\n",
                )
            else:
                code, ctype, body = api.endpoints.handle(parts.path, parts.query)
                self._send_text(code, ctype, body)
        except Exception as exc:
            self._safe_error(exc)

    def do_DELETE(self) -> None:  # noqa: N802 - http.server API
        api = self.api
        api.http_requests.inc()
        parts = urlsplit(self.path)
        try:
            prefix = "/v1/requests/"
            if not parts.path.startswith(prefix):
                self._send(404, error_body("not found", "invalid_request_error"))
                return
            rid = _parse_request_id(parts.path[len(prefix):])
            if rid is None:
                self._send(400, error_body(
                    "request id must be cmpl-<n>, chatcmpl-<n>, or an integer",
                    "invalid_request_error",
                ))
                return
            cancelled = api.frontdoor.cancel(rid)
            self._send(200 if cancelled else 404, {
                "id": f"cmpl-{rid}",
                "object": "request.cancellation",
                "cancelled": cancelled,
            })
        except Exception as exc:
            self._safe_error(exc)

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        api = self.api
        api.http_requests.inc()
        api.http_inflight.inc()
        parts = urlsplit(self.path)
        try:
            if parts.path == "/v1/completions":
                call = parse_completion_request(self._read_body(),
                                                encode=api.encode)
            elif parts.path == "/v1/chat/completions":
                call = parse_chat_request(self._read_body(),
                                          template=api.chat_template,
                                          encode=api.encode)
            else:
                self._send(404, error_body("not found", "invalid_request_error"))
                return
            # attribution comes from headers, never the JSON body: the body
            # is caller-controlled, the headers are gateway-controlled
            call.tenant = _tenant_from_headers(self.headers)
            self._generate(call)
        except ValidationError as exc:
            self._send(400, error_body(str(exc), "invalid_request_error",
                                       param=exc.param))
        except AdmissionError as exc:
            self._admission_refused(exc)
        except TimeoutError as exc:
            # the driver didn't pick up the ticket in time: the engine is
            # wedged or saturated, but the condition is transient — tell the
            # client to come back, not that the server is broken
            self._send(503, error_body(
                str(exc), "service_unavailable", code="driver_busy",
            ), extra_headers={"Retry-After": _retry_after(5.0)})
        except Exception as exc:
            self._safe_error(exc)
        finally:
            api.http_inflight.dec()

    # ---------------------------------------------------------- generation
    def _admission_refused(self, exc: AdmissionError) -> None:
        api = self.api
        if exc.retriable:
            api.http_429.inc()
            headers = {}
            if exc.retry_after_s is not None:
                headers["Retry-After"] = _retry_after(exc.retry_after_s)
            self._send(429, error_body(
                str(exc), "rate_limit_error", code="engine_overloaded",
            ), extra_headers=headers)
        elif "not found" in str(exc):
            self._send(404, error_body(str(exc), "invalid_request_error",
                                       code="model_not_found", param="model"))
        else:
            self._send(400, error_body(str(exc), "invalid_request_error",
                                       code="capacity_exceeded"))

    def _generate(self, call: CompletionCall) -> None:
        api = self.api
        version = api.frontdoor.resolve_model(call.model)
        req, stream = api.frontdoor.submit(call, model_version=version)
        # address the request by the front door's id, not req.rid: engine
        # rids are per-replica and rewritten on failover adoption
        request_id = _request_id(call, stream.rid)
        created = int(time.time())
        model = call.model or api.frontdoor.model_name
        if call.stream:
            self._stream_response(call, stream.rid, stream, request_id,
                                  created, model)
            return
        if not stream.wait_done(api.request_timeout_s):
            api.frontdoor.cancel(stream.rid)
            self._send(504, error_body(
                f"generation exceeded {api.request_timeout_s}s",
                "timeout_error",
            ))
            return
        if isinstance(stream.error, DeadlineExceeded):
            self._send(504, error_body(
                str(stream.error), "timeout_error", code="deadline_exceeded",
            ))
            return
        if stream.error is not None:
            self._send(500, error_body(
                f"generation failed: {stream.error!r}", "internal_error",
            ))
            return
        headers = {"X-Request-Id": request_id}
        if call.tenant is not None:
            headers["X-Tenant"] = call.tenant
        self._send(200, completion_response(
            call, request_id, created, model, stream.final_tokens,
            eos_token_id=call.stop_token_id,
            cancelled=stream.final_state is not None
            and stream.final_state.name == "CANCELLED",
            decode=api.decode,
        ), extra_headers=headers)

    def _stream_response(self, call: CompletionCall, rid: int, stream,
                         request_id: str, created: int, model: str) -> None:
        api = self.api
        api.sse_streams.inc()
        # SSE: no Content-Length — the body ends when the connection closes
        # (Connection: close keeps that well-formed under HTTP/1.1)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream; charset=utf-8")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("X-Request-Id", request_id)
        if call.tenant is not None:
            self.send_header("X-Tenant", call.tenant)
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        first = True
        # per-request waterfall: accumulate this handler thread's SSE write
        # time into the trace (an overlay — it runs concurrently with engine
        # phases on another thread, so it never enters the TTFT tiling)
        trace = get_reqtrace().lookup(str(rid))
        sse_t0 = time.perf_counter()
        try:
            while True:
                try:
                    token = stream.get(timeout=api.request_timeout_s)
                except Exception:
                    api.frontdoor.cancel(rid)
                    return
                if token is None:
                    break
                if (faults.ACTIVE is not None
                        and faults.ACTIVE.fire("handler_disconnect")):
                    # stand-in for the client's socket dying mid-stream: the
                    # except below must cancel the lane and free its pages
                    raise BrokenPipeError("injected SSE client disconnect")
                w0 = time.perf_counter()
                self.wfile.write(sse_frame(completion_chunk(
                    call, request_id, created, model, token, first,
                    decode=api.decode,
                )).encode("utf-8"))
                self.wfile.flush()
                if trace is not None:
                    trace.add_sse_write(time.perf_counter() - w0)
                first = False
            cancelled = (stream.final_state is not None
                         and stream.final_state.name == "CANCELLED")
            if stream.error is not None:
                # headers are long gone — an explicit error chunk is the only
                # honest way to end a broken SSE stream
                reason = "error"
            else:
                reason = ("cancelled" if cancelled else "stop"
                          if (call.stop_token_id is not None
                              and stream.final_tokens
                              and stream.final_tokens[-1] == call.stop_token_id)
                          else "length")
            self.wfile.write(sse_frame(completion_chunk(
                call, request_id, created, model, None, first,
                finish_reason=reason, decode=api.decode,
            )).encode("utf-8"))
            self.wfile.write(SSE_DONE.encode("utf-8"))
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            # the client went away mid-stream: free its lane and KV now
            api.frontdoor.cancel(rid)
        finally:
            if trace is not None and trace.sse_writes:
                trace.overlay("sse_write", sse_t0, trace.sse_write_s,
                              writes=trace.sse_writes)
            api.sse_streams.dec()

    def _safe_error(self, exc: Exception) -> None:
        logger.warning("api handler failed", exc_info=True)
        try:
            self._send(500, error_body(f"internal error: {exc!r}",
                                       "internal_error"))
        except Exception:  # noqa: swallowed-exception (client socket is gone)
            pass


class _HttpServer(ThreadingHTTPServer):
    """Handler threads are daemons, and the accept backlog is sized for
    bursts: the stdlib default (5) turns a flood into TCP connection resets
    before admission control can answer 429."""

    daemon_threads = True
    request_queue_size = 128


class ApiServer:
    """Binds the front door + telemetry surface to one HTTP port.

    Parameters
    ----------
    frontdoor: a started :class:`FrontDoor` (this server never steps
        engines itself).
    host/port: bind address; port ``0`` picks an ephemeral port (tests).
        Default host comes from ``ATPU_API_HOST`` (fallback 127.0.0.1 — the
        generation API is not a scrape endpoint; expose it deliberately).
    registry: metrics registry for the HTTP counters (default: the process
        registry, i.e. the same one the engines publish to — one
        ``/metrics`` page tells the whole story).
    encode/decode: optional tokenizer hooks (``str -> ids`` and
        ``ids -> str``).  Without them the API is token-id native.
    chat_template: token-id chat template for ``/v1/chat/completions``.
    unhealthy_after_s: heartbeat staleness threshold for ``/healthz``.
    request_timeout_s: server-side cap on one generation (504 + cancel).
    slo_healthz: opt-in — flip ``/healthz`` to 503 while any installed SLO
        is fast-burning (both burn windows over threshold).  Off by default
        because a load balancer draining a replica for an error-budget burn
        is a policy decision, not a liveness fact.
    """

    def __init__(
        self,
        frontdoor: FrontDoor,
        host: Optional[str] = None,
        port: int = 0,
        registry: Optional[MetricsRegistry] = None,
        encode=None,
        decode=None,
        chat_template: Optional[ChatTemplate] = None,
        unhealthy_after_s: float = 60.0,
        request_timeout_s: float = 600.0,
        slo_healthz: bool = False,
    ):
        self.frontdoor = frontdoor
        self.encode = encode
        self.decode = decode
        self.chat_template = chat_template if chat_template is not None \
            else ChatTemplate()
        self.request_timeout_s = float(request_timeout_s)
        self.metrics = registry if registry is not None else get_registry()
        self.endpoints = TelemetryEndpoints(
            registry=self.metrics,
            unhealthy_after_s=unhealthy_after_s,
            health_extra=self._router_health,
            slo_healthz=slo_healthz,
        )
        self.http_requests = self.metrics.counter(
            "serve/http_requests_total",
            help="HTTP requests accepted by the serving front door",
        )
        self.http_inflight = self.metrics.gauge(
            "serve/http_inflight",
            help="generation requests currently inside a handler thread",
        )
        self.http_429 = self.metrics.counter(
            "serve/http_429_total",
            help="requests refused with 429 under admission backpressure",
        )
        self.sse_streams = self.metrics.gauge(
            "serve/sse_streams",
            help="SSE token streams currently open",
        )
        host = host if host is not None else os.environ.get(
            "ATPU_API_HOST", "127.0.0.1"
        )
        self._httpd = _HttpServer((host, int(port)), _ApiHandler)
        self._httpd.api_server = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="atpu-api-server",
            daemon=True,
        )
        self._thread.start()
        logger.info("serving front door listening on %s", self.url)

    # ------------------------------------------------------------- surface
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def url(self) -> str:
        host = self.host if self.host not in ("0.0.0.0", "") else "127.0.0.1"
        return f"http://{host}:{self.port}"

    def models_body(self) -> Dict[str, Any]:
        """``GET /v1/models``: the served name plus one pinnable entry per
        live weights version."""
        created = int(time.time())
        name = self.frontdoor.model_name
        data = [{
            "id": name, "object": "model", "created": created,
            "owned_by": "accelerate_tpu",
        }]
        for version, replicas in sorted(self.frontdoor.model_versions().items()):
            data.append({
                "id": f"{name}@{version}", "object": "model",
                "created": created, "owned_by": "accelerate_tpu",
                "weights_version": version, "replicas": replicas,
            })
        return {"object": "list", "data": data}

    def _router_health(self) -> Tuple[bool, Dict[str, Any]]:
        """Per-replica aggregation merged into ``/healthz``: a replica with
        queued-or-running work whose engine never steps shows up here as
        ``has_work`` with a stale heartbeat — and the stale heartbeat alone
        already trips the base check; this adds the per-replica view and the
        routing counters an operator needs to see which replica it is."""
        health = self.frontdoor.health()
        return True, {"router": health}

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

"""OpenAI-compatible HTTP front door for the serving engine.

Three layers, separable on purpose:

- :mod:`.protocol` — wire validation, chat templating, SSE framing (pure
  functions, no threads, no engine).
- :mod:`.frontdoor` — the driver thread that exclusively owns the
  :class:`~accelerate_tpu.serving.router.ReplicaRouter`; handler threads
  cross only through its ticket API and per-request
  :class:`~.frontdoor.TokenStream` queues (enforced by the
  ``handler-blocking`` lint rule).
- :mod:`.server` — the stdlib ``ThreadingHTTPServer`` edge: OpenAI routes,
  SSE streaming, backpressure → 429, disconnect → cancel, and the muxed
  telemetry surface (``/metrics``, ``/healthz``, ``/debug/*``).

``python -m accelerate_tpu.serve`` (see :mod:`accelerate_tpu.serve`) wires
the three into a runnable service; ``bench_inference.py --task serve
--http-ab`` drives them over the wire.  See ``docs/usage/api_server.md``.
"""

from .frontdoor import FrontDoor, TokenStream
from .protocol import (
    SSE_DONE,
    ChatTemplate,
    CompletionCall,
    ValidationError,
    completion_chunk,
    completion_response,
    parse_chat_request,
    parse_completion_request,
    sse_frame,
)
from .server import ApiServer

__all__ = [
    "ApiServer",
    "FrontDoor",
    "TokenStream",
    "ChatTemplate",
    "CompletionCall",
    "ValidationError",
    "parse_completion_request",
    "parse_chat_request",
    "completion_response",
    "completion_chunk",
    "sse_frame",
    "SSE_DONE",
]

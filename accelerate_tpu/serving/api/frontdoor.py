"""The driver: single-threaded engine ownership behind a thread-safe inbox.

The engine's host state (scheduler deques, lane arrays, block tables, the
prefix-cache radix tree) is mutated without locks by design — everything
device-adjacent happens on ONE thread.  A ``ThreadingHTTPServer`` hands each
request its own thread, so the front door needs a crossing point, and this
module is it: :class:`FrontDoor` owns a driver thread that is the *only*
thread ever calling into the :class:`~accelerate_tpu.serving.router.
ReplicaRouter` or its engines.  Handler threads interact exclusively
through:

* :meth:`submit` / :meth:`cancel` / :meth:`hot_swap` / :meth:`add_replica` /
  :meth:`drain_replica` — synchronous *tickets*: the closure is queued, the
  driver runs it between engine steps, and the caller's thread blocks on an
  event until the result (or the raised ``AdmissionError``) comes back.
* :class:`TokenStream` — a per-request ``queue.Queue`` the driver feeds from
  the engine's ``on_token`` callback and closes when the request reaches
  ``DONE``/``CANCELLED``; handler threads only ever *read* it.

This contract is machine-checked: the ``handler-blocking`` atpu-lint rule
forbids every other module in :mod:`accelerate_tpu.serving.api` from calling
engine/router internals or blocking device readbacks directly.

The driver loop also emits the ``serve/step`` heartbeat while idle (an idle
API server is a healthy one — without this, ``/healthz`` would go stale-503
the moment traffic pauses) and reaps finished requests into their streams.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...logging import get_logger
from ...models.generation import GenerationConfig
from ...telemetry import get_flight_recorder, get_reqtrace, slo_tick
from ..errors import AdmissionError, DeadlineExceeded
from ..router import ReplicaRouter
from ..scheduler import Request, RequestState
from .protocol import CompletionCall

logger = get_logger(__name__)

__all__ = ["FrontDoor", "TokenStream"]

#: Sentinel queued into a TokenStream when the producer side closes.
_CLOSED = object()


class TokenStream:
    """One request's token feed across the thread boundary.

    The driver thread is the only producer (``push`` per token, ``close``
    once, at completion/cancellation); any number of handler-side consumers
    may ``get`` or ``wait_done``.  After ``close``, ``final_tokens`` /
    ``final_state`` are the authoritative snapshot — handler threads never
    read the live ``Request`` object the engine is still mutating.
    """

    def __init__(self, rid: int):
        self.rid = rid
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self.final_tokens: List[int] = []
        self.final_state: Optional[RequestState] = None
        self.error: Optional[BaseException] = None

    # ---- driver side -----------------------------------------------------
    def push(self, token: int) -> None:
        self._q.put(int(token))

    def close(self, tokens: List[int], state: Optional[RequestState],
              error: Optional[BaseException] = None) -> None:
        self.final_tokens = list(tokens)
        self.final_state = state
        self.error = error
        self._done.set()
        self._q.put(_CLOSED)

    # ---- handler side ----------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next token, or ``None`` when the stream is closed (drain any
        tokens queued before the close first).  Raises ``queue.Empty`` on
        timeout."""
        item = self._q.get(timeout=timeout)
        return None if item is _CLOSED else item

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class _Ticket:
    """One closure to run on the driver thread, plus the rendezvous."""

    __slots__ = ("fn", "admin", "event", "result", "error")

    def __init__(self, fn: Callable[[], Any], admin: bool):
        self.fn = fn
        self.admin = admin
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None


class FrontDoor:
    """Owns the router + driver thread; the API server's only way in.

    Parameters
    ----------
    router: the (elastic) replica backend.  The front door takes over
        driving it — nothing else may call ``router.step()`` once
        :meth:`start` runs.
    model_name: the id served by ``/v1/models``; requests may pin a weights
        version as ``"<model_name>@<version>"``.
    idle_sleep_s: driver nap between polls when there is no work and no
        tickets (keeps the idle loop off a CPU core).
    heartbeat_interval_s: cadence of the idle ``serve/step`` heartbeat.
    ticket_timeout_s: how long a handler thread waits for the driver to pick
        up its ticket before giving up (a driver wedged in device work this
        long means the stall detector is about to fire anyway).
    """

    def __init__(
        self,
        router: ReplicaRouter,
        model_name: str = "accelerate-tpu",
        idle_sleep_s: float = 0.001,
        heartbeat_interval_s: float = 1.0,
        ticket_timeout_s: float = 120.0,
    ):
        self.router = router
        self.model_name = str(model_name)
        self.idle_sleep_s = float(idle_sleep_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.ticket_timeout_s = float(ticket_timeout_s)
        self.recorder = get_flight_recorder().tagged(engine="frontdoor")
        self._tickets: "queue.Queue[_Ticket]" = queue.Queue()
        # keyed by a front-door-minted id, NOT ``req.rid``: engine rids are
        # per-replica counters (and rewritten by failover adoption), so two
        # replicas' rids collide here and the clobbered entry's stream would
        # never be reaped — its handler would hang until the client timeout
        self._next_key = 0
        self._outstanding: Dict[int, Tuple[Request, TokenStream]] = {}
        self._stop = threading.Event()
        self._in_admin = False
        self._thread: Optional[threading.Thread] = None
        self._last_heartbeat = 0.0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FrontDoor":
        if self._thread is not None:
            raise RuntimeError("FrontDoor already started")
        self._thread = threading.Thread(
            target=self._drive, name="atpu-frontdoor-driver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # ---------------------------------------------------- handler-side API
    def _call(self, fn: Callable[[], Any], admin: bool = False) -> Any:
        """Run ``fn`` on the driver thread; block until it completes."""
        if self._thread is None:
            raise RuntimeError("FrontDoor is not running (call start())")
        if threading.current_thread() is self._thread:
            return fn()  # already on the driver: run inline, never deadlock
        t = _Ticket(fn, admin)
        self._tickets.put(t)
        if not t.event.wait(self.ticket_timeout_s):
            raise TimeoutError(
                f"driver did not service the request within "
                f"{self.ticket_timeout_s}s"
            )
        if t.error is not None:
            raise t.error
        return t.result

    def submit(self, call: CompletionCall,
               model_version: Optional[str] = None) -> Tuple[Request, TokenStream]:
        """Queue one validated call; returns the live request handle plus its
        token stream.  Raises :class:`AdmissionError` exactly as the router
        does (queue full / capacity / no replica for the pinned version)."""
        gen = GenerationConfig(
            max_new_tokens=int(call.max_tokens),
            do_sample=call.temperature > 0.0,
            temperature=call.temperature if call.temperature > 0.0 else 1.0,
            top_k=call.top_k,
            top_p=call.top_p,
            eos_token_id=call.stop_token_id,
        )

        def _do() -> Tuple[Request, TokenStream]:
            stream_box: List[TokenStream] = []

            def on_token(req: Request, token: int) -> None:
                stream_box[0].push(token)

            req = self.router.submit(
                call.prompt, config=gen, on_token=on_token,
                model_version=model_version, deadline_s=call.deadline_s,
                tenant=call.tenant,
            )
            self._next_key += 1
            stream = TokenStream(self._next_key)
            stream_box.append(stream)
            self._outstanding[stream.rid] = (req, stream)
            # the front-door key becomes the trace's authoritative id: it is
            # what the API server echoes as X-Request-Id, and unlike the
            # engine rid it never changes across failover adoption
            get_reqtrace().rekey(req.trace, str(stream.rid))
            return req, stream

        return self._call(_do)

    def cancel(self, rid: int) -> bool:
        """Cancel by front-door request id — the ``stream.rid`` handed back
        from :meth:`submit` and echoed to clients (queued or running).  The
        stream closes on the driver's next reap pass."""

        def _do() -> bool:
            entry = self._outstanding.get(rid)
            if entry is None:
                return False
            req, stream = entry
            ok = self.router.cancel(req)
            # a request the engine already finished can't be cancelled, but
            # either way the stream resolves on the next reap
            self._reap()
            return ok

        return self._call(_do)

    def hot_swap(self, params: Any, version: Optional[str] = None) -> int:
        """Rolling zero-downtime weight swap across every replica (see
        :meth:`ReplicaRouter.hot_swap`).  Blocks the calling thread until
        the rollout completes; in-flight and newly submitted requests keep
        being served throughout — the drain loop keeps pumping the inbox."""
        return self._call(
            lambda: self.router.hot_swap(params, version=version,
                                         step_fn=self._pump),
            admin=True,
        )

    def add_replica(self, engine) -> int:
        return self._call(lambda: self.router.add_replica(engine), admin=True)

    def drain_replica(self, replica_id: int) -> None:
        return self._call(
            lambda: self.router.drain_replica(replica_id), admin=True
        )

    def migrate_lane(
        self,
        from_replica: Optional[int] = None,
        to_replica: Optional[int] = None,
        slot: Optional[int] = None,
        reason: str = "rebalance",
    ) -> bool:
        """Live-rebalance one running lane between replicas
        (:meth:`ReplicaRouter.migrate_lane`) — an admin ticket, so the move
        runs on the driver thread between steps, never mid-window."""
        return self._call(
            lambda: self.router.migrate_lane(
                from_replica=from_replica, to_replica=to_replica,
                slot=slot, reason=reason,
            ),
            admin=True,
        )

    def lookup(self, rid: int) -> Optional[Tuple[Request, TokenStream]]:
        """Read-only peek at an outstanding request (DELETE-cancel routing).
        The tuple is a snapshot; only :class:`TokenStream` may be consumed
        from handler threads."""
        return self._outstanding.get(rid)

    def health(self) -> dict:
        """Router aggregation for ``/healthz`` — plain host-side counters
        (ints/bools), safe to read from any thread."""
        return self.router.health()

    def model_versions(self) -> dict:
        return self.router.versions()

    def resolve_model(self, model: Optional[str]) -> Optional[str]:
        """Map the wire ``model`` string to a weights-version pin: ``None``
        or the bare served name routes anywhere; ``"<name>@<version>"``
        (or a bare version label) pins.  Unknown names raise
        :class:`AdmissionError` (non-retriable → 400/404 at the edge)."""
        if model is None or model == "" or model == self.model_name:
            return None
        version = model
        if model.startswith(self.model_name + "@"):
            version = model[len(self.model_name) + 1:]
        if version in self.router.versions():
            return version
        raise AdmissionError(
            f"model {model!r} not found (serving {self.model_name!r}, "
            f"versions {sorted(self.router.versions())})",
            retriable=False,
        )

    # ------------------------------------------------------------- driver
    def _reap(self) -> None:
        """Close the streams of every finished/cancelled request.  Runs on
        the driver thread only."""
        finished = [
            rid for rid, (req, _) in self._outstanding.items()
            if req.state in (RequestState.DONE, RequestState.CANCELLED)
        ]
        for rid in finished:
            req, stream = self._outstanding.pop(rid)
            if req.deadline_exceeded:
                # the engine's deadline sweep cancelled it — close with the
                # typed error so the edge answers 504, not a silent truncation
                stream.close(
                    req.tokens, req.state,
                    error=DeadlineExceeded(
                        f"request {rid} exceeded its {req.deadline_s}s "
                        f"deadline after {len(req.tokens)} tokens",
                        deadline_s=req.deadline_s or 0.0,
                    ),
                )
            else:
                stream.close(req.tokens, req.state)

    def _process_tickets(self, skip_admin: bool = False) -> None:
        deferred: List[_Ticket] = []
        while True:
            try:
                t = self._tickets.get_nowait()
            except queue.Empty:
                break
            if skip_admin and t.admin:
                # an admin op is already in progress on this stack (we are
                # inside its drain loop); run nested admin ops after it
                deferred.append(t)
                continue
            try:
                t.result = t.fn()
            except BaseException as exc:  # propagate to the waiting thread
                t.error = exc
            finally:
                t.event.set()
        for t in deferred:
            self._tickets.put(t)

    def _pump(self) -> None:
        """One drive iteration: service the inbox (admin ops deferred —
        this is also the hot-swap drain hook, which must keep accepting
        submits without re-entering another rollout), step replicas with
        work, resolve finished requests."""
        self._process_tickets(skip_admin=True)
        if self.router.has_work:
            self.router.step()
        self._reap()

    def _fail_outstanding(self, exc: BaseException) -> None:
        """An engine step blew up: every in-flight request's stream is closed
        with the error (handlers turn it into a 500) instead of stranding its
        handler thread until the request timeout.  The driver keeps running —
        later submits get a fresh, fast error rather than a dead socket."""
        logger.exception("front door driver step failed: %r", exc)
        self.recorder.record("serve/driver_error", error=repr(exc),
                             outstanding=len(self._outstanding))
        for rid, (req, stream) in list(self._outstanding.items()):
            stream.close(req.tokens, req.state, error=exc)
            self._outstanding.pop(rid, None)

    def _drive(self) -> None:
        while not self._stop.is_set():
            worked = False
            try:
                self._process_tickets()
                if self.router.has_work:
                    self.router.step()
                    worked = True
                self._reap()
            except Exception as exc:
                self._fail_outstanding(exc)
            now = time.monotonic()
            if now - self._last_heartbeat >= self.heartbeat_interval_s:
                # stepping engines heartbeat on their own; the idle server
                # must too, or /healthz would 503 between requests
                self.recorder.heartbeat(
                    "serve/step",
                    idle=not worked,
                    outstanding=len(self._outstanding),
                )
                self._last_heartbeat = now
                # fleet-health tick rides the heartbeat: samples the
                # time-series ring and re-evaluates installed SLOs even
                # while the server is idle (an idle replica can still be
                # burning availability budget on sheds it just served)
                slo_tick()
            if not worked and self._tickets.empty():
                time.sleep(self.idle_sleep_s)
        # drain: fail any still-waiting tickets rather than strand threads
        while True:
            try:
                t = self._tickets.get_nowait()
            except queue.Empty:
                break
            t.error = RuntimeError("front door stopped")
            t.event.set()
        for rid, (req, stream) in list(self._outstanding.items()):
            stream.close(req.tokens, req.state)
            self._outstanding.pop(rid, None)

"""OpenAI wire protocol: request validation, chat templating, SSE framing.

Pure host-side data plumbing — no engine, no device, no threads.  The
handler layer (:mod:`.server`) parses bytes into :class:`CompletionCall`
here, and renders :class:`~accelerate_tpu.serving.scheduler.Request` results
back into OpenAI response / SSE-chunk dicts here, so the protocol surface is
testable without ever binding a port.

Token-id native: this stack serves models, not tokenizers.  ``prompt`` (and
chat message ``content``) is accepted as an **array of token ids** — a form
the OpenAI completions API itself permits — and responses always carry a
``token_ids`` extension field alongside ``text``.  Plain-string prompts are
supported only when the front door was built with ``encode``/``decode``
hooks (any callable pair; e.g. a sentencepiece model); without them a string
prompt is a 400, not a crash.

SSE framing follows the OpenAI streaming contract: each event is
``data: <json>\n\n`` with object type ``text_completion`` (completions) or
``chat.completion.chunk`` (chat, deltas), and the stream terminates with the
literal ``data: [DONE]\n\n`` sentinel.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ValidationError",
    "CompletionCall",
    "ChatTemplate",
    "parse_completion_request",
    "parse_chat_request",
    "completion_response",
    "completion_chunk",
    "sse_frame",
    "SSE_DONE",
]

#: Terminal SSE frame, verbatim from the OpenAI streaming contract.
SSE_DONE = "data: [DONE]\n\n"


class ValidationError(ValueError):
    """Malformed request body — the front door maps it to HTTP 400 with an
    OpenAI-style ``invalid_request_error`` envelope."""

    def __init__(self, message: str, param: Optional[str] = None):
        super().__init__(message)
        self.param = param


@dataclasses.dataclass
class CompletionCall:
    """One validated generation call, engine-ready.

    ``prompt`` is always token ids by the time this exists; ``model`` is the
    raw model string (version pinning is resolved by the front door, which
    knows what the router serves); ``chat`` marks which response dialect
    (``text_completion`` vs ``chat.completion``) the caller spoke.
    """

    prompt: List[int]
    max_tokens: int = 16
    temperature: float = 1.0
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    stop_token_id: Optional[int] = None
    stream: bool = False
    model: Optional[str] = None
    echo: bool = False
    chat: bool = False
    # SLO budget in seconds from submit: admission sheds (429) when the
    # queue estimate says it is unmeetable, and a running lane that blows it
    # is cancelled with a 504 (engine-side deadline sweep)
    deadline_s: Optional[float] = None
    # caller attribution: set by the HTTP layer from the ``X-Tenant`` header
    # (or the Authorization API-key prefix), never from the JSON body — the
    # body is caller-controlled, the header is gateway-controlled.  Threads
    # through FrontDoor -> ReplicaRouter -> ServingEngine.submit(tenant=)
    tenant: Optional[str] = None


def _require_dict(body: Any) -> Dict[str, Any]:
    if not isinstance(body, dict):
        raise ValidationError("request body must be a JSON object")
    return body


def _token_list(value: Any, param: str) -> List[int]:
    if not isinstance(value, (list, tuple)) or not value:
        raise ValidationError(f"{param} must be a non-empty array of token ids",
                              param=param)
    out = []
    for t in value:
        if isinstance(t, bool) or not isinstance(t, int):
            raise ValidationError(
                f"{param} must contain only integer token ids (got {t!r})",
                param=param,
            )
        if t < 0:
            raise ValidationError(f"{param} token ids must be >= 0", param=param)
        out.append(int(t))
    return out


def _coerce_prompt(value: Any, param: str,
                   encode: Optional[Callable[[str], Sequence[int]]]) -> List[int]:
    """Token ids pass through; strings go through the ``encode`` hook."""
    if isinstance(value, str):
        if encode is None:
            raise ValidationError(
                f"{param} is a string but this server has no tokenizer; "
                f"send an array of token ids",
                param=param,
            )
        return [int(t) for t in encode(value)]
    return _token_list(value, param)


def _number(body: Dict[str, Any], key: str, default, lo, hi, integral=False):
    value = body.get(key, default)
    if value is None:
        return None
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise ValidationError(f"{key} must be a number", param=key)
    if integral and int(value) != value:
        raise ValidationError(f"{key} must be an integer", param=key)
    if not (lo <= value <= hi):
        raise ValidationError(f"{key} must be in [{lo}, {hi}]", param=key)
    return int(value) if integral else float(value)


def _common_fields(body: Dict[str, Any]) -> Dict[str, Any]:
    n = _number(body, "n", 1, 1, 1, integral=True)
    if n != 1:  # unreachable via the bounds, kept for a clear message
        raise ValidationError("only n=1 is supported", param="n")
    stream = body.get("stream", False)
    if not isinstance(stream, bool):
        raise ValidationError("stream must be a boolean", param="stream")
    stop = body.get("stop")
    stop_token_id = None
    if stop is not None:
        if isinstance(stop, bool) or not isinstance(stop, int):
            raise ValidationError(
                "stop must be a single token id on this server", param="stop"
            )
        stop_token_id = int(stop)
    model = body.get("model")
    if model is not None and not isinstance(model, str):
        raise ValidationError("model must be a string", param="model")
    return dict(
        max_tokens=_number(body, "max_tokens", 16, 1, 1 << 20, integral=True),
        temperature=_number(body, "temperature", 1.0, 0.0, 2.0),
        top_p=_number(body, "top_p", None, 0.0, 1.0),
        top_k=_number(body, "top_k", None, 1, 1 << 20, integral=True),
        stop_token_id=stop_token_id,
        stream=stream,
        model=model,
        deadline_s=_number(body, "deadline_s", None, 0.001, 3600.0),
    )


def parse_completion_request(
    body: Any, encode: Optional[Callable[[str], Sequence[int]]] = None
) -> CompletionCall:
    """Validate a ``POST /v1/completions`` body into a :class:`CompletionCall`."""
    body = _require_dict(body)
    if "prompt" not in body:
        raise ValidationError("prompt is required", param="prompt")
    echo = body.get("echo", False)
    if not isinstance(echo, bool):
        raise ValidationError("echo must be a boolean", param="echo")
    return CompletionCall(
        prompt=_coerce_prompt(body["prompt"], "prompt", encode),
        echo=echo,
        chat=False,
        **_common_fields(body),
    )


@dataclasses.dataclass
class ChatTemplate:
    """Token-id chat template: per-role prefix/suffix ids framing each
    message, plus the generation prompt appended after the last message.

    The default is the empty template — plain concatenation of message
    content — which is exactly right for the token-id-native tests/benches
    (the ids ARE the conversation).  Deployments with a real tokenizer pass
    the ids their model's chat format uses (e.g. ``<|im_start|>`` blocks).
    """

    role_prefix: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict
    )
    role_suffix: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict
    )
    generation_prefix: Tuple[int, ...] = ()

    def render(self, messages: Sequence[Dict[str, Any]],
               encode: Optional[Callable[[str], Sequence[int]]]) -> List[int]:
        ids: List[int] = []
        for i, msg in enumerate(messages):
            if not isinstance(msg, dict):
                raise ValidationError(
                    "messages must be objects with role and content",
                    param=f"messages[{i}]",
                )
            role = msg.get("role")
            if role not in ("system", "user", "assistant", "tool"):
                raise ValidationError(
                    f"unknown role {role!r}", param=f"messages[{i}].role"
                )
            if "content" not in msg:
                raise ValidationError(
                    "content is required", param=f"messages[{i}].content"
                )
            ids.extend(self.role_prefix.get(role, ()))
            ids.extend(
                _coerce_prompt(msg["content"], f"messages[{i}].content", encode)
            )
            ids.extend(self.role_suffix.get(role, ()))
        ids.extend(self.generation_prefix)
        return ids


def parse_chat_request(
    body: Any,
    template: Optional[ChatTemplate] = None,
    encode: Optional[Callable[[str], Sequence[int]]] = None,
) -> CompletionCall:
    """Validate a ``POST /v1/chat/completions`` body: messages are rendered
    through the chat template into one token-id prompt."""
    body = _require_dict(body)
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise ValidationError(
            "messages must be a non-empty array", param="messages"
        )
    template = template if template is not None else ChatTemplate()
    return CompletionCall(
        prompt=template.render(messages, encode),
        echo=False,
        chat=True,
        **_common_fields(body),
    )


# --------------------------------------------------------------- responses
def _finish_reason(tokens: Sequence[int], call: CompletionCall,
                   eos_token_id: Optional[int], cancelled: bool) -> str:
    if cancelled:
        return "cancelled"
    if (eos_token_id is not None and tokens
            and int(tokens[-1]) == int(eos_token_id)):
        return "stop"
    return "length"


def completion_response(
    call: CompletionCall,
    request_id: str,
    created: int,
    model: str,
    tokens: Sequence[int],
    eos_token_id: Optional[int] = None,
    cancelled: bool = False,
    decode: Optional[Callable[[Sequence[int]], str]] = None,
) -> Dict[str, Any]:
    """The full (non-streaming) response object, completions or chat dialect."""
    tokens = [int(t) for t in tokens]
    text = decode(tokens) if decode is not None else ""
    reason = _finish_reason(tokens, call, eos_token_id, cancelled)
    if call.chat:
        choice: Dict[str, Any] = {
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "token_ids": tokens,
            "finish_reason": reason,
        }
        object_type = "chat.completion"
    else:
        out_tokens = list(call.prompt) + tokens if call.echo else tokens
        choice = {
            "index": 0,
            "text": decode(out_tokens) if decode is not None else "",
            "token_ids": out_tokens,
            "finish_reason": reason,
        }
        object_type = "text_completion"
    return {
        "id": request_id,
        "object": object_type,
        "created": created,
        "model": model,
        "choices": [choice],
        "usage": {
            "prompt_tokens": len(call.prompt),
            "completion_tokens": len(tokens),
            "total_tokens": len(call.prompt) + len(tokens),
        },
    }


def completion_chunk(
    call: CompletionCall,
    request_id: str,
    created: int,
    model: str,
    token: Optional[int],
    first: bool,
    finish_reason: Optional[str] = None,
    decode: Optional[Callable[[Sequence[int]], str]] = None,
) -> Dict[str, Any]:
    """One streaming chunk.  ``token=None`` with a ``finish_reason`` is the
    final summary chunk (no content) that precedes ``data: [DONE]``."""
    tokens = [] if token is None else [int(token)]
    text = decode(tokens) if decode is not None and tokens else ""
    if call.chat:
        delta: Dict[str, Any] = {}
        if first:
            delta["role"] = "assistant"
        if tokens:
            delta["content"] = text
        choice: Dict[str, Any] = {
            "index": 0,
            "delta": delta,
            "token_ids": tokens,
            "finish_reason": finish_reason,
        }
        object_type = "chat.completion.chunk"
    else:
        choice = {
            "index": 0,
            "text": text,
            "token_ids": tokens,
            "finish_reason": finish_reason,
        }
        object_type = "text_completion"
    return {
        "id": request_id,
        "object": object_type,
        "created": created,
        "model": model,
        "choices": [choice],
    }


def sse_frame(payload: Dict[str, Any]) -> str:
    """One ``data:`` SSE event (compact JSON, double-newline terminated)."""
    return f"data: {json.dumps(payload, separators=(',', ':'))}\n\n"


def error_body(message: str, err_type: str, code: Optional[str] = None,
               param: Optional[str] = None) -> Dict[str, Any]:
    """OpenAI error envelope (``{"error": {...}}``)."""
    return {
        "error": {
            "message": message,
            "type": err_type,
            "param": param,
            "code": code,
        }
    }

"""Live KV page migration and request-state marshalling between replicas.

Replicas stop being silos here.  The existing recovery path
(:func:`export_inflight` / :func:`adopt`, relocated from ``engine.py``)
moves a request between engines by *throwing the KV away* and re-prefilling
``prompt + generated`` on the adopter — token-exact under greedy, re-seeded
under sampling, and O(prefix) device work every time.  :class:`PageMigrator`
moves the KV itself: a lane's live pages, block-table row, per-page quant
scales, pending token, and RNG stream travel to the destination, which
installs them into its own allocator and continues **bit-identically** —
greedy and sampled alike — at O(pages) copy cost independent of how much
compute produced them.

Two arms, chosen per engine pair (``mode="auto"``):

- **d2d** — both pools live on the same platform with the same sharding
  layout (single-device twins, or tp slices of one mesh): the D2H-shaped
  gather's device outputs are handed straight to the destination's
  scatter-install via ``jax.device_put``, never touching the host.
- **bounce** — anything else (cross-process, cross-platform, mismatched
  meshes): the gather lands in pinned host memory through the one
  sanctioned blocking ``fetch`` and re-uploads with the destination pool's
  placement, exactly like a hierarchical-cache promotion.

Executable discipline: one gather (``serve/migrate_extract``) and one
scatter-install (``serve/migrate_install``) per engine, built lazily on
first migration from the hierarchical cache's factories
(:func:`~.pool.make_spill_extract` / :func:`~.pool.make_promote_install`)
at the pool's full ``pages_per_lane`` width — a lane's live page-id list is
padded with ``NULL_PAGE`` up to that fixed width
(:func:`~.pool.pad_page_ids`), so per-lane page counts never leak into jit
signatures.  On the destination the install enqueues BEHIND any in-flight
decode window per the ``Readback``/``_stale_handles`` depth-1 discipline,
so migration overlaps the destination's decode.  The source drains its own
pipeline first — the migration barrier that makes its host mirrors
(pending token, lane length) and the device-carried RNG row authoritative —
then its other lanes resume overlapped while the gather executes.

Failure semantics: every refusal raises :class:`MigrationError` *before*
any engine state mutates.  ``retriable=True`` (destination slot/page
pressure) means try again next step; ``retriable=False`` (geometry
mismatch, an injected ``migrate_d2d``/``migrate_bounce`` fault) means fall
back to the export/adopt replay path — the source lane is untouched and
the source replica stays healthy.  See ``docs/usage/serving.md``
("Disaggregated prefill/decode") and ``docs/usage/fault_tolerance.md``.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import numpy as np

from ..telemetry import (
    MetricsRegistry,
    RecompileWatchdog,
    get_flight_recorder,
    get_registry,
    get_tracer,
)
from . import faults
from .errors import AdmissionError
from .pool import (
    make_promote_install,
    make_spill_extract,
    pad_page_ids,
    plan_chunks,
)
from .readback import fetch
from .scheduler import Request, RequestState

__all__ = [
    "MigrationError",
    "PageMigrator",
    "adopt",
    "export_inflight",
    "migration_executables",
]

# Migration wall time spans ~10 us (single-page d2d handoff on one chip) to
# ~100 s (a full lane bounced over a congested host link): 20 x2 buckets
# from 10 us in ms units cover it.
_MIGRATE_MS_BUCKETS = tuple(1e-2 * 2.0**i for i in range(20))


class MigrationError(RuntimeError):
    """A migration that could not run; nothing was mutated on either engine.

    ``retriable=True`` — transient destination pressure (no free slot, page
    pool dry): the lane stays where it is and the caller may try again next
    step.  ``retriable=False`` — the pair can never migrate this lane
    (geometry mismatch, lane finished, injected fault): the caller should
    fall back to the export/adopt re-prefill replay path.
    """

    def __init__(self, reason: str, retriable: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.retriable = retriable


# ---------------------------------------------------------------- marshalling
def export_inflight(engine) -> List[Request]:
    """Snapshot every request ``engine`` still owes an answer — running
    lanes, the mid-prefill request, and the waiting queue — detached from
    the engine's state and ready for :func:`adopt` on a survivor.

    Each RUNNING lane exports as ``prompt + generated-so-far`` via
    ``Request.prefill_tokens`` (the preempt-and-replay machinery): replay
    re-prefills the effective prompt and generation resumes exactly where
    it stopped, token-exact under greedy.  Tokens already streamed are
    never re-emitted.  Prefix-cache pins on THIS engine are released and
    the per-engine prefill plan cleared — the adopting engine re-plans
    against its own buckets and cache.  Device state is NOT touched (the
    engine may be poisoned mid-window); ``revive()`` handles teardown.
    Returns requests in rid order — original FCFS submit order."""
    out: List[Request] = []
    for s in range(engine.num_slots):
        req = engine._slot_req[s]
        if req is not None and req.state is RequestState.RUNNING:
            out.append(req)
    for hd in (engine._prev_handle, engine._inflight):
        if hd is None:
            continue
        # a pre-freed lane's request left _slot_req when its final window
        # dispatched but is still owed that window's tokens from the
        # drain this engine will never run — it lives only on the handle
        for s in hd.prefreed:
            req = hd.reqs[s]
            if (req is not None and req.state is RequestState.RUNNING
                    and not any(req is r for r in out)):
                out.append(req)
    out.extend(engine.scheduler.take_prefills())
    out.extend(engine.scheduler.queue)
    engine.scheduler.queue.clear()
    for req in out:
        if engine.prefix_cache is not None and req.cache_nodes:
            engine.prefix_cache.release(req.cache_nodes)
        req.cache_nodes = []
        req.cached_chunks = 0
        req.cache_chain_broken = False
        req.chunks = ()
        req.next_chunk = 0
        req.slot = None
        req.state = RequestState.QUEUED
    out.sort(key=lambda r: r.rid)
    for req in out:
        if req.trace is not None:
            req.trace.annotate("export_inflight", rid=req.rid,
                               generated=len(req.tokens))
    engine.recorder.record(
        "serve/export_inflight", count=len(out), step=engine._step_count,
    )
    return out


def adopt(engine, request: Request) -> Request:
    """Admit a request exported from a dead replica, at the FRONT of
    ``engine``'s queue (it already waited its FCFS turn once).  The
    effective prompt is ``prefill_tokens`` — greedy lanes replay
    token-exact; sampled lanes resume on a re-seeded stream (the fresh rid
    folds into this engine's base rng at install), distribution-correct
    but not sample-exact.  Raises a non-retriable :class:`AdmissionError`
    when the effective prompt cannot fit this engine's geometry; never
    refused for queue depth — survivors absorb a dead peer's load."""
    eff = len(request.prefill_tokens)
    if eff > engine.max_prompt_len:
        raise AdmissionError(
            f"replayed prompt+generated length {eff} > max_prompt_len "
            f"{engine.max_prompt_len}",
            queue_depth=engine.scheduler.queue_depth, retriable=False,
        )
    span = max(engine.window, engine._spec_span)
    remaining = max(request.config.max_new_tokens - len(request.tokens), 1)
    if eff + remaining + span > engine.max_len:
        raise AdmissionError(
            f"replayed length {eff} + remaining {remaining} + span {span} "
            f"exceeds slot capacity {engine.max_len}",
            queue_depth=engine.scheduler.queue_depth, retriable=False,
        )
    padded = sum(b for b, _ in plan_chunks(eff, engine.buckets))
    cap = engine.max_len if engine.paged else engine.max_prompt_len
    if padded > cap:
        raise AdmissionError(
            f"replayed length {eff} pads to {padded} prefill tokens under "
            f"buckets {engine.buckets}, exceeding capacity {cap}",
            queue_depth=engine.scheduler.queue_depth, retriable=False,
        )
    old_rid = request.rid
    request.rid = engine._next_rid
    engine._next_rid += 1
    if request.trace is not None:
        # the SAME trace crosses replicas: close the ejection-to-adoption
        # interval as a failover phase and re-index under the new rid —
        # the waterfall continues rather than restarting
        request.trace.phase(
            "failover", from_engine=request.trace.engine,
            to_engine=engine.engine_id, old_rid=old_rid, rid=request.rid,
            generated=len(request.tokens),
        )
        engine.reqtrace.rebind(request.trace, engine.engine_id, request.rid)
    engine.scheduler.requeue(request)
    engine._bump("requests_submitted")
    engine._bump("requests_replayed")
    # the tenant label rides the Request across the failover — the
    # adopting engine keeps the caller's books exact
    engine._bump_tenant(request.tenant, "requests_submitted")
    engine._bump_tenant(request.tenant, "requests_replayed")
    if request.deadline_s is not None:
        engine._has_deadlines = True
    engine.recorder.record(
        "serve/adopt", rid=request.rid, old_rid=old_rid,
        effective_len=eff, generated=len(request.tokens),
    )
    return request


# ----------------------------------------------------------------- executables
def migration_executables(engine):
    """The engine's ``(extract, install)`` migration pair, built lazily on
    first use and cached — ``serve/migrate_extract`` (D2H-shaped page
    gather) and ``serve/migrate_install`` (donated H2D-shaped scatter), one
    of each per engine at the pool's full ``pages_per_lane`` width.  Lazy
    because most engines never migrate: the compiled budget only grows on
    the replicas that actually participate, and by exactly this documented
    set (``compiled_executable_counts``)."""
    if engine._migrate_extract is None:
        npages = engine.kv.pages_per_lane
        engine._migrate_extract = RecompileWatchdog(
            make_spill_extract(npages, shardings=engine._shardings),
            name="serve/migrate_extract", budget=1, registry=engine.metrics,
        )
        engine._migrate_install = RecompileWatchdog(
            make_promote_install(npages, shardings=engine._shardings),
            name="serve/migrate_install", budget=1, registry=engine.metrics,
        )
    return engine._migrate_extract, engine._migrate_install


# ------------------------------------------------------------------- migrator
class PageMigrator:
    """Move live decode lanes between :class:`ServingEngine` replicas.

    Stateless apart from telemetry: every :meth:`migrate` call is one
    complete lane move (or a clean :class:`MigrationError` refusal), so one
    migrator instance can serve a whole router.  Pass the same private
    ``registry`` the engines use to keep bench arms isolated."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.metrics = registry if registry is not None else get_registry()
        self.recorder = get_flight_recorder().tagged(engine="migrator")
        self.tracer = get_tracer()
        self._migrations = self.metrics.counter(
            "serve/migrations_total",
            help="live lanes moved between replicas with their KV pages "
                 "(d2d and host-bounce arms both); replay fallbacks do not "
                 "count — they bump serve/requests_replayed_total instead",
        )
        self._bytes = self.metrics.counter(
            "serve/migrate_bytes_total",
            help="KV payload bytes migrated between replicas (live pages + "
                 "quant scales, at storage dtype) — the crossover input of "
                 "the migrate-vs-replay A/B",
        )
        self._handoffs = self.metrics.counter(
            "serve/prefill_handoffs_total",
            help="lanes handed off prefill-role -> decode-role right after "
                 "their last prefill chunk landed (disaggregated policy); a "
                 "subset of serve/migrations_total",
        )
        self._ms_hist = self.metrics.histogram(
            "serve/migrate_ms",
            buckets=_MIGRATE_MS_BUCKETS,
            help="wall time per lane migration, source drain barrier through "
                 "destination lane install dispatch (the install itself "
                 "overlaps the destination's decode)",
        )

    # ------------------------------------------------------------ feasibility
    @staticmethod
    def compatible(src, dst) -> Optional[str]:
        """``None`` when lanes can migrate ``src -> dst``; else the blocking
        reason.  The pools must agree on page geometry and storage dtype so
        the gathered chunk feeds the destination's install bit-for-bit."""
        if src is dst:
            return "source and destination are the same engine"
        if not (src.paged and dst.paged):
            return "both engines must run the paged KV pool"
        if src.kv.page_size != dst.kv.page_size:
            return (f"page_size differs ({src.kv.page_size} vs "
                    f"{dst.kv.page_size})")
        if src.kv.pages_per_lane != dst.kv.pages_per_lane:
            return (f"pages_per_lane differs ({src.kv.pages_per_lane} vs "
                    f"{dst.kv.pages_per_lane})")
        if src.kv.storage_dtype != dst.kv.storage_dtype:
            return (f"KV storage dtype differs ({src.kv.storage_dtype} vs "
                    f"{dst.kv.storage_dtype})")
        if src.kv.pages_k.shape[0] != dst.kv.pages_k.shape[0] \
                or src.kv.pages_k.shape[2:] != dst.kv.pages_k.shape[2:]:
            return "KV pool geometry (layers/heads/head_dim) differs"
        return None

    @staticmethod
    def resolve_mode(src, dst) -> str:
        """``"d2d"`` when the gather's outputs can feed the destination
        install without a host round trip — same platform AND the same
        sharding structure (both unsharded, or both meshes, where
        ``device_put`` re-lays the chunk onto the destination mesh) —
        else ``"bounce"``."""
        sdev = next(iter(src.kv.pages_k.devices()))
        ddev = next(iter(dst.kv.pages_k.devices()))
        if sdev.platform != ddev.platform:
            return "bounce"
        if (src._shardings is None) != (dst._shardings is None):
            return "bounce"
        return "d2d"

    # -------------------------------------------------------------- migration
    def migrate(self, src, dst, slot: int, mode: str = "auto",
                reason: str = "rebalance") -> Request:
        """Move the RUNNING lane in ``src`` slot ``slot`` to ``dst``,
        KV pages included, and return its request — which continues on the
        destination bit-identically (greedy AND sampled: the live RNG row
        travels, unlike :func:`adopt`'s re-seed).  Raises
        :class:`MigrationError` with nothing mutated otherwise."""
        req = src._slot_req[slot]
        if req is None or req.state is not RequestState.RUNNING \
                or not src._active[slot]:
            raise MigrationError(f"no running lane in slot {slot}")
        why = self.compatible(src, dst)
        if why is not None:
            raise MigrationError(why)
        if mode == "auto":
            mode = self.resolve_mode(src, dst)
        if mode not in ("d2d", "bounce"):
            raise MigrationError(f"unknown migration mode {mode!r}")
        if dst._next_free_slot() is None:
            raise MigrationError("destination has no free slot",
                                 retriable=True)
        t0 = time.perf_counter()
        # the source-side migration barrier: drain the depth-1 pipeline so
        # the host mirrors (pending token, lane length) are current and the
        # device-carried RNG row is the lane's live stream.  The source's
        # other lanes resume overlapped decode the very next step.
        src._drain_inflight()
        if not src._active[slot] or src._slot_req[slot] is not req:
            raise MigrationError("lane finished while draining the source")
        lane_len = int(src._lane_len[slot])
        span = max(dst.window, dst._spec_span)
        remaining = max(req.config.max_new_tokens - len(req.tokens), 1)
        if lane_len + 1 + remaining + span > dst.max_len:
            raise MigrationError(
                f"lane length {lane_len} + remaining {remaining} + span "
                f"{span} exceeds destination capacity {dst.max_len}")
        page_ids = src.kv.lane_pages(slot)
        npages = len(page_ids)
        pending = int(src._pending_tok[slot])
        if src._lane_device is not None:
            # the sampling stream rides the device between windows; with
            # the pipeline drained this sanctioned fetch returns without a
            # real wait, and the row transfers the stream bit-exactly
            rng = np.asarray(fetch(src._lane_device[-1])[slot], np.uint32)
        else:
            rng = np.asarray(src._rngs[slot], np.uint32)
        point = f"migrate_{mode}"
        if faults.ACTIVE is not None and faults.ACTIVE.fire(point):
            self.recorder.record(
                "serve/fault", point=point, rid=req.rid, slot=int(slot),
                src=src.engine_id, dst=dst.engine_id,
            )
            raise MigrationError(f"injected {point} fault")
        new_ids = dst.kv.allocator.alloc(npages)
        if new_ids is None:
            if dst._reclaim_pages(npages, allow_preempt=False):
                new_ids = dst.kv.allocator.alloc(npages)
            if new_ids is None:
                raise MigrationError("destination page pool exhausted",
                                     retriable=True)
        extract, _ = migration_executables(src)
        _, install = migration_executables(dst)
        behind = dst._inflight is not None or dst._prev_handle is not None
        skv, dkv = src.kv, dst.kv
        with self.tracer.span("serve/migrate", mode=mode, pages=npages,
                              behind_window=behind):
            handles = extract(
                skv.pages_k, skv.pages_v, skv.k_scales, skv.v_scales,
                src._put(pad_page_ids(page_ids, skv.pages_per_lane)),
            )
            if mode == "bounce":
                # the pinned-host bounce: the one sanctioned fetch, waiting
                # only on the gather just dispatched (source pipeline is
                # empty), then re-uploaded with the destination placement
                ck, cv, cks, cvs = fetch(*handles)
                ck, cv = dst._put_kv_chunk(ck), dst._put_kv_chunk(cv)
                cks = dst._put_scale_chunk(cks)
                cvs = dst._put_scale_chunk(cvs)
            else:
                ck, cv, cks, cvs = handles
                if dst._shardings is not None:
                    # same platform, different mesh handles: re-lay the
                    # gathered chunk onto the destination's sharding —
                    # device-to-device, never through the host
                    ck = jax.device_put(ck, dst._shardings.kv)
                    cv = jax.device_put(cv, dst._shardings.kv)
                    cks = jax.device_put(cks, dst._shardings.scales)
                    cvs = jax.device_put(cvs, dst._shardings.scales)
            # the install donates the destination pool handles, which any
            # in-flight destination window still consumes: park them until
            # its drain, per the depth-1 discipline (_stale_handles)
            dst._stale_handles += [dkv.pages_k, dkv.pages_v,
                                   dkv.k_scales, dkv.v_scales]
            (dkv.pages_k, dkv.pages_v, dkv.k_scales,
             dkv.v_scales) = install(
                dkv.pages_k, dkv.pages_v, dkv.k_scales, dkv.v_scales,
                ck, cv, cks, cvs,
                dst._put(pad_page_ids(new_ids, dkv.pages_per_lane)),
            )
        # source teardown: the lane's page refs drop now — the device runs
        # in dispatch order, so any later source prefill recycling these
        # pages is ordered BEHIND the gather (the spill discipline)
        src._retire_lane(slot)
        dst_slot = self._install_lane(dst, req, new_ids, lane_len, pending,
                                      rng)
        old_rid = req.rid
        req.rid = dst._next_rid
        dst._next_rid += 1
        req.slot = dst_slot
        if req.trace is not None:
            # the SAME trace crosses replicas, like failover — the
            # waterfall gains a migrate phase instead of restarting
            req.trace.phase(
                "migrate", from_engine=src.engine_id,
                to_engine=dst.engine_id, old_rid=old_rid, rid=req.rid,
                mode=mode, pages=npages, generated=len(req.tokens),
            )
            dst.reqtrace.rebind(req.trace, dst.engine_id, req.rid)
        self._reestablish_prefix(dst, req, new_ids, lane_len)
        nbytes = skv.chunk_bytes(npages)
        self._migrations.inc()
        self._bytes.inc(nbytes)
        self._ms_hist.observe((time.perf_counter() - t0) * 1e3)
        self.recorder.record(
            "serve/migrate", rid=req.rid, old_rid=old_rid, mode=mode,
            src=src.engine_id, dst=dst.engine_id, slot=int(slot),
            dst_slot=dst_slot, pages=npages, bytes=nbytes,
            behind_window=behind, reason=reason,
        )
        return req

    def handoff(self, src, dst, slot: int, mode: str = "auto") -> Request:
        """Prefill handoff: migrate a freshly prefilled lane off a
        prefill-role replica onto a decode-role one — the disaggregated
        steady state.  Same mechanics as :meth:`migrate`; counted
        separately because handoffs are the *policy* (every lane, once)
        where rebalance migrations are the *exception* (hot spots only)."""
        req = self.migrate(src, dst, slot, mode=mode,
                           reason="prefill_handoff")
        self._handoffs.inc()
        self.recorder.record(
            "serve/prefill_handoff", rid=req.rid, src=src.engine_id,
            dst=dst.engine_id, generated=len(req.tokens),
        )
        return req

    # -------------------------------------------------------------- internals
    @staticmethod
    def _install_lane(dst, req: Request, new_ids: List[int], lane_len: int,
                      pending: int, rng: np.ndarray) -> int:
        """Wire the migrated lane into ``dst`` — ``_install``'s twin minus
        the re-prefill: the block-table row points at the freshly installed
        pages, the host mirrors take the TRANSFERRED lane length, pending
        token, and RNG row (not a re-fold of the base rng — that is what
        makes continuation bit-identical where :func:`adopt` is only
        distribution-correct), and the one-slot lane-install scatter edits
        the device mirror behind any in-flight window without a sync."""
        s = dst._next_free_slot()
        dst.kv.lane_append_owned(s, new_ids)
        gen = req.config
        eos_v = -1 if gen.eos_token_id is None else gen.eos_token_id
        top_k_v = 0 if gen.top_k is None else gen.top_k
        top_p_v = 1.0 if gen.top_p is None else gen.top_p
        if dst._lane_device is not None:
            ld = dst._lane_device
            # the replaced handles are inputs of the scatter (and outputs
            # of any in-flight window): park them until the next drain so
            # their destructors never wait on pending device work
            dst._stale_handles += [ld[0], ld[1], ld[2], ld[3], ld[4],
                                   ld[5], ld[6], ld[8]]
            (ld[0], ld[1], ld[2], ld[3], ld[4], ld[5], ld[6],
             ld[8]) = dst._lane_install(
                ld[0], ld[1], ld[2], ld[3], ld[4], ld[5], ld[6], ld[8],
                dst._put(np.int32(s)), dst._put(np.int32(pending)),
                dst._put(np.int32(eos_v)),
                dst._put(np.bool_(gen.do_sample)),
                dst._put(np.float32(gen.temperature)),
                dst._put(np.int32(top_k_v)), dst._put(np.float32(top_p_v)),
                dst._put(rng),
            )
        dst._pending_tok[s] = pending
        dst._active[s] = True
        dst._eos[s] = eos_v
        dst._do_sample[s] = gen.do_sample
        dst._temperature[s] = gen.temperature
        dst._top_k[s] = top_k_v
        dst._top_p[s] = top_p_v
        dst._rngs[s] = rng
        dst._lane_len[s] = lane_len
        if dst._draft_window is not None:
            # seed the draft context from the full sequence tail: its last
            # token IS the lane's pending token, the tree-root invariant
            dst._draft_window.begin(s, req.output_ids)
        if dst._slot_ever_used[s]:
            dst._bump("slots_reused")
        dst._slot_ever_used[s] = True
        dst._slot_req[s] = req
        dst._reserved_slots.discard(s)
        if req.deadline_s is not None:
            dst._has_deadlines = True
        req.state = RequestState.RUNNING
        return s

    @staticmethod
    def _reestablish_prefix(dst, req: Request, new_ids: List[int],
                            lane_len: int) -> None:
        """Re-establish prefix-cache pins on the destination: the migrated
        prompt chunks alias the lane's NEW physical pages zero-copy, each
        full chunk inserted with its own allocator reference exactly like
        ``_populate_cache`` — so future destination requests sharing the
        prefix hit instead of re-prefilling.  (The source side needs no
        step: ``_retire_lane`` dropped the lane's refs, while the source
        cache's own nodes — and their refs — stay resident and servable.)
        Chunks whose pages reach the lane's write frontier are skipped:
        decode keeps writing there, and a cached page must be immutable."""
        if dst.prefix_cache is None or not req.cache_prefix:
            return
        ptoks = np.asarray(req.prompt, np.int32).reshape(-1)
        page = dst.page_size
        frontier = (lane_len // page) * page
        parent = None
        start = 0
        for bucket, valid in plan_chunks(len(ptoks), dst.buckets):
            if valid != bucket or start + bucket > frontier:
                break
            npg = bucket // page
            first = start // page
            ids = list(new_ids[first:first + npg])
            node = dst.prefix_cache.insert_pages(
                parent, ptoks[start:start + bucket], ids,
                nbytes=dst.kv.chunk_bytes(npg),
            )
            if node is None:
                break
            if node.pages == tuple(ids):
                # a NEW node: the cache holds its own reference per page
                # (dropped by _on_prefix_evict); a deduped re-insert keeps
                # the resident node's pages and refs untouched
                dst.kv.allocator.ref(ids)
            parent = node
            start += bucket

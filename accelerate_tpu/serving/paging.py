"""Paged KV allocator: one physical page pool behind every lane AND the prefix cache.

The slot pool gives each lane a contiguous ``max_len`` KV slab — worst-case
memory reserved up front, so mixed-length traffic caps concurrency at
``HBM / max_len`` lanes even when most requests are short.  vLLM's
PagedAttention breaks that: KV lives in fixed-size *pages*, a lane owns a
block table mapping logical positions to physical pages, pages are allocated
as the lane grows, and refcounting lets many lanes alias the same physical
page.  The TPU-native translation here keeps every device program fixed-shape
(:mod:`.pool` grows exactly one gather/scatter executable per existing shape)
while all allocation, refcounting, and copy-on-write stay host-side numpy:

* :class:`PageAllocator` — the refcounted free list.  Page id ``0`` is the
  reserved **null page**: freed or frozen lanes' garbage writes land there
  (their block-table rows are reset to null), so no compiled program ever
  needs a "has pages?" branch.
* :class:`PagedKVPool` — the device-resident page arrays
  ``[L, num_pages, page_size, Hkv, Dh]`` plus per-lane block tables
  (host ``[num_slots, pages_per_lane]`` int32, uploaded per cycle — a few KB).
  ``pages_per_lane * page_size == max_len`` exactly: the gathered per-lane
  view has the *same* width as the legacy slab, so paged decode runs the
  bitwise-identical attention program (a wider view would change the softmax
  reduction shape and with it the last-ulp rounding — measured, not
  hypothetical).

Sharing model: the prefix cache pins pages (one allocator ref per caching
node), every lane aliasing a cached prefix takes its own ref per page, and a
page returns to the free list only at refcount zero.  Copy-on-write happens in
exactly one place — the page holding a lane's first decode-write position
(``prompt_len - 1``) when that page is shared — everything a lane writes after
that lands in pages it owns alone.

Telemetry (documented in ``docs/usage/observability.md``):
``serve/kv_pages_in_use``, ``serve/kv_pages_free`` and
``serve/kv_bytes_shared`` published by :meth:`PagedKVPool.publish_gauges`;
``serve/preemptions_total`` is counted by the engine when page pressure forces
a lane to release its pages and requeue for replay.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..telemetry import MetricsRegistry, get_registry

#: Reserved garbage-sink page id. Never allocated, never freed; block-table
#: rows of inactive lanes point here so frozen-lane writes have a harmless
#: destination and gathers read finite (zero-initialised) values.
NULL_PAGE = 0


class PageAllocator:
    """Refcounted free-list allocator over ``num_pages`` physical pages.

    Page 0 is the permanently-pinned null page (:data:`NULL_PAGE`).  The free
    list hands out ascending ids deterministically — allocation order is part
    of the engine's reproducibility story (same workload, same tables).
    """

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        if self.num_pages < 2:
            raise ValueError(f"need at least 2 pages (null + 1), got {num_pages}")
        self.refs = np.zeros(self.num_pages, np.int64)
        self.refs[NULL_PAGE] = 1  # never allocatable, never freed
        # pop() takes from the tail: ids come out ascending (1, 2, 3, ...)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        """Allocated pages (null excluded)."""
        return self.num_pages - 1 - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` pages (refcount 1 each) or ``None`` — all-or-nothing, so
        a partial grab under pressure never leaks pages."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self.refs[ids] += 1
        return ids

    def ref(self, ids: Sequence[int]) -> None:
        """One more reference on each of ``ids`` (aliasing a shared prefix)."""
        for p in ids:
            if self.refs[p] <= 0:
                raise RuntimeError(f"ref() on unallocated page {p}")
            self.refs[p] += 1

    def deref(self, ids: Sequence[int]) -> int:
        """Drop one reference per page; pages hitting zero return to the free
        list.  Returns how many pages were actually freed."""
        freed = 0
        for p in ids:
            if p == NULL_PAGE:
                continue
            self.refs[p] -= 1
            if self.refs[p] < 0:
                raise RuntimeError(f"page {p} refcount underflow")
            if self.refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    def shared_extra_refs(self) -> int:
        """Σ max(refs - 1, 0) over real pages: how many page-copies sharing is
        saving right now (the ``serve/kv_bytes_shared`` numerator)."""
        return int(np.maximum(self.refs[1:] - 1, 0).sum())


class PagedKVPool:
    """Device page arrays + host block tables for ``num_slots`` lanes.

    Parameters
    ----------
    config: the model's ``TransformerConfig`` (layer/head/dim geometry; pages
        use ``config.dtype`` exactly like the legacy slab pool).
    num_slots: lane count (the decode batch dimension).
    max_len: per-lane logical KV capacity.  Must be a multiple of
        ``page_size`` — the gathered view is exactly this wide, which is what
        makes paged decode bitwise-identical to the contiguous slab.
    page_size: tokens per page (the prefix-cache chunk granularity must be a
        multiple of it; the engine uses gcd(prefill buckets) by default).
    num_pages: physical pages including the null page.  Must be at least
        ``max_len // page_size + 1`` so a single lane can always run to its
        capacity even with nothing else to reclaim.
    kv_dtype: page storage format — ``None`` keeps ``config.dtype`` (the
        token-identical path), ``"bf16"`` stores bf16, ``"int8"`` / ``"fp8"``
        store quantized pages with one f32 dequantization scale per
        (layer, page, kv-head) written at scatter time
        (:func:`accelerate_tpu.ops.paged_attention.paged_quantized_insert`).
        Scale arrays exist for every format (ones when direct-store) so the
        compiled window signature does not fork on the dtype knob.
    """

    def __init__(self, config, num_slots: int, max_len: int, page_size: int,
                 num_pages: int, registry: Optional[MetricsRegistry] = None,
                 kv_dtype: Optional[str] = None, mesh=None,
                 tp_axis: str = "tp"):
        if max_len % page_size != 0:
            raise ValueError(
                f"max_len {max_len} must be a multiple of page_size {page_size} "
                f"(the gathered view must match the legacy slab width exactly)"
            )
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.num_slots = int(num_slots)
        self.pages_per_lane = self.max_len // self.page_size
        self.num_pages = int(num_pages)
        if self.num_pages < self.pages_per_lane + 1:
            raise ValueError(
                f"num_pages {num_pages} cannot hold one full lane "
                f"({self.pages_per_lane} pages) plus the null page"
            )
        cfg = config
        from ..ops.paged_attention import kv_qmax, kv_storage_dtype

        self.kv_dtype = kv_dtype
        self.storage_dtype = kv_storage_dtype(kv_dtype, cfg.dtype)
        self.quantized = kv_qmax(self.storage_dtype) is not None
        self.mesh = mesh
        self.tp_axis = tp_axis
        if mesh is not None:
            from ..parallel.mesh import mesh_axis_size

            self.tp_degree = mesh_axis_size(mesh, tp_axis)
        else:
            self.tp_degree = 1
        if self.tp_degree > 1 and cfg.num_kv_heads % self.tp_degree != 0:
            raise ValueError(
                f"num_kv_heads {cfg.num_kv_heads} must divide evenly over "
                f"tp={self.tp_degree} to shard the page pool on the head axis"
            )
        shape = (cfg.num_layers, self.num_pages, self.page_size,
                 cfg.num_kv_heads, cfg.resolved_head_dim)
        scale_shape = (cfg.num_layers, self.num_pages, cfg.num_kv_heads)
        if mesh is not None:
            # head-axis NamedSharding: each device holds Hkv/tp heads of every
            # page.  Block tables / refcounts stay host-side and whole.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            ax = tp_axis if self.tp_degree > 1 else None
            kv_sh = NamedSharding(mesh, PartitionSpec(None, None, None, ax, None))
            sc_sh = NamedSharding(mesh, PartitionSpec(None, None, ax))
            self.pages_k = jax.device_put(
                jnp.zeros(shape, self.storage_dtype), kv_sh
            )
            self.pages_v = jax.device_put(
                jnp.zeros(shape, self.storage_dtype), kv_sh
            )
            self.k_scales = jax.device_put(jnp.ones(scale_shape, jnp.float32), sc_sh)
            self.v_scales = jax.device_put(jnp.ones(scale_shape, jnp.float32), sc_sh)
        else:
            self.pages_k = jnp.zeros(shape, self.storage_dtype)
            self.pages_v = jnp.zeros(shape, self.storage_dtype)
            # per-(layer, page, kv-head) dequantization scales; ones (a no-op
            # multiply the direct-store windows never read) when not quantized
            self.k_scales = jnp.ones(scale_shape, jnp.float32)
            self.v_scales = jnp.ones(scale_shape, jnp.float32)
        #: bytes of k+v one page holds, scales included — the sharing/HBM
        #: accounting unit
        itemsize = jnp.zeros((), self.storage_dtype).itemsize
        self.page_kv_bytes = 2 * int(
            np.prod(shape[2:]) * cfg.num_layers * itemsize
            + cfg.num_layers * cfg.num_kv_heads * 4
        )
        self.allocator = PageAllocator(self.num_pages)
        # host block tables: row s maps lane s's logical page slots to
        # physical ids; NULL_PAGE marks unmapped (garbage-sink) entries
        self.tables = np.zeros((self.num_slots, self.pages_per_lane), np.int32)
        self.lane_npages = np.zeros(self.num_slots, np.int32)

        registry = registry if registry is not None else get_registry()
        self._in_use_gauge = registry.gauge(
            "serve/kv_pages_in_use", help="allocated KV pages (null page excluded)"
        )
        self._free_gauge = registry.gauge(
            "serve/kv_pages_free", help="KV pages on the free list"
        )
        self._shared_gauge = registry.gauge(
            "serve/kv_bytes_shared",
            help="KV bytes extra references alias instead of copying "
                 "(sum of (refs-1) * page_bytes over shared pages)",
        )
        registry.gauge(
            "serve/kv_bytes_per_token",
            help="per-device KV HBM one token costs across all layers at the "
                 "pool's storage dtype, amortized per-page scales included "
                 "(the head axis divides exactly over tp when sharded)",
        ).set(self.page_kv_bytes / self.page_size / self.tp_degree)
        self.publish_gauges()

    # -------------------------------------------------------------- lane ops
    def lane_append_owned(self, slot: int, ids: Sequence[int]) -> None:
        """Map freshly allocated pages (refcount already 1, owned by caller —
        ownership transfers to the lane) onto the next logical slots."""
        n = self.lane_npages[slot]
        for i, p in enumerate(ids):
            self.tables[slot, n + i] = p
        self.lane_npages[slot] = n + len(ids)

    def lane_append_shared(self, slot: int, ids: Sequence[int]) -> None:
        """Alias already-resident pages (a prefix-cache hit): takes one new
        reference per page, then maps them.  Zero device work — this IS the
        zero-copy hit path."""
        self.allocator.ref(ids)
        self.lane_append_owned(slot, ids)

    def lane_replace(self, slot: int, page_slot: int, new_id: int) -> int:
        """Copy-on-write bookkeeping: swap one logical slot to ``new_id``
        (already allocated by the caller) and drop the lane's reference on the
        old physical page.  Returns the old id (the copy source)."""
        old = int(self.tables[slot, page_slot])
        self.tables[slot, page_slot] = new_id
        self.allocator.deref([old])
        return old

    def lane_release(self, slot: int) -> int:
        """Unmap the whole lane (finish / cancel / preempt): deref every
        mapped page and reset the row to the null sink.  Returns pages freed."""
        freed = self.allocator.deref(self.lane_detach(slot))
        return freed

    def lane_detach(self, slot: int) -> List[int]:
        """Unmap the lane NOW but keep its page references alive: the row
        resets to the null sink (the next table upload routes any further
        write for this lane to the garbage page) and the physical ids come
        back to the caller, who derefs them later.  This is the async serve
        loop's deferred release: a window dispatched while the lane was live
        still holds the OLD table on device and may write these pages, so
        they must not return to the allocator until that window retires
        (:meth:`~accelerate_tpu.serving.readback.Readback.settle`)."""
        n = int(self.lane_npages[slot])
        held = [int(p) for p in self.tables[slot, :n]]
        self.tables[slot, :] = NULL_PAGE
        self.lane_npages[slot] = 0
        return held

    def chunk_ids(self, slot: int, start_page: int, n: int) -> List[int]:
        """Physical ids backing ``n`` logical page slots from ``start_page``
        (what the prefix cache retains for a freshly prefilled chunk)."""
        return [int(p) for p in self.tables[slot, start_page:start_page + n]]

    def lane_pages(self, slot: int) -> List[int]:
        """Every physical id the lane currently maps, in logical order —
        the block-table row a migration marshals (the ids themselves stay
        behind; only their *content* travels, into pages the destination
        allocator hands out)."""
        return self.chunk_ids(slot, 0, int(self.lane_npages[slot]))

    # ------------------------------------------------------------- accounting
    def kv_bytes(self) -> int:
        """Device HBM held by the page arrays (the whole pool, null included)."""
        return (
            int(self.pages_k.nbytes) + int(self.pages_v.nbytes)
            + int(self.k_scales.nbytes) + int(self.v_scales.nbytes)
        )

    def kv_bytes_per_device(self) -> int:
        """Per-device share of :meth:`kv_bytes`: pages and scales both carry
        the kv-head axis, which splits exactly over the tp degree."""
        return self.kv_bytes() // self.tp_degree

    def chunk_bytes(self, npages: int) -> int:
        """Bytes ``npages`` pages of KV cost — K+V data at the storage dtype
        PLUS both per-page f32 scale slabs.  The ONE accounting unit every
        byte budget that charges per chunk must use (`prefix_cache_mb`,
        `prefix_host_mb`, the shared-bytes gauge): quantized pools carry real
        HBM in the scale slabs, and a budget that counted data bytes only
        would under-charge int8/fp8 entries by ``L * Hkv * 8`` bytes per
        page."""
        return int(npages) * self.page_kv_bytes

    def publish_gauges(self) -> None:
        self._in_use_gauge.set(self.allocator.used_count)
        self._free_gauge.set(self.allocator.free_count)
        self._shared_gauge.set(
            self.allocator.shared_extra_refs() * self.page_kv_bytes
        )


class DraftContextWindow:
    """Host-side sliding context for the draft model — the one piece of
    per-lane drafting state :func:`~accelerate_tpu.serving.spec_exec
    .make_draft_forward` needs.

    The draft forward is stateless (it re-prefills its context every cycle
    into an in-trace scratch cache), so the host only has to hand it the
    last ``width`` visible tokens per lane, right-padded, plus a valid
    length.  Two numpy slabs sized ``[slots, width]`` / ``[slots]`` make
    that a zero-copy dispatch argument: :meth:`begin` seeds a lane from its
    prompt tail, :meth:`push` slides committed tokens in after each verify
    drain, :meth:`retire` zeroes the row.  A bounded window (default 64 in
    the engine) deliberately trades long-range draft context for a fixed,
    small prefill cost — the draft's job is local continuation ranking, and
    tokens beyond the window only reach it through the lane's real KV at
    verify time anyway.
    """

    def __init__(self, slots: int, width: int, pad: int = 0) -> None:
        if width < 1:
            raise ValueError(f"need width >= 1, got {width}")
        self.width = width
        self.pad = pad
        self.tokens = np.full((slots, width), pad, dtype=np.int32)
        self.length = np.zeros(slots, dtype=np.int32)

    def begin(self, slot: int, tokens: Sequence[int]) -> None:
        """Seed ``slot`` from a prompt: keep the last ``width`` tokens."""
        toks = np.asarray(tokens, dtype=np.int32).ravel()[-self.width:]
        self.tokens[slot] = self.pad
        self.tokens[slot, : toks.size] = toks
        self.length[slot] = toks.size

    def push(self, slot: int, tokens: Sequence[int]) -> None:
        """Append committed tokens, sliding the window left on overflow."""
        toks = np.asarray(tokens, dtype=np.int32).ravel()
        if toks.size >= self.width:
            self.tokens[slot] = toks[-self.width:]
            self.length[slot] = self.width
            return
        n = int(self.length[slot])
        spill = n + toks.size - self.width
        if spill > 0:
            self.tokens[slot, : n - spill] = self.tokens[slot, spill:n]
            n -= spill
        self.tokens[slot, n : n + toks.size] = toks
        self.length[slot] = n + toks.size

    def retire(self, slot: int) -> None:
        self.tokens[slot] = self.pad
        self.length[slot] = 0


__all__ = ["NULL_PAGE", "DraftContextWindow", "PageAllocator", "PagedKVPool"]

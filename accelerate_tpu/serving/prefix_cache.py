"""Chunk-granular prefix KV cache: a radix tree over chunk-aligned prefixes.

Under a serving queue with shared system/few-shot prefixes, most prefill FLOPs
recompute KV the pool already produced for an earlier request.  SGLang's
RadixAttention and vLLM's automatic prefix caching reuse that KV across
requests; the TPU-native translation caches at **chunk granularity** — the
exact bucket boundaries :func:`~accelerate_tpu.serving.pool.plan_chunks`
already prefills at — so reuse rides ONE fixed-shape copy executable per
bucket (:func:`~accelerate_tpu.serving.pool.make_copy_chunk`) and the
compiled-shape budget stays static no matter how requests share.

Structure: a tree whose edges are *full* chunks of token ids.  A node's
identity is the whole token prefix from the root; its key inside the parent is
a rolling hash of that prefix (:func:`rolling_hash`), verified token-exact on
every lookup so a hash collision can never serve wrong KV.  Each node retains
the device KV slab ``[L, 1, chunk, H, D]`` (k and v) that prefill computed for
its chunk *given its full prefix* — KV at a position depends on every earlier
token through attention, which is why only exact whole-prefix matches are
reusable and why partial (padded) final chunks are never cached.

Lifecycle: nodes are pinned (``refs``) while any request between admission and
slot insertion depends on them; eviction is leaf-only LRU among unpinned
nodes, under a byte ``capacity`` (``ServingEngine(prefix_cache_mb=...)``).
Evicting a leaf may expose its parent as the next candidate — interior nodes
are never dropped from under their children, so every resident slab's prefix
chain stays resident.

Tiering (paged engine only): with ``host_capacity_bytes > 0`` and a ``spill``
hook installed, a device-tier eviction *demotes* the node instead of dropping
it — the hook D2H-extracts the node's pages (data **and** per-page quant
scales, so int8/fp8 entries spill at their quantized density) into a host-RAM
ring under its own byte budget, the node's page references are released, and
the node stays in the radix tree with ``tier == "host"`` holding the payload.
A later radix hit against a spilled node *promotes* it: the engine allocates
fresh pages, H2D-installs the payload behind the in-flight decode window, and
calls :meth:`promote_node` to re-admit the node to the device tier.  An
optional disk ring (``disk_capacity_bytes`` + ``disk_dir``) sits behind the
host ring: host-tier LRU victims whose payload has landed host-side are
written out instead of dropped.  Each tier runs its own leaf-only LRU; pinned
nodes never demote out of their tier, and a spilled chain is always a suffix —
a device node's ancestors are device-resident, so any matched chain is
``device* host* disk*`` in order.

All of this is host-side bookkeeping; the only device work a cache hit costs
is one ``dynamic_update_slice`` per reused chunk (slot pool) or an H2D install
per *spilled* chunk (paged pool — device-tier hits stay zero-copy).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import MetricsRegistry, get_registry

#: Seed for the root prefix hash (djb2's seed; any odd constant works).
_HASH_SEED = 5381
#: Large Mersenne prime modulus keeps the rolling hash in cheap python ints.
_HASH_MOD = (1 << 61) - 1
_HASH_MULT = 1_000_003


def rolling_hash(prev: int, tokens) -> int:
    """Extend prefix hash ``prev`` over ``tokens`` (order-sensitive).

    ``rolling_hash(rolling_hash(seed, a), b) == rolling_hash(seed, a + b)`` —
    a node's key is the hash of its *entire* prefix, computed incrementally
    from its parent's key.
    """
    h = int(prev)
    for t in np.asarray(tokens).ravel().tolist():
        h = (h * _HASH_MULT + int(t) + 1) % _HASH_MOD
    return h


class PrefixNode:
    """One cached chunk: token ids + the retained KV — either a device slab
    (``k``/``v``, the slot-pool engine) or physical page ids into the shared
    page pool (``pages``, the paged engine; see :mod:`.paging`).  A page node
    holds one allocator reference per page for as long as it is device-tier
    resident; a spilled node (``tier != "device"``) holds no pages and keeps
    its KV in ``host`` instead — a tuple of per-layer page/scale arrays (still
    device handles while the D2H extract is in flight, host ndarrays once the
    drain lands it) or, for the disk tier, the path of the ring file."""

    __slots__ = ("key", "tokens", "parent", "children", "k", "v", "pages",
                 "nbytes", "refs", "last_used", "tier", "host")

    def __init__(self, key: int, tokens: Optional[np.ndarray], parent, k, v,
                 pages: Optional[Tuple[int, ...]] = None, nbytes: Optional[int] = None):
        self.key = key
        self.tokens = tokens                 # [chunk] int32; None for the root
        self.parent = parent
        self.children: Dict[int, "PrefixNode"] = {}
        self.k = k                           # [L, 1, chunk, H, D] device slab
        self.v = v
        self.pages = pages                   # physical page ids (paged mode)
        if nbytes is not None:
            self.nbytes = int(nbytes)
        else:
            self.nbytes = (int(k.nbytes) + int(v.nbytes)) if k is not None else 0
        self.refs = 0
        self.last_used = 0
        self.tier = "device"                 # "device" | "host" | "disk"
        self.host = None                     # spilled payload (tier != device)

    def __repr__(self) -> str:  # debugging aid only
        n = 0 if self.tokens is None else len(self.tokens)
        return (f"PrefixNode(len={n}, tier={self.tier}, refs={self.refs}, "
                f"children={len(self.children)}, bytes={self.nbytes})")


class PrefixCache:
    """Host-managed radix cache of device KV slabs with LRU byte budgeting.

    Parameters
    ----------
    capacity_bytes: retained-slab budget (device tier).  Pinned (``refs > 0``)
        nodes never evict, so in-flight requests can transiently hold the
        cache over budget; eviction restores it as soon as pins release.
    registry: metrics registry for the ``serve/prefix_*`` gauges and the
        eviction/spill/promotion counters (default: the process registry).
    on_evict: called with each node as it leaves the cache *entirely* — the
        paged engine uses this to drop the allocator references its page nodes
        hold (the pages themselves survive while lanes still alias them;
        refcounting, not residency in this tree, decides when HBM is
        reclaimed).  A demotion to the host ring is NOT an eviction: the
        engine's ``spill`` hook releases the page refs itself.
    host_capacity_bytes: host-RAM spill ring budget; 0 disables tiering and
        restores drop-on-evict behavior exactly.
    spill: ``spill(node) -> payload | None`` — the engine hook that
        D2H-extracts a device-tier node's pages (returning the payload the
        node will carry) and releases its page references.  ``None`` means
        the node cannot be spilled and is dropped instead.
    disk_capacity_bytes / disk_dir: optional disk ring behind the host ring;
        host-tier LRU victims with landed payloads demote into ``.npz`` files
        under ``disk_dir`` instead of dropping.
    """

    def __init__(self, capacity_bytes: int,
                 registry: Optional[MetricsRegistry] = None,
                 on_evict=None,
                 host_capacity_bytes: int = 0,
                 spill=None,
                 disk_capacity_bytes: int = 0,
                 disk_dir: Optional[str] = None):
        self.on_evict = on_evict
        self.spill = spill
        self.capacity = int(capacity_bytes)
        if self.capacity <= 0:
            raise ValueError(f"capacity_bytes must be positive, got {capacity_bytes}")
        self.host_capacity = int(host_capacity_bytes or 0)
        self.disk_capacity = int(disk_capacity_bytes or 0)
        self.disk_dir = disk_dir
        if self.disk_capacity > 0 and not disk_dir:
            raise ValueError("disk_capacity_bytes > 0 requires disk_dir")
        self.root = PrefixNode(_HASH_SEED, None, None, None, None)
        self.bytes = 0
        self.host_bytes = 0
        self.disk_bytes = 0
        self.evictions = 0
        self.host_evictions = 0
        self.spills = 0
        self.promotions = 0
        self._nodes: List[PrefixNode] = []
        self._host_nodes: List[PrefixNode] = []
        self._disk_nodes: List[PrefixNode] = []
        self._disk_seq = 0
        self._clock = 0
        registry = registry if registry is not None else get_registry()
        self._bytes_gauge = registry.gauge(
            "serve/prefix_cache_bytes", help="retained prefix KV slab bytes"
        )
        self._nodes_gauge = registry.gauge(
            "serve/prefix_cache_nodes", help="resident prefix cache nodes"
        )
        self._host_bytes_gauge = registry.gauge(
            "serve/prefix_host_bytes",
            help="prefix KV bytes resident in the host-RAM spill ring",
        )
        self._evict_counter = registry.counter(
            "serve/prefix_cache_evictions_total",
            help="prefix cache nodes dropped by LRU eviction",
        )
        self._spill_counter = registry.counter(
            "serve/prefix_spills_total",
            help="prefix nodes demoted device -> host spill ring",
        )
        self._promote_counter = registry.counter(
            "serve/prefix_promotions_total",
            help="spilled prefix nodes re-admitted to the device tier",
        )

    # ---------------------------------------------------------------- lookup
    def _touch(self, node: PrefixNode) -> None:
        self._clock += 1
        node.last_used = self._clock

    def match(self, prompt: np.ndarray,
              chunks: Sequence[Tuple[int, int]]) -> List[PrefixNode]:
        """Longest chain of cached nodes covering ``prompt``'s leading chunks.

        Walks ``chunks`` (the request's :func:`plan_chunks` plan) from the
        root; stops at the first partial chunk (``valid < bucket`` — padded
        chunks are never cached) or the first miss.  Matched nodes are
        LRU-touched but NOT pinned — callers pin via :meth:`acquire`.  A chain
        may cross tiers (``device* host* disk*`` — spilling is leaf-first, so
        spilled nodes are always a suffix); spilled nodes hit like device
        nodes and the engine promotes them at admission.
        """
        prompt = np.asarray(prompt)
        nodes: List[PrefixNode] = []
        node, start = self.root, 0
        for bucket, valid in chunks:
            if valid != bucket:
                break
            tokens = prompt[start:start + bucket]
            child = node.children.get(rolling_hash(node.key, tokens))
            if child is None or not np.array_equal(child.tokens, tokens):
                break
            self._touch(child)
            nodes.append(child)
            node, start = child, start + bucket
        return nodes

    # --------------------------------------------------------------- pinning
    def acquire(self, nodes: Iterable[PrefixNode]) -> None:
        """Pin ``nodes`` against eviction (a request depends on their slabs)."""
        for n in nodes:
            n.refs += 1

    def release(self, nodes: Iterable[PrefixNode]) -> None:
        """Drop pins taken by :meth:`acquire`; touched so fresh users rank hot."""
        for n in nodes:
            n.refs -= 1
            if n.refs < 0:
                raise RuntimeError(f"prefix cache refcount underflow on {n!r}")
            self._touch(n)

    # -------------------------------------------------------------- mutation
    def insert(self, parent: Optional[PrefixNode], tokens, k, v
               ) -> Optional[PrefixNode]:
        """Retain one freshly prefilled chunk under ``parent`` (None = root).

        Returns the resident node — the existing one if this exact chunk is
        already cached — or ``None`` when it cannot be retained (the byte
        budget cannot be met even after eviction, or a hash collision with a
        different token sequence occupies the key; both leave the cache
        untouched, and the caller must then stop extending this chain).
        """
        parent = parent if parent is not None else self.root
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        key = rolling_hash(parent.key, tokens)
        existing = parent.children.get(key)
        if existing is not None:
            if np.array_equal(existing.tokens, tokens):
                self._touch(existing)
                return existing
            return None  # 61-bit hash collision: keep the resident entry
        nbytes = int(k.nbytes) + int(v.nbytes)
        if not self._make_room(nbytes):
            return None
        node = PrefixNode(key, tokens, parent, k, v)
        self._touch(node)
        parent.children[key] = node
        self._nodes.append(node)
        self.bytes += nbytes
        self._publish()
        return node

    def insert_pages(self, parent: Optional[PrefixNode], tokens,
                     page_ids: Sequence[int], nbytes: int
                     ) -> Optional[PrefixNode]:
        """Retain one freshly prefilled chunk as *page references* (the paged
        engine: zero copies — the lane's own pages are aliased, the caller
        takes one allocator ref per page iff a NEW node was created OR a
        spilled node was re-admitted in place, which it detects by
        ``node.pages == tuple(page_ids)``).

        Same contract as :meth:`insert`: returns the resident node (the
        existing one on an exact re-insert — whose ``pages`` will differ from
        ``page_ids`` unless the re-insert healed a spilled node), or ``None``
        when the chunk cannot be retained.
        """
        parent = parent if parent is not None else self.root
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        key = rolling_hash(parent.key, tokens)
        existing = parent.children.get(key)
        if existing is not None:
            if np.array_equal(existing.tokens, tokens):
                self._touch(existing)
                if existing.tier != "device":
                    # a degraded promotion re-prefilled this chunk: fold the
                    # fresh pages back in so the node heals to device tier
                    self._readmit(existing, page_ids, int(nbytes))
                return existing
            return None  # 61-bit hash collision: keep the resident entry
        if not self._make_room(int(nbytes)):
            return None
        node = PrefixNode(key, tokens, parent, None, None,
                          pages=tuple(int(p) for p in page_ids), nbytes=nbytes)
        self._touch(node)
        parent.children[key] = node
        self._nodes.append(node)
        self.bytes += node.nbytes
        self._publish()
        return node

    def evict_one(self) -> bool:
        """Force one LRU device-tier eviction (page-pressure reclaim in the
        paged engine) — a demotion to the host ring when tiering is on, a drop
        otherwise; either way the node's page refs are released.  Returns
        False when nothing is evictable."""
        skip: set = set()
        while True:
            victim = self._lru_device_victim(skip)
            if victim is None:
                return False
            if self._evict(victim):
                return True
            skip.add(id(victim))

    def flush(self) -> int:
        """Drop every unpinned node from EVERY tier, leaf-first (interior
        nodes become leaves as their children go).  The weight hot-swap path
        calls this: retained KV was computed under the OLD weights, and
        replaying it after a swap would splice stale activations into fresh
        prefill — token corruption no output check downstream could attribute.
        Spilled tiers are purged too (never demoted: stale KV must not survive
        anywhere).  Pinned nodes (``refs > 0``) survive; callers drop queued
        requests' pins first (:meth:`Scheduler.drop_cache_pins`).  Returns
        nodes removed."""
        before = len(self._nodes) + len(self._host_nodes) + len(self._disk_nodes)
        skip: set = set()
        while True:
            victim = self._lru_device_victim(skip)
            if victim is None:
                break
            if not self._drop_subtree(victim):
                skip.add(id(victim))
        for nodes, drop in ((self._host_nodes, self._drop_host),
                            (self._disk_nodes, self._drop_disk)):
            skip = set()
            while True:
                victim = self._lru_leaf(nodes, skip)
                if victim is None:
                    break
                drop(victim)
        return before - (len(self._nodes) + len(self._host_nodes)
                         + len(self._disk_nodes))

    # ------------------------------------------------------------- promotion
    def node_payload(self, node: PrefixNode):
        """The spilled KV payload for promotion: the engine-provided spill
        value for host-tier nodes (device handles while the extract is in
        flight, host arrays once landed), or the arrays reloaded from the
        disk ring.  ``None`` when the node is not spilled or the ring file
        is gone."""
        if node.tier == "host":
            return node.host
        if node.tier == "disk":
            try:
                with np.load(node.host) as z:
                    return tuple(z[k] for k in z.files)
            except (OSError, ValueError):
                return None
        return None

    def settle_payload(self, node: PrefixNode, arrays) -> None:
        """Replace a host-tier node's in-flight device handles with the landed
        host arrays (the engine calls this from the drain side)."""
        if node.tier == "host":
            node.host = arrays

    def discard_spilled(self, node: PrefixNode) -> None:
        """Drop a spilled node (and its spilled subtree) whose payload can no
        longer be trusted — e.g. the spill gather failed to land.  No-op for
        device-tier or already-detached nodes."""
        if node.tier == "device" or node.key not in node.parent.children:
            return
        self._drop_subtree(node)

    def promote_node(self, node: PrefixNode, page_ids: Sequence[int]) -> bool:
        """Record a successful H2D promotion of a spilled node and try to
        re-admit it to the device tier with the freshly installed pages.  The
        caller (engine) has already scatter-installed the payload into
        ``page_ids`` — that promotion counts regardless — and takes one
        allocator ref per page iff this returns True (re-admission
        succeeded).  Re-admission fails, with the node staying spilled and
        its payload kept for the next hit, when the parent is not
        device-resident or the device byte budget cannot be met (e.g. every
        resident node is pinned by a running lane) — the lane still owns its
        pages either way, only cache retention is lost."""
        if node.tier == "device":
            return False
        self.promotions += 1
        self._promote_counter.inc()
        if not self._readmit(node, page_ids, node.nbytes):
            return False
        self._touch(node)
        return True

    def _readmit(self, node: PrefixNode, page_ids: Sequence[int],
                 nbytes: int) -> bool:
        """host/disk -> device transition in place (shared by promotion and
        the degraded-promotion heal in :meth:`insert_pages`)."""
        if node.parent.tier != "device":
            return False  # keep the device* host* disk* chain ordering
        if not self._make_room(int(nbytes)):
            return False
        if node.tier == "host":
            self._host_nodes.remove(node)
            self.host_bytes -= node.nbytes
        else:
            self._disk_nodes.remove(node)
            self.disk_bytes -= node.nbytes
            self._unlink_disk(node)
        node.host = None
        node.tier = "device"
        node.pages = tuple(int(p) for p in page_ids)
        node.nbytes = int(nbytes)
        self._nodes.append(node)
        self.bytes += node.nbytes
        self._publish()
        return True

    # -------------------------------------------------------------- eviction
    def _make_room(self, nbytes: int) -> bool:
        """Evict LRU unpinned device leaves until ``nbytes`` more fits; False
        if the survivors (pinned or interior) can't shrink far enough."""
        if nbytes > self.capacity:
            return False
        skip: set = set()
        while self.bytes + nbytes > self.capacity:
            victim = self._lru_device_victim(skip)
            if victim is None:
                return False
            if not self._evict(victim):
                skip.add(id(victim))
        return True

    def _lru_device_victim(self, skip=()) -> Optional[PrefixNode]:
        """LRU unpinned device node with no device-tier children.  Spilled
        children don't shield a parent from eviction — the parent spills too
        (keeping the chain ordering) or the whole spilled subtree drops."""
        victim = None
        for n in self._nodes:
            if n.refs > 0 or id(n) in skip:
                continue
            if any(c.tier == "device" for c in n.children.values()):
                continue
            if victim is None or n.last_used < victim.last_used:
                victim = n
        return victim

    @staticmethod
    def _lru_leaf(nodes: List[PrefixNode], skip=()) -> Optional[PrefixNode]:
        victim = None
        for n in nodes:
            if n.refs > 0 or n.children or id(n) in skip:
                continue
            if victim is None or n.last_used < victim.last_used:
                victim = n
        return victim

    def _evict(self, node: PrefixNode) -> bool:
        """Demote ``node`` to the host ring when tiering allows; drop it (and
        any spilled descendants) otherwise.  False when neither is possible
        (e.g. a pinned spilled descendant)."""
        if (self.host_capacity > 0 and self.spill is not None
                and node.pages and self._demote(node)):
            return True
        return self._drop_subtree(node)

    def _demote(self, node: PrefixNode) -> bool:
        """device -> host transition: make host-ring room first, then run the
        engine's D2H spill hook.  Page refs are released by the hook."""
        if node.nbytes > self.host_capacity:
            return False
        while self.host_bytes + node.nbytes > self.host_capacity:
            victim = self._lru_leaf(self._host_nodes)
            if victim is None:
                return False
            self._remove_host(victim)
        payload = self.spill(node)
        if payload is None:
            return False
        node.host = payload
        node.tier = "host"
        node.pages = None
        self._nodes.remove(node)
        self.bytes -= node.nbytes
        self._host_nodes.append(node)
        self.host_bytes += node.nbytes
        self.spills += 1
        self._spill_counter.inc()
        self._publish()
        return True

    def _drop_subtree(self, node: PrefixNode) -> bool:
        """Drop ``node`` and its spilled descendants leaf-first (a device
        victim may carry host/disk children); refuses — removing nothing —
        when any descendant is pinned."""
        stack, order = [node], []
        while stack:
            n = stack.pop()
            if n.refs > 0:
                return False
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):
            if n.tier == "device":
                self._remove(n)
            elif n.tier == "host":
                self._drop_host(n)
            else:
                self._drop_disk(n)
        return True

    def _remove(self, node: PrefixNode) -> None:
        del node.parent.children[node.key]
        self._nodes.remove(node)
        self.bytes -= node.nbytes
        self.evictions += 1
        self._evict_counter.inc()
        self._publish()
        if self.on_evict is not None:
            self.on_evict(node)

    def _remove_host(self, node: PrefixNode) -> None:
        """Host-ring victim: demote to the disk ring when possible, drop
        otherwise."""
        if self._disk_admit(node):
            return
        self._drop_host(node)

    def _drop_host(self, node: PrefixNode) -> None:
        del node.parent.children[node.key]
        self._host_nodes.remove(node)
        self.host_bytes -= node.nbytes
        node.host = None
        node.tier = "device"  # detached; neutral state for late settles
        self.host_evictions += 1
        self.evictions += 1
        self._evict_counter.inc()
        self._publish()
        if self.on_evict is not None:
            self.on_evict(node)

    def _disk_admit(self, node: PrefixNode) -> bool:
        """host -> disk transition for a landed payload; in-flight payloads
        (still device handles) and oversized nodes are not disk-eligible."""
        if self.disk_capacity <= 0 or node.children or node.nbytes > self.disk_capacity:
            return False
        payload = node.host
        if not (isinstance(payload, tuple)
                and payload
                and all(isinstance(a, np.ndarray) for a in payload)):
            return False
        while self.disk_bytes + node.nbytes > self.disk_capacity:
            victim = self._lru_leaf(self._disk_nodes)
            if victim is None:
                return False
            self._drop_disk(victim)
        self._disk_seq += 1
        path = os.path.join(self.disk_dir,
                            f"prefix_{node.key:016x}_{self._disk_seq}.npz")
        try:
            np.savez(path, *payload)
        except OSError:
            return False
        node.host = path
        node.tier = "disk"
        self._host_nodes.remove(node)
        self.host_bytes -= node.nbytes
        self._disk_nodes.append(node)
        self.disk_bytes += node.nbytes
        self._publish()
        return True

    def _drop_disk(self, node: PrefixNode) -> None:
        del node.parent.children[node.key]
        self._disk_nodes.remove(node)
        self.disk_bytes -= node.nbytes
        self._unlink_disk(node)
        node.host = None
        node.tier = "device"  # detached; neutral state for late settles
        self.evictions += 1
        self._evict_counter.inc()
        if self.on_evict is not None:
            self.on_evict(node)

    def _unlink_disk(self, node: PrefixNode) -> None:
        try:
            os.remove(node.host)
        except (OSError, TypeError):
            pass

    def _publish(self) -> None:
        self._bytes_gauge.set(self.bytes)
        self._nodes_gauge.set(len(self._nodes))
        self._host_bytes_gauge.set(self.host_bytes)

    # ----------------------------------------------------------------- stats
    @property
    def num_nodes(self) -> int:
        return len(self._nodes)

    def stats(self) -> Dict[str, Any]:
        """Plain-dict snapshot for the engine's legacy stats surface."""
        return {
            "capacity_bytes": self.capacity,
            "bytes": self.bytes,
            "nodes": len(self._nodes),
            "evictions": self.evictions,
            "host_capacity_bytes": self.host_capacity,
            "host_bytes": self.host_bytes,
            "host_nodes": len(self._host_nodes),
            "host_evictions": self.host_evictions,
            "disk_bytes": self.disk_bytes,
            "disk_nodes": len(self._disk_nodes),
            "spills": self.spills,
            "promotions": self.promotions,
        }


__all__ = ["PrefixCache", "PrefixNode", "rolling_hash"]

"""Chunk-granular prefix KV cache: a radix tree over chunk-aligned prefixes.

Under a serving queue with shared system/few-shot prefixes, most prefill FLOPs
recompute KV the pool already produced for an earlier request.  SGLang's
RadixAttention and vLLM's automatic prefix caching reuse that KV across
requests; the TPU-native translation caches at **chunk granularity** — the
exact bucket boundaries :func:`~accelerate_tpu.serving.pool.plan_chunks`
already prefills at — so reuse rides ONE fixed-shape copy executable per
bucket (:func:`~accelerate_tpu.serving.pool.make_copy_chunk`) and the
compiled-shape budget stays static no matter how requests share.

Structure: a tree whose edges are *full* chunks of token ids.  A node's
identity is the whole token prefix from the root; its key inside the parent is
a rolling hash of that prefix (:func:`rolling_hash`), verified token-exact on
every lookup so a hash collision can never serve wrong KV.  Each node retains
the device KV slab ``[L, 1, chunk, H, D]`` (k and v) that prefill computed for
its chunk *given its full prefix* — KV at a position depends on every earlier
token through attention, which is why only exact whole-prefix matches are
reusable and why partial (padded) final chunks are never cached.

Lifecycle: nodes are pinned (``refs``) while any request between admission and
slot insertion depends on them; eviction is leaf-only LRU among unpinned
nodes, under a byte ``capacity`` (``ServingEngine(prefix_cache_mb=...)``).
Evicting a leaf may expose its parent as the next candidate — interior nodes
are never dropped from under their children, so every resident slab's prefix
chain stays resident.

All of this is host-side bookkeeping; the only device work a cache hit costs
is one ``dynamic_update_slice`` per reused chunk.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import MetricsRegistry, get_registry

#: Seed for the root prefix hash (djb2's seed; any odd constant works).
_HASH_SEED = 5381
#: Large Mersenne prime modulus keeps the rolling hash in cheap python ints.
_HASH_MOD = (1 << 61) - 1
_HASH_MULT = 1_000_003


def rolling_hash(prev: int, tokens) -> int:
    """Extend prefix hash ``prev`` over ``tokens`` (order-sensitive).

    ``rolling_hash(rolling_hash(seed, a), b) == rolling_hash(seed, a + b)`` —
    a node's key is the hash of its *entire* prefix, computed incrementally
    from its parent's key.
    """
    h = int(prev)
    for t in np.asarray(tokens).ravel().tolist():
        h = (h * _HASH_MULT + int(t) + 1) % _HASH_MOD
    return h


class PrefixNode:
    """One cached chunk: token ids + the retained KV — either a device slab
    (``k``/``v``, the slot-pool engine) or physical page ids into the shared
    page pool (``pages``, the paged engine; see :mod:`.paging`).  A page node
    holds one allocator reference per page for as long as it is resident."""

    __slots__ = ("key", "tokens", "parent", "children", "k", "v", "pages",
                 "nbytes", "refs", "last_used")

    def __init__(self, key: int, tokens: Optional[np.ndarray], parent, k, v,
                 pages: Optional[Tuple[int, ...]] = None, nbytes: Optional[int] = None):
        self.key = key
        self.tokens = tokens                 # [chunk] int32; None for the root
        self.parent = parent
        self.children: Dict[int, "PrefixNode"] = {}
        self.k = k                           # [L, 1, chunk, H, D] device slab
        self.v = v
        self.pages = pages                   # physical page ids (paged mode)
        if nbytes is not None:
            self.nbytes = int(nbytes)
        else:
            self.nbytes = (int(k.nbytes) + int(v.nbytes)) if k is not None else 0
        self.refs = 0
        self.last_used = 0

    def __repr__(self) -> str:  # debugging aid only
        n = 0 if self.tokens is None else len(self.tokens)
        return (f"PrefixNode(len={n}, refs={self.refs}, "
                f"children={len(self.children)}, bytes={self.nbytes})")


class PrefixCache:
    """Host-managed radix cache of device KV slabs with LRU byte budgeting.

    Parameters
    ----------
    capacity_bytes: retained-slab budget.  Pinned (``refs > 0``) nodes never
        evict, so in-flight requests can transiently hold the cache over
        budget; eviction restores it as soon as pins release.
    registry: metrics registry for the ``serve/prefix_cache_*`` gauges and the
        eviction counter (default: the process registry).
    on_evict: called with each node as it leaves the cache — the paged engine
        uses this to drop the allocator references its page nodes hold (the
        pages themselves survive while lanes still alias them; refcounting,
        not residency in this tree, decides when HBM is reclaimed).
    """

    def __init__(self, capacity_bytes: int,
                 registry: Optional[MetricsRegistry] = None,
                 on_evict=None):
        self.on_evict = on_evict
        self.capacity = int(capacity_bytes)
        if self.capacity <= 0:
            raise ValueError(f"capacity_bytes must be positive, got {capacity_bytes}")
        self.root = PrefixNode(_HASH_SEED, None, None, None, None)
        self.bytes = 0
        self.evictions = 0
        self._nodes: List[PrefixNode] = []
        self._clock = 0
        registry = registry if registry is not None else get_registry()
        self._bytes_gauge = registry.gauge(
            "serve/prefix_cache_bytes", help="retained prefix KV slab bytes"
        )
        self._nodes_gauge = registry.gauge(
            "serve/prefix_cache_nodes", help="resident prefix cache nodes"
        )
        self._evict_counter = registry.counter(
            "serve/prefix_cache_evictions_total",
            help="prefix cache nodes dropped by LRU eviction",
        )

    # ---------------------------------------------------------------- lookup
    def _touch(self, node: PrefixNode) -> None:
        self._clock += 1
        node.last_used = self._clock

    def match(self, prompt: np.ndarray,
              chunks: Sequence[Tuple[int, int]]) -> List[PrefixNode]:
        """Longest chain of cached nodes covering ``prompt``'s leading chunks.

        Walks ``chunks`` (the request's :func:`plan_chunks` plan) from the
        root; stops at the first partial chunk (``valid < bucket`` — padded
        chunks are never cached) or the first miss.  Matched nodes are
        LRU-touched but NOT pinned — callers pin via :meth:`acquire`.
        """
        prompt = np.asarray(prompt)
        nodes: List[PrefixNode] = []
        node, start = self.root, 0
        for bucket, valid in chunks:
            if valid != bucket:
                break
            tokens = prompt[start:start + bucket]
            child = node.children.get(rolling_hash(node.key, tokens))
            if child is None or not np.array_equal(child.tokens, tokens):
                break
            self._touch(child)
            nodes.append(child)
            node, start = child, start + bucket
        return nodes

    # --------------------------------------------------------------- pinning
    def acquire(self, nodes: Iterable[PrefixNode]) -> None:
        """Pin ``nodes`` against eviction (a request depends on their slabs)."""
        for n in nodes:
            n.refs += 1

    def release(self, nodes: Iterable[PrefixNode]) -> None:
        """Drop pins taken by :meth:`acquire`; touched so fresh users rank hot."""
        for n in nodes:
            n.refs -= 1
            if n.refs < 0:
                raise RuntimeError(f"prefix cache refcount underflow on {n!r}")
            self._touch(n)

    # -------------------------------------------------------------- mutation
    def insert(self, parent: Optional[PrefixNode], tokens, k, v
               ) -> Optional[PrefixNode]:
        """Retain one freshly prefilled chunk under ``parent`` (None = root).

        Returns the resident node — the existing one if this exact chunk is
        already cached — or ``None`` when it cannot be retained (the byte
        budget cannot be met even after eviction, or a hash collision with a
        different token sequence occupies the key; both leave the cache
        untouched, and the caller must then stop extending this chain).
        """
        parent = parent if parent is not None else self.root
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        key = rolling_hash(parent.key, tokens)
        existing = parent.children.get(key)
        if existing is not None:
            if np.array_equal(existing.tokens, tokens):
                self._touch(existing)
                return existing
            return None  # 61-bit hash collision: keep the resident entry
        nbytes = int(k.nbytes) + int(v.nbytes)
        if not self._make_room(nbytes):
            return None
        node = PrefixNode(key, tokens, parent, k, v)
        self._touch(node)
        parent.children[key] = node
        self._nodes.append(node)
        self.bytes += nbytes
        self._bytes_gauge.set(self.bytes)
        self._nodes_gauge.set(len(self._nodes))
        return node

    def insert_pages(self, parent: Optional[PrefixNode], tokens,
                     page_ids: Sequence[int], nbytes: int
                     ) -> Optional[PrefixNode]:
        """Retain one freshly prefilled chunk as *page references* (the paged
        engine: zero copies — the lane's own pages are aliased, the caller
        takes one allocator ref per page iff a NEW node was created, which it
        detects by ``node.pages == tuple(page_ids)``).

        Same contract as :meth:`insert`: returns the resident node (the
        existing one on an exact re-insert — whose ``pages`` will differ from
        ``page_ids``), or ``None`` when the chunk cannot be retained.
        """
        parent = parent if parent is not None else self.root
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        key = rolling_hash(parent.key, tokens)
        existing = parent.children.get(key)
        if existing is not None:
            if np.array_equal(existing.tokens, tokens):
                self._touch(existing)
                return existing
            return None  # 61-bit hash collision: keep the resident entry
        if not self._make_room(int(nbytes)):
            return None
        node = PrefixNode(key, tokens, parent, None, None,
                          pages=tuple(int(p) for p in page_ids), nbytes=nbytes)
        self._touch(node)
        parent.children[key] = node
        self._nodes.append(node)
        self.bytes += node.nbytes
        self._bytes_gauge.set(self.bytes)
        self._nodes_gauge.set(len(self._nodes))
        return node

    def evict_one(self) -> bool:
        """Force one LRU unpinned-leaf eviction (page-pressure reclaim in the
        paged engine).  Returns False when nothing is evictable."""
        victim = None
        for n in self._nodes:
            if n.children or n.refs > 0:
                continue
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        self._remove(victim)
        return True

    def flush(self) -> int:
        """Drop every unpinned node, leaf-first (interior nodes become leaves
        as their children go).  The weight hot-swap path calls this: retained
        KV was computed under the OLD weights, and replaying it after a swap
        would splice stale activations into fresh prefill — token corruption
        no output check downstream could attribute.  Pinned nodes (``refs >
        0``) survive; callers drop queued requests' pins first
        (:meth:`Scheduler.drop_cache_pins`).  Returns nodes removed."""
        removed = 0
        while self.evict_one():
            removed += 1
        return removed

    def _make_room(self, nbytes: int) -> bool:
        """Evict LRU unpinned leaves until ``nbytes`` more fits; False if the
        survivors (pinned or interior) can't shrink far enough."""
        if nbytes > self.capacity:
            return False
        while self.bytes + nbytes > self.capacity:
            victim = None
            for n in self._nodes:
                if n.children or n.refs > 0:
                    continue
                if victim is None or n.last_used < victim.last_used:
                    victim = n
            if victim is None:
                return False
            self._remove(victim)
        return True

    def _remove(self, node: PrefixNode) -> None:
        del node.parent.children[node.key]
        self._nodes.remove(node)
        self.bytes -= node.nbytes
        self.evictions += 1
        self._evict_counter.inc()
        self._bytes_gauge.set(self.bytes)
        self._nodes_gauge.set(len(self._nodes))
        if self.on_evict is not None:
            self.on_evict(node)

    # ----------------------------------------------------------------- stats
    @property
    def num_nodes(self) -> int:
        return len(self._nodes)

    def stats(self) -> Dict[str, Any]:
        """Plain-dict snapshot for the engine's legacy stats surface."""
        return {
            "capacity_bytes": self.capacity,
            "bytes": self.bytes,
            "nodes": len(self._nodes),
            "evictions": self.evictions,
        }


__all__ = ["PrefixCache", "PrefixNode", "rolling_hash"]

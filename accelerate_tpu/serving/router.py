"""Prefix-affinity router over data-parallel :class:`ServingEngine` replicas.

Tensor parallelism (``ServingEngine(mesh=...)``) makes one model span chips;
this module scales the *other* direction: N independent engines — one per
mesh slice (:func:`~accelerate_tpu.parallel.mesh.replica_meshes`) or per
process — behind a single front door.  The routing decision is where the
multi-chip win actually lands: each replica's prefix-cache radix tree holds
the KV for the prefixes *it* has served, so a request routed to the replica
that already holds its prefix replays cached KV instead of re-running
prefill, while a random or round-robin placement scatters a shared prefix
across every replica and pays the prefill everywhere (the reference's
big-model dispatch layer routes to where the weights live; here the hot
state is the prefix KV).

Policy ``"affinity"`` (default): rolling-hash the prompt's leading chunks
against each replica's radix tree (:meth:`PrefixCache.match` — a pure
host-side walk, no device work, no pinning) and score each replica by the
matched token count; the best positive scorer wins, load breaking ties, and
zero-scorers fall back to least-loaded.  Policy ``"round_robin"`` is the
baseline A/B arm (``bench_inference.py --task serve --tp-ab``).

Policy ``"disaggregated"`` splits the fleet by :class:`ServingEngine` role:
new requests route (affinity-scored) to prefill-capable replicas only, and
once a ``role="prefill"`` replica's last prompt chunk lands the router hands
the lane off — live KV pages, block table, quant scales, RNG and pending
state — to the least-loaded decode-capable replica via
:class:`~accelerate_tpu.serving.transfer.PageMigrator` (device-to-device
where platforms match, pinned-host bounce otherwise).  Decode continues
bit-identically: the migrated lane produces the same tokens, greedy or
sampled, it would have produced had it stayed put.  The same machinery backs
:meth:`migrate_lane` (live rebalancing) and upgrades failover from
re-prefill replay to migration while a dying replica's pages are still
readable.  See ``docs/usage/serving.md`` ("Disaggregated prefill/decode").

Failover: a replica that refuses a ``submit`` with an
:class:`~accelerate_tpu.serving.errors.AdmissionError` — transient queue
backpressure (``retriable=True``) or a capacity refusal such as a
heterogeneous ``max_len`` (``retriable=False``) — is skipped and the request
tries the remaining replicas by load; the LAST refusal propagates only when
every replica refuses.  Matching is on the type, never on message text.

Elasticity: replicas come and go at runtime.  :meth:`add_replica` attaches a
freshly built engine; :meth:`drain_replica` stops routing NEW requests to a
replica while everything it already accepted (queued included) runs to
completion, after which :meth:`step` detaches it automatically.  Because
detach re-indexes ``engines``, every routed request also carries a *stable*
``replica_id``; :meth:`cancel` resolves through it first.  :meth:`hot_swap`
composes the same machinery into a rolling zero-downtime weight swap: each
replica in turn pauses admission, drains its lanes (the OTHER replicas keep
serving, and its own queue merely waits), rebinds params through the
engine's donated-upload path (:meth:`ServingEngine.swap_params` — compiled
executables are reused, no recompile), and resumes.  Replicas may run
different ``weights_version`` labels between swaps — ``submit(...,
model_version=...)`` pins a request to one version, which is how two
checkpoints A/B behind a single endpoint.

Telemetry (``docs/usage/observability.md``): ``serve/replicas`` (info),
``serve/router_affinity_hit_rate`` (fraction of routed requests whose chosen
replica already held a matching prefix), and one ``serve/route`` flight
event per submit carrying the chosen replica and its affinity score.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..telemetry import (
    MetricsRegistry,
    get_flight_recorder,
    get_registry,
    get_reqtrace,
)
from . import faults
from .engine import ServingEngine
from .errors import AdmissionError
from .pool import plan_chunks
from .scheduler import Request, RequestState
from .transfer import MigrationError, PageMigrator

_POLICIES = ("affinity", "round_robin", "disaggregated")


class ReplicaRouter:
    """Route :meth:`submit` calls across N engine replicas; aggregate health.

    Parameters
    ----------
    engines: the replicas.  Each owns its KV pool, scheduler, prefix cache,
        and (optionally) its own tp mesh slice; the router never touches
        device state — it only reads each replica's host-side radix tree and
        queue depths.
    policy: ``"affinity"`` (prefix-cache affinity, least-loaded fallback) or
        ``"round_robin"`` (the A/B baseline).
    registry: metrics registry for the router's gauges (defaults to the
        process registry — pass the same private registry benches give their
        engines to keep arms isolated).
    """

    def __init__(
        self,
        engines: Sequence[ServingEngine],
        policy: str = "affinity",
        registry: Optional[MetricsRegistry] = None,
        breaker_base_s: float = 0.5,
        breaker_max_s: float = 30.0,
    ):
        if not engines:
            raise ValueError("ReplicaRouter needs at least one engine")
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got {policy!r}")
        if policy == "disaggregated":
            roles = [getattr(e, "role", "both") for e in engines]
            if not any(r in ("prefill", "both") for r in roles):
                raise ValueError(
                    "disaggregated policy needs at least one prefill-capable "
                    f"replica (role 'prefill' or 'both'); got roles {roles}"
                )
            if not any(r in ("decode", "both") for r in roles):
                raise ValueError(
                    "disaggregated policy needs at least one decode-capable "
                    f"replica (role 'decode' or 'both'); got roles {roles}"
                )
            if not all(e.paged for e in engines):
                raise ValueError(
                    "disaggregated routing moves lanes between replicas as "
                    "KV pages; every replica needs paged=True"
                )
        self.engines: List[ServingEngine] = list(engines)
        # stable per-replica identities, parallel to ``engines``: positions
        # shift when an earlier replica detaches, ids never do
        self._ids: List[int] = list(range(len(self.engines)))
        self._next_id = len(self.engines)
        self._draining: set = set()  # stable ids not admitting new requests
        self.policy = policy
        self.metrics = registry if registry is not None else get_registry()
        self.recorder = get_flight_recorder().tagged(engine="router")
        self._rr_next = 0
        self._routed = 0
        self._affinity_hits = 0
        self._replicas_gauge = self.metrics.gauge(
            "serve/replicas",
            help="info gauge: engine replicas behind the ReplicaRouter",
        )
        self._replicas_gauge.set(float(len(self.engines)))
        self._affinity_gauge = self.metrics.gauge(
            "serve/router_affinity_hit_rate",
            help="fraction of routed requests whose chosen replica already "
                 "held a matching prefix in its radix tree",
        )
        # half-open circuit breaker over ejected replicas: replica_id ->
        # {"engine", "failures", "open_until"}.  While open, no traffic; once
        # ``open_until`` passes, one probe (revive + a step) either re-admits
        # the replica or doubles the backoff.
        self.breaker_base_s = float(breaker_base_s)
        self.breaker_max_s = float(breaker_max_s)
        self._breaker: Dict[int, dict] = {}
        self._ejections = 0
        self._ejections_counter = self.metrics.counter(
            "serve/replica_ejections_total",
            help="replicas ejected by the router supervisor after a poisoned "
                 "step (their in-flight requests replay on survivors)",
        )
        # lazy: built on first handoff/migration so routers that never move
        # a lane register no migration metrics
        self._migrator: Optional[PageMigrator] = None

    @property
    def migrator(self) -> PageMigrator:
        """The router's :class:`PageMigrator`, built on first use."""
        if self._migrator is None:
            self._migrator = PageMigrator(registry=self.metrics)
        return self._migrator

    @staticmethod
    def _prefill_capable(engine: ServingEngine) -> bool:
        return getattr(engine, "role", "both") in ("prefill", "both")

    @staticmethod
    def _decode_capable(engine: ServingEngine) -> bool:
        return getattr(engine, "role", "both") in ("decode", "both")

    # ------------------------------------------------------------- placement
    def _load(self, engine: ServingEngine) -> int:
        """Host-side load proxy: queued + mid-prefill + active lanes.  Under
        the pipelined engine loop (``async_depth=1``) the active count lags
        a finishing lane by one drain — at most one step of load skew per
        replica, in the conservative (over-counting) direction."""
        return engine.scheduler.queue_depth + int(engine._active.sum())

    def _affinity(self, engine: ServingEngine, prompt: np.ndarray) -> int:
        """Tokens of ``prompt`` this replica's radix tree already holds —
        a read-only walk over full leading chunks (LRU touch only; nothing
        is pinned until the engine's own admission runs)."""
        if engine.prefix_cache is None:
            return 0
        chunks = plan_chunks(len(prompt), engine.buckets)
        nodes = engine.prefix_cache.match(prompt, chunks)
        return sum(len(n.tokens) for n in nodes)

    def _admittable(self, model_version: Optional[str] = None) -> List[int]:
        """Replica indices routing may place NEW requests on: not draining,
        — when the caller pinned a ``model_version`` — serving exactly that
        weights label, and, under the disaggregated policy, prefill-capable
        (every new request prefills before it decodes; decode-only replicas
        receive their lanes by migration, never by submit)."""
        return [
            i for i in range(len(self.engines))
            if self._ids[i] not in self._draining
            and (model_version is None
                 or self.engines[i].weights_version == model_version)
            and (self.policy != "disaggregated"
                 or self._prefill_capable(self.engines[i]))
        ]

    def _choose(self, prompt: np.ndarray, candidates: Sequence[int]) -> tuple:
        """``(replica_index, affinity_score)`` under the configured policy,
        restricted to ``candidates`` (admittable indices)."""
        if self.policy == "round_robin":
            i = candidates[self._rr_next % len(candidates)]
            self._rr_next += 1
            return i, 0
        scores = {i: self._affinity(self.engines[i], prompt) for i in candidates}
        best = max(scores.values())
        if best > 0:
            # highest score wins; load breaks ties among equals
            tied = [i for i, sc in scores.items() if sc == best]
            i = min(tied, key=lambda i: self._load(self.engines[i]))
            return i, best
        i = min(candidates, key=lambda i: self._load(self.engines[i]))
        return i, 0

    # ------------------------------------------------------------ submission
    def submit(
        self,
        prompt,
        config=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        model_version: Optional[str] = None,
        **kwargs: Any,
    ) -> Request:
        """Route one request to a replica and queue it there.  The returned
        :class:`Request` carries ``replica`` — the index it landed on — and
        ``replica_id`` — its stable identity — so callers can drive or cancel
        against the right engine even after an earlier replica detaches.
        ``model_version`` pins the request to replicas serving that weights
        label (the A/B knob); ``None`` routes across every version."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        candidates = self._admittable(model_version)
        if not candidates:
            # every replica is draining (or none serves the pinned version):
            # retriable iff capacity could come back without client changes
            raise AdmissionError(
                f"no admittable replica"
                + (f" serving model version {model_version!r}"
                   if model_version is not None else "")
                + f" ({len(self.engines)} attached, "
                  f"{len(self._draining)} draining)",
                retriable=model_version is None,
            )
        idx, score = self._choose(prompt, candidates)
        # failover ladder: chosen replica first, then the rest by load
        order = [idx] + sorted(
            (i for i in candidates if i != idx),
            key=lambda i: self._load(self.engines[i]),
        )
        last_err: Optional[Exception] = None
        for n_try, i in enumerate(order):
            try:
                req = self.engines[i].submit(
                    prompt, config=config, on_token=on_token, **kwargs
                )
            except AdmissionError as exc:
                last_err = exc
                continue
            req.replica = i
            req.replica_id = self._ids[i]
            self._routed += 1
            if i == idx and score > 0:
                self._affinity_hits += 1
            self._affinity_gauge.set(self._affinity_hits / self._routed)
            self.recorder.record(
                "serve/route", rid=req.rid, replica=i, affinity=int(score),
                policy=self.policy, failover=n_try,
            )
            return req
        raise last_err  # every replica refused; surface the final reason

    def cancel(self, request) -> bool:
        """Cancel on whichever replica holds the request.  Resolution order:
        the stable ``replica_id`` (survives detach re-indexing; a request
        whose replica already detached is necessarily finished — drain waits
        for it — so that cancel is simply False), then the positional
        ``replica`` index, then a full scan."""
        rid = getattr(request, "replica_id", None)
        if rid is not None:
            if rid not in self._ids:
                return False  # its replica drained + detached: request done
            return self.engines[self._ids.index(rid)].cancel(request)
        idx = getattr(request, "replica", None)
        if idx is not None and 0 <= idx < len(self.engines):
            return self.engines[idx].cancel(request)
        return any(e.cancel(request) for e in self.engines)

    # ------------------------------------------------------------- elasticity
    def replica_ids(self) -> List[int]:
        """Stable ids of the attached replicas, in ``engines`` order."""
        return list(self._ids)

    def add_replica(self, engine: ServingEngine) -> int:
        """Attach a freshly built replica; it is admittable immediately.
        Returns its stable replica id."""
        self.engines.append(engine)
        rid = self._next_id
        self._next_id += 1
        self._ids.append(rid)
        self._replicas_gauge.set(float(len(self.engines)))
        self.recorder.record(
            "serve/replica_add", replica_id=rid, replicas=len(self.engines),
            weights_version=engine.weights_version,
        )
        return rid

    def drain_replica(self, replica_id: int) -> None:
        """Stop routing NEW requests to ``replica_id``.  Everything it
        already accepted — running lanes AND its queue — runs to completion
        under the normal drive; once idle, :meth:`step` detaches it.  At
        least one replica must stay admitting (drain the front door itself
        by shutting the server down, not by starving the router)."""
        if replica_id not in self._ids:
            raise ValueError(f"unknown replica id {replica_id}")
        remaining = [i for i in self._ids if i not in self._draining]
        if remaining == [replica_id]:
            raise ValueError(
                "cannot drain the last admitting replica; add_replica a "
                "successor first"
            )
        self._draining.add(replica_id)
        self.recorder.record(
            "serve/replica_drain", replica_id=replica_id,
            queue_depth=self.engines[self._ids.index(replica_id)]
            .scheduler.queue_depth,
        )

    def detach_replica(self, replica_id: int) -> ServingEngine:
        """Remove an idle replica and return its engine (callers may keep it
        warm for re-attach).  Raises if it still has work — use
        :meth:`drain_replica` + the drive loop to get it idle first."""
        if replica_id not in self._ids:
            raise ValueError(f"unknown replica id {replica_id}")
        i = self._ids.index(replica_id)
        engine = self.engines[i]
        if engine.has_work:
            raise RuntimeError(
                f"replica {replica_id} still has work "
                f"(queue={engine.scheduler.queue_depth}); drain it first"
            )
        del self.engines[i]
        del self._ids[i]
        self._draining.discard(replica_id)
        self._replicas_gauge.set(float(len(self.engines)))
        self.recorder.record(
            "serve/replica_detach", replica_id=replica_id,
            replicas=len(self.engines),
        )
        return engine

    def _reap_drained(self) -> None:
        """Detach every draining replica that has gone idle."""
        for rid in [r for r in self._ids if r in self._draining]:
            if not self.engines[self._ids.index(rid)].has_work:
                self.detach_replica(rid)

    def hot_swap(self, params: Any, version: Optional[str] = None,
                 max_steps: int = 100_000, step_fn=None) -> int:
        """Rolling zero-downtime weight swap: every attached replica, one at
        a time, pauses admission, drains its lanes while the OTHER replicas
        keep serving (its own queued requests merely wait and then decode
        under the new weights), rebinds ``params`` through
        :meth:`ServingEngine.swap_params` (prefix cache flushed, compiled
        executables reused), and resumes.  No in-flight request is failed or
        served by a mixture of weight versions.  ``step_fn`` (default
        :meth:`step`) is called while waiting for each drain — the HTTP
        front door passes a hook that also keeps servicing its submit inbox.
        Returns the number of replicas swapped."""
        step_fn = step_fn if step_fn is not None else self.step
        swapped = 0
        for rid in list(self._ids):
            if rid not in self._ids or rid in self._draining:
                continue  # detached or draining mid-rollout: skip
            engine = self.engines[self._ids.index(rid)]
            engine.pause_admission()
            try:
                steps = 0
                while not engine.drained:
                    step_fn()
                    steps += 1
                    if steps > max_steps:
                        raise RuntimeError(
                            f"replica {rid} did not drain in {max_steps} steps"
                        )
                engine.swap_params(params, version=version)
                swapped += 1
            finally:
                engine.resume_admission()
        return swapped

    def versions(self) -> dict:
        """``weights_version -> replica count`` over attached replicas (the
        ``/v1/models`` surface)."""
        out: dict = {}
        for e in self.engines:
            out[e.weights_version] = out.get(e.weights_version, 0) + 1
        return out

    # ------------------------------------------------------- lane migration
    def _pick_migration_dst(
        self, src: ServingEngine
    ) -> Optional[ServingEngine]:
        """Least-loaded decode-capable replica whose pool geometry matches
        ``src``'s, or None when nothing can receive a lane right now."""
        cands = [
            e for e in self.engines
            if e is not src and e._poisoned is None
            and self._decode_capable(e)
            and self.migrator.compatible(src, e) is None
        ]
        if not cands:
            return None
        return min(cands, key=self._load)

    def _fallback_replay(self, src: ServingEngine, req: Request) -> None:
        """Migration's non-retriable fallback: retire the lane on ``src``
        and replay the request (prompt + generated-so-far) on a survivor —
        exactly the export/adopt path, for one lane.  Greedy lanes stay
        token-exact; sampled lanes resume re-seeded."""
        if req.slot is not None and src._slot_req[req.slot] is req:
            src._retire_lane(req.slot)
        if src.prefix_cache is not None and req.cache_nodes:
            src.prefix_cache.release(req.cache_nodes)
        req.cache_nodes = []
        req.cached_chunks = 0
        req.cache_chain_broken = False
        req.chunks = ()
        req.next_chunk = 0
        req.slot = None
        req.state = RequestState.QUEUED
        self._replay_one(req)

    def _sweep_handoffs(self) -> None:
        """Disaggregated steady state: every installed lane on a
        ``role="prefill"`` replica has its last prompt chunk landed (install
        happens only then) and is waiting to decode somewhere else — hand
        each off to the least-loaded decode-capable replica.  Destination
        pressure (retriable :class:`MigrationError`) leaves the lane in
        place for the next sweep; a non-retriable failure falls back to
        single-lane replay so no request ever strands on a replica that
        will never decode it."""
        for src in list(self.engines):
            if getattr(src, "role", "both") != "prefill":
                continue
            for s in range(src.num_slots):
                req = src._slot_req[s]
                if req is None or req.state is not RequestState.RUNNING:
                    continue
                dst = self._pick_migration_dst(src)
                if dst is None:
                    return  # no decode capacity anywhere; retry next step
                try:
                    self.migrator.handoff(src, dst, s)
                except MigrationError as exc:
                    if exc.retriable:
                        continue
                    self._fallback_replay(src, req)
                else:
                    i = self.engines.index(dst)
                    req.replica = i
                    req.replica_id = self._ids[i]

    def migrate_lane(
        self,
        from_replica: Optional[int] = None,
        to_replica: Optional[int] = None,
        slot: Optional[int] = None,
        reason: str = "rebalance",
    ) -> bool:
        """Live rebalancing: move one running lane between replicas without
        interrupting its generation.  Replicas are named by stable id
        (:meth:`replica_ids`).  Defaults pick the move a rebalancer wants:
        the hottest source (by queued + active load, among replicas with a
        running lane), its youngest lane (highest rid — least sunk decode
        work behind it), and the coldest compatible decode-capable
        destination.  Returns True when the lane left the source — migrated
        bit-identically, or (non-retriable failure) replayed token-exact
        under greedy; False when nothing could move (no source lane, no
        destination, or a retriable refusal worth retrying later)."""
        if from_replica is not None:
            if from_replica not in self._ids:
                raise ValueError(f"unknown replica id {from_replica}")
            src = self.engines[self._ids.index(from_replica)]
        else:
            hot = [e for e in self.engines
                   if any(r is not None and r.state is RequestState.RUNNING
                          for r in e._slot_req)]
            if not hot:
                return False
            src = max(hot, key=self._load)
        if slot is None:
            running = [(s, r) for s, r in enumerate(src._slot_req)
                       if r is not None and r.state is RequestState.RUNNING]
            if not running:
                return False
            slot = max(running, key=lambda sr: sr[1].rid)[0]
        req = src._slot_req[slot]
        if req is None:
            return False
        if to_replica is not None:
            if to_replica not in self._ids:
                raise ValueError(f"unknown replica id {to_replica}")
            dst = self.engines[self._ids.index(to_replica)]
        else:
            dst = self._pick_migration_dst(src)
            if dst is None:
                return False
        try:
            self.migrator.migrate(src, dst, slot, reason=reason)
        except MigrationError as exc:
            if exc.retriable:
                return False
            self._fallback_replay(src, req)
            return True
        i = self.engines.index(dst)
        req.replica = i
        req.replica_id = self._ids[i]
        return True

    # -------------------------------------------------------- fault recovery
    def _migrate_off(self, engine: ServingEngine) -> None:
        """Failover upgrade (disaggregated policy): while the dying
        replica's pages are still readable, move its RUNNING lanes to
        survivors bit-identically instead of replaying them.  The first
        failure of any kind aborts the remaining attempts — a replica that
        cannot be read coherently falls back to export/replay for
        everything still on it (the lanes it keeps stay untouched, so the
        fallback sees them exactly as a plain ejection would)."""
        for s in range(engine.num_slots):
            req = engine._slot_req[s]
            if req is None or req.state is not RequestState.RUNNING:
                continue
            dst = self._pick_migration_dst(engine)
            if dst is None:
                return
            try:
                self.migrator.migrate(engine, dst, s, reason="failover")
            except Exception as exc:
                # the dying replica could not be read coherently (or the
                # destination refused): record it and let the caller's
                # export/replay pass take everything still on the engine
                self.recorder.record(
                    "serve/migrate_failover_abort", slot=s, error=repr(exc),
                )
                return
            i = self.engines.index(dst)
            req.replica = i
            req.replica_id = self._ids[i]

    def _eject_and_replay(self, engine: ServingEngine, exc: BaseException) -> None:
        """Remove a dead replica and replay everything it owed on survivors.

        The replica's in-flight requests (:meth:`ServingEngine.
        export_inflight` — running lanes as prompt + generated-so-far,
        mid-prefill, queued) are adopted by surviving replicas at the FRONT
        of their queues, least-loaded first: greedy lanes resume token-exact,
        sampled lanes re-seeded.  A request no survivor can fit (geometry
        refusal) is CANCELLED — its stream closes rather than hangs.  The
        dead engine parks behind the half-open circuit breaker; once the
        backoff expires, :meth:`_probe_breaker` revives and re-admits it."""
        if engine not in self.engines:
            return
        i = self.engines.index(engine)
        replica_id = self._ids[i]
        if self.policy == "disaggregated" and len(self.engines) > 1:
            # failover upgrade: lanes whose pages are still readable migrate
            # bit-identically; export_inflight below picks up only what the
            # migration pass could not move
            self._migrate_off(engine)
        exported = engine.export_inflight()
        del self.engines[i]
        del self._ids[i]
        self._draining.discard(replica_id)
        self._replicas_gauge.set(float(len(self.engines)))
        self._ejections += 1
        self._ejections_counter.inc()
        self.recorder.record(
            "serve/failover", replica_id=replica_id, error=repr(exc),
            inflight=len(exported), replicas_left=len(self.engines),
        )
        self._breaker[replica_id] = {
            "engine": engine,
            "failures": 0,
            "open_until": time.monotonic() + self.breaker_base_s,
        }
        # newest first: each appendleft lands in front of the previous one,
        # so per-survivor queue order ends up oldest-rid-first (FCFS intact)
        for req in reversed(exported):
            self._replay_one(req)

    def _replay_one(self, req: Request) -> None:
        pool = range(len(self.engines))
        if self.policy == "disaggregated":
            # replays re-prefill then decode on the adopting engine, so the
            # adopter must be decode-capable (decode-role replicas prefill
            # adopted replays: role shapes steady-state routing, not
            # recovery); prefill-only replicas can never finish the request
            capable = [i for i in pool
                       if self._decode_capable(self.engines[i])]
            pool = capable if capable else pool
        survivors = sorted(
            pool, key=lambda i: self._load(self.engines[i])
        )
        last_err: Optional[Exception] = None
        for i in survivors:
            try:
                self.engines[i].adopt(req)
            except AdmissionError as exc:
                last_err = exc
                continue
            req.replica = i
            req.replica_id = self._ids[i]
            self.recorder.record(
                "serve/replay", rid=req.rid, replica=i,
                generated=len(req.tokens),
            )
            return
        req.state = RequestState.CANCELLED
        req.deadline_exceeded = False
        if req.trace is not None:
            req.trace.annotate(
                "replay_failed",
                error=repr(last_err) if last_err is not None else "no survivors",
            )
            get_reqtrace().complete(req.trace, status="error")
        self.recorder.record(
            "serve/replay_failed", rid=req.rid,
            error=repr(last_err) if last_err is not None else "no survivors",
        )

    def _probe_breaker(self) -> None:
        """Half-open probe: for every ejected replica whose backoff expired,
        try ``revive()`` + one step.  Success re-admits it as a fresh replica
        (new stable id); failure doubles the backoff up to ``breaker_max_s``."""
        if not self._breaker:
            return
        now = time.monotonic()
        for replica_id in [r for r, b in self._breaker.items()
                           if now >= b["open_until"]]:
            entry = self._breaker[replica_id]
            engine = entry["engine"]
            try:
                engine.revive()
                engine.step()  # one idle probe step proves it can run
            except Exception as exc:
                entry["failures"] += 1
                entry["open_until"] = now + min(
                    self.breaker_max_s,
                    self.breaker_base_s * 2 ** entry["failures"],
                )
                self.recorder.record(
                    "serve/breaker_open", replica_id=replica_id,
                    failures=entry["failures"], error=repr(exc),
                )
                continue
            del self._breaker[replica_id]
            new_id = self.add_replica(engine)
            self.recorder.record(
                "serve/breaker_close", replica_id=replica_id, new_id=new_id,
                failures=entry["failures"],
            )

    # ----------------------------------------------------------------- drive
    @property
    def has_work(self) -> bool:
        # a due breaker probe is work: the drive loop must keep stepping so
        # an ejected replica gets its re-admission attempt even when idle
        if any(e.has_work for e in self.engines):
            return True
        now = time.monotonic()
        return any(now >= b["open_until"] for b in self._breaker.values())

    def step(self) -> None:
        """One iteration of every replica that has work (round-robin drive —
        in production each replica runs its own host loop/process; this
        single-threaded drive is what tests and benches use).  Each replica
        runs its own depth-1 pipeline (``async_depth=1``): with window k in
        flight on replica A, the drive moves on to dispatch replica B's
        window while A's device computes, so even the single-threaded drive
        overlaps replicas; ``has_work`` holds until every replica's pipeline
        has drained (an in-flight window counts as work).

        Supervision rides the same loop: a replica whose step raises — or
        that arrives already poisoned (:meth:`ServingEngine.kill`) — is
        ejected and its in-flight requests replay on survivors; ejected
        replicas re-admit through the half-open circuit breaker."""
        if (faults.ACTIVE is not None and len(self.engines) > 1
                and faults.ACTIVE.fire("replica_kill")):
            # kill the busiest replica — the worst case for replay
            victim = max(self.engines, key=lambda e: int(e._active.sum()))
            victim.kill("injected replica kill")
        for engine in list(self.engines):
            if engine not in self.engines:
                continue  # ejected earlier this very step
            if engine._poisoned is not None:
                self._eject_and_replay(engine, engine._poisoned)
                continue
            if not engine.has_work:
                continue
            try:
                engine.step()
            except Exception as exc:
                self._eject_and_replay(engine, exc)
        if self.policy == "disaggregated":
            # after the replicas stepped: any lane whose final prompt chunk
            # just landed on a prefill replica moves to a decode replica now,
            # so its first decode window dispatches next step
            self._sweep_handoffs()
        self._reap_drained()
        self._probe_breaker()

    def run(self, max_steps: Optional[int] = None) -> None:
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(f"router did not drain in {max_steps} steps")

    def serve(self, prompts: Sequence, configs=None) -> List[Request]:
        """Submit every prompt through the router, drain all replicas, return
        the requests in submission order."""
        reqs = []
        for i, p in enumerate(prompts):
            cfg = configs[i] if isinstance(configs, (list, tuple)) else configs
            reqs.append(self.submit(p, config=cfg))
        self.run()
        return reqs

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Sum of every replica's ``stats`` dict, plus router counters and a
        fleet-wide per-tenant rollup (each tenant's counters summed across
        replicas — failover replays land on the adopting engine, so only the
        cross-replica sum is the caller's true account)."""
        out: dict = {}
        for e in self.engines:
            for k, v in e.stats.items():
                out[k] = out.get(k, 0) + v
        out["routed"] = self._routed
        out["affinity_hits"] = self._affinity_hits
        tenants: dict = {}
        for e in self.engines:
            for tenant, counts in getattr(e, "_tenant_stats", {}).items():
                agg = tenants.setdefault(tenant, {})
                for k, v in counts.items():
                    agg[k] = agg.get(k, 0) + v
        if tenants:
            out["tenants"] = tenants
        return out

    def prefix_cache_stats(self) -> dict:
        """Aggregate prefix-cache health across replicas (token-weighted
        hit rate — the router A/B's headline number)."""
        hit = sum(e.stats["prefix_hit_tokens"] for e in self.engines)
        miss = sum(e.stats["prefix_miss_tokens"] for e in self.engines)
        covered = hit + miss
        return {
            "prefix_hit_tokens": hit,
            "prefix_miss_tokens": miss,
            "hit_rate": hit / covered if covered else 0.0,
            "per_replica": [e.prefix_cache_stats() for e in self.engines],
        }

    def health(self) -> dict:
        """One snapshot a front door can poll: per-replica queue/occupancy
        plus the router's routing counters."""
        now = time.monotonic()
        return {
            "replicas": len(self.engines),
            "policy": self.policy,
            "routed": self._routed,
            "affinity_hit_rate": (
                self._affinity_hits / self._routed if self._routed else 0.0
            ),
            "ejections": self._ejections,
            "breaker": [
                {
                    "replica_id": r,
                    "failures": b["failures"],
                    "retry_in_s": max(b["open_until"] - now, 0.0),
                }
                for r, b in self._breaker.items()
            ],
            "versions": self.versions(),
            "per_replica": [
                {
                    "replica_id": self._ids[i],
                    "queue_depth": e.scheduler.queue_depth,
                    "active_lanes": int(e._active.sum()),
                    "role": getattr(e, "role", "both"),
                    "tp_degree": e.tp_degree,
                    "has_work": e.has_work,
                    "draining": self._ids[i] in self._draining,
                    "admission_paused": e.admission_paused,
                    "weights_version": e.weights_version,
                }
                for i, e in enumerate(self.engines)
            ],
        }


__all__ = ["ReplicaRouter"]

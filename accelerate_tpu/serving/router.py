"""Prefix-affinity router over data-parallel :class:`ServingEngine` replicas.

Tensor parallelism (``ServingEngine(mesh=...)``) makes one model span chips;
this module scales the *other* direction: N independent engines — one per
mesh slice (:func:`~accelerate_tpu.parallel.mesh.replica_meshes`) or per
process — behind a single front door.  The routing decision is where the
multi-chip win actually lands: each replica's prefix-cache radix tree holds
the KV for the prefixes *it* has served, so a request routed to the replica
that already holds its prefix replays cached KV instead of re-running
prefill, while a random or round-robin placement scatters a shared prefix
across every replica and pays the prefill everywhere (the reference's
big-model dispatch layer routes to where the weights live; here the hot
state is the prefix KV).

Policy ``"affinity"`` (default): rolling-hash the prompt's leading chunks
against each replica's radix tree (:meth:`PrefixCache.match` — a pure
host-side walk, no device work, no pinning) and score each replica by the
matched token count; the best positive scorer wins, load breaking ties, and
zero-scorers fall back to least-loaded.  Policy ``"round_robin"`` is the
baseline A/B arm (``bench_inference.py --task serve --tp-ab``).

Failover: a replica that rejects a ``submit`` (capacity validation —
e.g. heterogeneous ``max_len``) is skipped and the request tries the
remaining replicas by load; the error propagates only when every replica
refuses.

Telemetry (``docs/usage/observability.md``): ``serve/replicas`` (info),
``serve/router_affinity_hit_rate`` (fraction of routed requests whose chosen
replica already held a matching prefix), and one ``serve/route`` flight
event per submit carrying the chosen replica and its affinity score.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..telemetry import MetricsRegistry, get_flight_recorder, get_registry
from .engine import ServingEngine
from .pool import plan_chunks
from .scheduler import Request

_POLICIES = ("affinity", "round_robin")


class ReplicaRouter:
    """Route :meth:`submit` calls across N engine replicas; aggregate health.

    Parameters
    ----------
    engines: the replicas.  Each owns its KV pool, scheduler, prefix cache,
        and (optionally) its own tp mesh slice; the router never touches
        device state — it only reads each replica's host-side radix tree and
        queue depths.
    policy: ``"affinity"`` (prefix-cache affinity, least-loaded fallback) or
        ``"round_robin"`` (the A/B baseline).
    registry: metrics registry for the router's gauges (defaults to the
        process registry — pass the same private registry benches give their
        engines to keep arms isolated).
    """

    def __init__(
        self,
        engines: Sequence[ServingEngine],
        policy: str = "affinity",
        registry: Optional[MetricsRegistry] = None,
    ):
        if not engines:
            raise ValueError("ReplicaRouter needs at least one engine")
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got {policy!r}")
        self.engines: List[ServingEngine] = list(engines)
        self.policy = policy
        self.metrics = registry if registry is not None else get_registry()
        self.recorder = get_flight_recorder()
        self._rr_next = 0
        self._routed = 0
        self._affinity_hits = 0
        self.metrics.gauge(
            "serve/replicas",
            help="info gauge: engine replicas behind the ReplicaRouter",
        ).set(float(len(self.engines)))
        self._affinity_gauge = self.metrics.gauge(
            "serve/router_affinity_hit_rate",
            help="fraction of routed requests whose chosen replica already "
                 "held a matching prefix in its radix tree",
        )

    # ------------------------------------------------------------- placement
    def _load(self, engine: ServingEngine) -> int:
        """Host-side load proxy: queued + mid-prefill + active lanes.  Under
        the pipelined engine loop (``async_depth=1``) the active count lags
        a finishing lane by one drain — at most one step of load skew per
        replica, in the conservative (over-counting) direction."""
        return engine.scheduler.queue_depth + int(engine._active.sum())

    def _affinity(self, engine: ServingEngine, prompt: np.ndarray) -> int:
        """Tokens of ``prompt`` this replica's radix tree already holds —
        a read-only walk over full leading chunks (LRU touch only; nothing
        is pinned until the engine's own admission runs)."""
        if engine.prefix_cache is None:
            return 0
        chunks = plan_chunks(len(prompt), engine.buckets)
        nodes = engine.prefix_cache.match(prompt, chunks)
        return sum(len(n.tokens) for n in nodes)

    def _choose(self, prompt: np.ndarray) -> tuple:
        """``(replica_index, affinity_score)`` under the configured policy."""
        if self.policy == "round_robin":
            i = self._rr_next % len(self.engines)
            self._rr_next += 1
            return i, 0
        scores = [self._affinity(e, prompt) for e in self.engines]
        best = max(scores)
        if best > 0:
            # highest score wins; load breaks ties among equals
            tied = [i for i, sc in enumerate(scores) if sc == best]
            i = min(tied, key=lambda i: self._load(self.engines[i]))
            return i, best
        i = min(range(len(self.engines)), key=lambda i: self._load(self.engines[i]))
        return i, 0

    # ------------------------------------------------------------ submission
    def submit(
        self,
        prompt,
        config=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        **kwargs: Any,
    ) -> Request:
        """Route one request to a replica and queue it there.  The returned
        :class:`Request` carries ``replica`` — the index it landed on — so
        callers can drive or cancel against the right engine."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        idx, score = self._choose(prompt)
        # failover ladder: chosen replica first, then the rest by load
        order = [idx] + sorted(
            (i for i in range(len(self.engines)) if i != idx),
            key=lambda i: self._load(self.engines[i]),
        )
        last_err: Optional[Exception] = None
        for n_try, i in enumerate(order):
            try:
                req = self.engines[i].submit(
                    prompt, config=config, on_token=on_token, **kwargs
                )
            except ValueError as exc:
                last_err = exc
                continue
            req.replica = i
            self._routed += 1
            if i == idx and score > 0:
                self._affinity_hits += 1
            self._affinity_gauge.set(self._affinity_hits / self._routed)
            self.recorder.record(
                "serve/route", rid=req.rid, replica=i, affinity=int(score),
                policy=self.policy, failover=n_try,
            )
            return req
        raise last_err  # every replica refused; surface the final reason

    def cancel(self, request) -> bool:
        """Cancel on whichever replica holds the request."""
        engines = (
            [self.engines[request.replica]]
            if getattr(request, "replica", None) is not None
            else self.engines
        )
        return any(e.cancel(request) for e in engines)

    # ----------------------------------------------------------------- drive
    @property
    def has_work(self) -> bool:
        return any(e.has_work for e in self.engines)

    def step(self) -> None:
        """One iteration of every replica that has work (round-robin drive —
        in production each replica runs its own host loop/process; this
        single-threaded drive is what tests and benches use).  Each replica
        runs its own depth-1 pipeline (``async_depth=1``): with window k in
        flight on replica A, the drive moves on to dispatch replica B's
        window while A's device computes, so even the single-threaded drive
        overlaps replicas; ``has_work`` holds until every replica's pipeline
        has drained (an in-flight window counts as work)."""
        for e in self.engines:
            if e.has_work:
                e.step()

    def run(self, max_steps: Optional[int] = None) -> None:
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(f"router did not drain in {max_steps} steps")

    def serve(self, prompts: Sequence, configs=None) -> List[Request]:
        """Submit every prompt through the router, drain all replicas, return
        the requests in submission order."""
        reqs = []
        for i, p in enumerate(prompts):
            cfg = configs[i] if isinstance(configs, (list, tuple)) else configs
            reqs.append(self.submit(p, config=cfg))
        self.run()
        return reqs

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Sum of every replica's ``stats`` dict, plus router counters."""
        out: dict = {}
        for e in self.engines:
            for k, v in e.stats.items():
                out[k] = out.get(k, 0) + v
        out["routed"] = self._routed
        out["affinity_hits"] = self._affinity_hits
        return out

    def prefix_cache_stats(self) -> dict:
        """Aggregate prefix-cache health across replicas (token-weighted
        hit rate — the router A/B's headline number)."""
        hit = sum(e.stats["prefix_hit_tokens"] for e in self.engines)
        miss = sum(e.stats["prefix_miss_tokens"] for e in self.engines)
        covered = hit + miss
        return {
            "prefix_hit_tokens": hit,
            "prefix_miss_tokens": miss,
            "hit_rate": hit / covered if covered else 0.0,
            "per_replica": [e.prefix_cache_stats() for e in self.engines],
        }

    def health(self) -> dict:
        """One snapshot a front door can poll: per-replica queue/occupancy
        plus the router's routing counters."""
        return {
            "replicas": len(self.engines),
            "policy": self.policy,
            "routed": self._routed,
            "affinity_hit_rate": (
                self._affinity_hits / self._routed if self._routed else 0.0
            ),
            "per_replica": [
                {
                    "queue_depth": e.scheduler.queue_depth,
                    "active_lanes": int(e._active.sum()),
                    "tp_degree": e.tp_degree,
                    "has_work": e.has_work,
                }
                for e in self.engines
            ],
        }


__all__ = ["ReplicaRouter"]

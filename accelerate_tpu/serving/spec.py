"""Host-side draft proposal for self-speculative decoding.

Decode is memory-bandwidth-bound: one model forward per emitted token per
lane reads the full weight set to produce a single row of logits, leaving the
MXU idle (`serve/decode_flops_per_token` vs the chip's HBM peak makes the gap
visible).  Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding") closes it by *verifying* K cheaply
drafted tokens in ONE batched forward: the verify pass computes the true
next-token distribution at every drafted position, and an accept/commit rule
keeps the output distribution exactly what non-speculative decode would have
produced — for greedy decode, token-for-token identical.

The drafter here is **prompt-lookup / n-gram matching** (the draft-model-free
scheme popularized by vLLM's ngram speculator): each lane's draft is the
continuation of the most recent earlier occurrence of its trailing n-gram in
its own context (prompt + generated tokens).  No second model, no extra
params, no device work — a numpy suffix match per lane per cycle.  It shines
on repetitive or structured output (code, JSON, extraction, long quotes of
the prompt) where the continuation literally already appears in the context,
and degrades to nothing on high-entropy text — which is why the engine falls
back to the plain decode window whenever no lane drafts.

Device-side verification lives in :func:`~.pool.make_verify_window`; the
engine (:mod:`.engine`) wires the two together per cycle.

Drafting is the one serve-loop stage that is *inherently sequential* with
the previous window: a lane's draft extends its own freshest context, so the
pipelined loop (``ServingEngine(async_depth=1)``) drains the in-flight
window before calling :func:`propose_ngram_draft` — speculative cycles
overlap scheduling/admission with device compute, but not drafting or
``_emit``.  Keep the per-lane cost here strictly O(context) numpy with no
device interaction: this function runs on the host's critical path between
a drain and the next dispatch.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


def propose_ngram_draft(
    context: np.ndarray,
    k: int,
    max_ngram: int = 3,
    min_ngram: int = 1,
    pad: int = 0,
) -> Optional[np.ndarray]:
    """Draft ``k`` tokens by prompt-lookup: find the most recent earlier
    occurrence of the longest trailing n-gram of ``context`` and return the
    tokens that followed it.

    Tries n-gram sizes from ``max_ngram`` down to ``min_ngram`` (longer
    matches draft with higher acceptance).  The match must end strictly
    before the context's tail (the trailing n-gram itself never matches) and
    have at least one following token.

    A match at lag ``L`` from the tail implies the context is locally
    periodic with period ``L``, so the draft extends *cyclically*:
    ``draft[j] = context[start + (j % L)]``.  For matches deep in the
    context this is just the ``k`` literal follower tokens; for the common
    steady-state case — generation locked into a cycle shorter than ``k``,
    where the most recent match sits one period from the tail — it predicts
    whole future periods instead of running out of context (drafting past
    the end and padding would cap acceptance at the cycle length).

    Returns the ``[k]`` int32 draft, or ``None`` when no n-gram recurs —
    the caller falls back to ordinary decode for this lane.  ``pad`` is
    accepted for signature stability but never needed (cyclic extension
    always fills all ``k`` slots).
    """
    context = np.ascontiguousarray(context, dtype=np.int32)
    n_ctx = int(context.size)
    if k <= 0 or min_ngram < 1 or n_ctx < min_ngram + 1:
        return None
    for n in range(min(max_ngram, n_ctx - 1), min_ngram - 1, -1):
        tail = context[n_ctx - n:]
        # candidate windows start at 0..n_ctx-n-2: they end strictly before
        # the tail starts a new copy AND leave >= 1 token to draft from
        windows = np.lib.stride_tricks.sliding_window_view(context[: n_ctx - 1], n)
        hits = np.nonzero((windows == tail).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + n          # most recent match wins
            lag = n_ctx - start                # local period implied by the match
            return context[start + (np.arange(k) % lag)]
    return None


class NgramIndex:
    """Incremental per-lane suffix index: :func:`propose_ngram_draft` without
    the per-cycle O(context) rescan.

    The brute-force matcher re-walks the whole context every verify cycle to
    find the most recent earlier occurrence of the trailing n-gram.  This
    index instead keeps, for every n-gram size, a dict mapping each window
    (as a token tuple) to the *latest* start position where it occurs —
    maintained by :meth:`append` in O(max_ngram) per committed token, so
    steady-state drafting is O(k) per cycle regardless of context length.

    Equivalence with the rescan: the brute force takes ``hits[-1]`` (the
    largest matching start over windows of ``context[:n_ctx - 1]``), and the
    dict records each start exactly once in increasing order, so its value
    IS the largest start seen.  :meth:`append` records the window *ending
    just before* the new token, which keeps the trailing n-gram itself out of
    the index until a later token makes it an "earlier" occurrence — the
    same strict-before-the-tail rule the sliding-window scan enforces.
    Token-identical by construction; ``TestNgramDraft`` pins both paths to
    the same goldens.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1) -> None:
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got [{min_ngram}, {max_ngram}]"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self._ctx: list = []
        self._idx: Dict[int, Dict[Tuple[int, ...], int]] = {
            n: {} for n in range(min_ngram, max_ngram + 1)
        }

    def __len__(self) -> int:
        return len(self._ctx)

    def append(self, token: int) -> None:
        """Commit one token: index every window that *ends* at the old tail
        (the new token is its follower), then grow the context."""
        ctx, L = self._ctx, len(self._ctx)
        for n in range(self.min_ngram, min(self.max_ngram, L) + 1):
            self._idx[n][tuple(ctx[L - n:])] = L - n
        ctx.append(int(token))

    def extend(self, tokens) -> None:
        for t in np.asarray(tokens, dtype=np.int32).ravel():
            self.append(int(t))

    def propose(self, k: int) -> Optional[np.ndarray]:
        """O(k) draft: longest trailing n-gram whose latest earlier start is
        on record, extended cyclically exactly like the rescan path."""
        ctx, n_ctx = self._ctx, len(self._ctx)
        if k <= 0 or n_ctx < self.min_ngram + 1:
            return None
        for n in range(min(self.max_ngram, n_ctx - 1), self.min_ngram - 1, -1):
            s = self._idx[n].get(tuple(ctx[n_ctx - n:]))
            if s is not None:
                start = s + n
                lag = n_ctx - start
                return np.asarray(
                    [ctx[start + (j % lag)] for j in range(k)], dtype=np.int32
                )
        return None

"""Host-side draft proposal for self-speculative decoding.

Decode is memory-bandwidth-bound: one model forward per emitted token per
lane reads the full weight set to produce a single row of logits, leaving the
MXU idle (`serve/decode_flops_per_token` vs the chip's HBM peak makes the gap
visible).  Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding") closes it by *verifying* K cheaply
drafted tokens in ONE batched forward: the verify pass computes the true
next-token distribution at every drafted position, and an accept/commit rule
keeps the output distribution exactly what non-speculative decode would have
produced — for greedy decode, token-for-token identical.

The drafter here is **prompt-lookup / n-gram matching** (the draft-model-free
scheme popularized by vLLM's ngram speculator): each lane's draft is the
continuation of the most recent earlier occurrence of its trailing n-gram in
its own context (prompt + generated tokens).  No second model, no extra
params, no device work — a numpy suffix match per lane per cycle.  It shines
on repetitive or structured output (code, JSON, extraction, long quotes of
the prompt) where the continuation literally already appears in the context,
and degrades to nothing on high-entropy text — which is why the engine falls
back to the plain decode window whenever no lane drafts.

Device-side verification lives in :func:`~.pool.make_verify_window`; the
engine (:mod:`.engine`) wires the two together per cycle.

Drafting is the one serve-loop stage that is *inherently sequential* with
the previous window: a lane's draft extends its own freshest context, so the
pipelined loop (``ServingEngine(async_depth=1)``) drains the in-flight
window before calling :func:`propose_ngram_draft` — speculative cycles
overlap scheduling/admission with device compute, but not drafting or
``_emit``.  Keep the per-lane cost here strictly O(context) numpy with no
device interaction: this function runs on the host's critical path between
a drain and the next dispatch.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def propose_ngram_draft(
    context: np.ndarray,
    k: int,
    max_ngram: int = 3,
    min_ngram: int = 1,
    pad: int = 0,
) -> Optional[np.ndarray]:
    """Draft ``k`` tokens by prompt-lookup: find the most recent earlier
    occurrence of the longest trailing n-gram of ``context`` and return the
    tokens that followed it.

    Tries n-gram sizes from ``max_ngram`` down to ``min_ngram`` (longer
    matches draft with higher acceptance).  The match must end strictly
    before the context's tail (the trailing n-gram itself never matches) and
    have at least one following token.

    A match at lag ``L`` from the tail implies the context is locally
    periodic with period ``L``, so the draft extends *cyclically*:
    ``draft[j] = context[start + (j % L)]``.  For matches deep in the
    context this is just the ``k`` literal follower tokens; for the common
    steady-state case — generation locked into a cycle shorter than ``k``,
    where the most recent match sits one period from the tail — it predicts
    whole future periods instead of running out of context (drafting past
    the end and padding would cap acceptance at the cycle length).

    Returns the ``[k]`` int32 draft, or ``None`` when no n-gram recurs —
    the caller falls back to ordinary decode for this lane.  ``pad`` is
    accepted for signature stability but never needed (cyclic extension
    always fills all ``k`` slots).
    """
    context = np.ascontiguousarray(context, dtype=np.int32)
    n_ctx = int(context.size)
    if k <= 0 or min_ngram < 1 or n_ctx < min_ngram + 1:
        return None
    for n in range(min(max_ngram, n_ctx - 1), min_ngram - 1, -1):
        tail = context[n_ctx - n:]
        # candidate windows start at 0..n_ctx-n-2: they end strictly before
        # the tail starts a new copy AND leave >= 1 token to draft from
        windows = np.lib.stride_tricks.sliding_window_view(context[: n_ctx - 1], n)
        hits = np.nonzero((windows == tail).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + n          # most recent match wins
            lag = n_ctx - start                # local period implied by the match
            return context[start + (np.arange(k) % lag)]
    return None

"""Deferred device->host readback for the pipelined serve loop.

The synchronous engine loop materializes every window's tokens immediately
after dispatch, so the device idles while the host runs ``_emit``, streaming
callbacks, drafting, and admission — and the host idles while the device
computes.  With ``ServingEngine(async_depth=1)`` the engine instead parks the
window's device-side outputs in a :class:`Readback` handle, dispatches the
NEXT window first, and only then materializes the previous window's tokens:
JAX's async dispatch queues the new window behind the old one, so the
blocking :func:`fetch` returns as soon as the *old* window finishes while the
new one keeps the device busy under the host's emit/scheduling work.

:func:`fetch` is the ONE sanctioned blocking device->host transfer in the
serving hot path — atpu-lint's ``blocking-readback`` rule lints every other
``jax.device_get`` / ``block_until_ready`` out of ``accelerate_tpu/serving``
so a stray eager readback cannot silently re-serialize the pipeline.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, List, Optional

import jax
import numpy as np

__all__ = ["Readback", "fetch"]


def fetch(*arrays):
    """Materialize device arrays on the host (blocking).

    Blocks until the computation producing each array has finished; all
    outputs of one jitted window materialize together, so fetching a window's
    tokens also guarantees its KV writes have landed — the invariant the
    deferred page release in :meth:`Readback.settle` relies on.
    """
    out = tuple(np.asarray(jax.device_get(a)) for a in arrays)  # noqa: blocking-readback
    return out[0] if len(out) == 1 else out


@dataclasses.dataclass
class Readback:
    """One in-flight decode/verify window: the device handles to its outputs
    plus the dispatch-time host state needed to land them later.

    The handle is created at dispatch and drained at most one cycle later
    (depth-1 pipeline).  ``active``/``reqs``/``eos`` snapshot the lane state
    the window was dispatched under: between dispatch and drain the host may
    cancel a lane, preempt it, or install a new request into a slot the
    window still considers live, so ``_emit`` must mask by what the *device*
    saw, and retire-by-identity (``engine._slot_req[s] is reqs[s]``) rather
    than by slot number.
    """

    kind: str                      # "decode" | "verify"
    toks: Any                      # device [slots, width] token block
    width: int                     # decode window width / speculate_k + 1
    counts: Any = None             # device [slots] n_commit (verify only)
    qerr: Any = None               # device KV quantization round-trip error
    active: Optional[np.ndarray] = None   # dispatch-time active mask (copy)
    reqs: Optional[list] = None           # dispatch-time _slot_req snapshot
    eos: Optional[np.ndarray] = None      # dispatch-time per-lane EOS ids
    n_occupied: int = 0
    drafted: Optional[np.ndarray] = None  # verify: lanes that proposed drafts
    n_drafted: int = 0
    dispatch_t: float = dataclasses.field(default_factory=time.perf_counter)
    #: physical KV page ids whose deref was deferred because this window may
    #: still write through the block table it was dispatched with; settled
    #: (dereffed) only after :func:`fetch` proves the window retired.
    deferred_pages: List[int] = dataclasses.field(default_factory=list)
    #: slots retired *predictively* after this window dispatched: their lane
    #: provably exhausts its length budget inside this window (no EOS
    #: configured, fixed decode width), so the engine freed the slot for
    #: re-admission one cycle early.  ``_emit`` lands these lanes' tokens
    #: even though the slot has a new owner — the pre-freed request is DONE
    #: at drain, not dropped.
    prefreed: set = dataclasses.field(default_factory=set)
    #: device handles this window (or a lane edit enqueued just before it)
    #: consumed: the previous cycle's donated pool/pending/rng and any lane
    #: vectors replaced by an install scatter.  Dropping the last Python
    #: reference to such a handle *blocks until the consuming computation
    #: finishes* — exactly the stall the pipeline exists to avoid — so the
    #: engine parks the old references here and lets them die with the
    #: handle, after :func:`fetch` proved the window retired.
    consumed: list = dataclasses.field(default_factory=list)
    #: device quant-error scalars from prefill chunks dispatched in this
    #: window's cycle (interleaved chunked prefill): fetching one eagerly
    #: would sync the pipeline right after the chunk enqueued, so the engine
    #: parks the handles here and folds them into the quant-error gauge at
    #: drain — by which point the chunks have long retired behind the window.
    prefill_qerrs: list = dataclasses.field(default_factory=list)
    #: pending prefix-cache spills riding this window: ``(node, handles)``
    #: pairs whose D2H gathers were enqueued before this window dispatched.
    #: Fetching a gather eagerly would sync the pipeline at eviction time, so
    #: the engine parks the handles here and lands them into the node's host
    #: payload at drain — behind the same blocking point everything else
    #: syncs at.
    spills: list = dataclasses.field(default_factory=list)
    #: spilled-prefix promotions dispatched behind this window (host -> device
    #: H2D install records): completion is acknowledged at drain, where the
    #: install has provably retired with the window it was enqueued behind.
    promotions: list = dataclasses.field(default_factory=list)

    def lane_live(self, slot: int) -> bool:
        """Was ``slot`` active when this window was dispatched?  A live lane's
        pages must not return to the allocator until the window retires."""
        return self.active is not None and bool(self.active[slot])

    def live_requests(self):
        """``(slot, request)`` pairs for lanes live at dispatch — the lanes
        this window owes tokens to (pre-freed lanes included: they were
        active when the window dispatched).  Drain-side per-request
        attribution (``engine._trace_drain``) iterates these against the
        dispatch-time snapshot, not the possibly-moved-on live state."""
        if self.active is None or self.reqs is None:
            return
        for s in np.nonzero(self.active)[0]:
            req = self.reqs[s]
            if req is not None:
                yield int(s), req

    def settle(self, allocator) -> int:
        """Deref every deferred page (call only after :func:`fetch` on this
        window's outputs — i.e. after its KV writes provably landed)."""
        if not self.deferred_pages:
            return 0
        freed = allocator.deref(self.deferred_pages)
        self.deferred_pages = []
        return freed

"""Deterministic, seeded fault injection for the serving stack.

Chaos testing a continuous-batching engine is only useful when the chaos is
reproducible: a flaky failure that cannot be replayed cannot be debugged.
This module provides named injection points threaded through the serving hot
path — decode-window dispatch, the one sanctioned blocking ``fetch``, the KV
page pool, weight hot-swap upload, SSE handler writes, and whole-replica
kills — each driven by its own seeded PRNG stream so a given
``(seed, point)`` pair always fires on the same sequence of checks no matter
how the other points interleave.

Off by default with zero hot-path cost: every call site is guarded by
``if faults.ACTIVE is not None`` (a module-attribute load and an ``is``
check), no new jitted executables are created, and nothing below this module
imports it.

Enable with the ``ATPU_FAULTS`` environment variable or programmatically::

    ATPU_FAULTS="seed=7,decode_dispatch=0.02,fetch_slow=0.05,replica_kill@40"

    from accelerate_tpu.serving import faults
    faults.install(faults.FaultPlan(seed=7, probs={"fetch_fail": 0.01}))
    ...
    faults.clear()

Plan entries are either probabilistic (``point=p`` fires each check with
probability ``p``) or one-shot (``point@n`` fires exactly once, on the n-th
check of that point, 1-based).  ``slow_ms=<float>`` sets the stall injected
by ``fetch_slow``.  See ``docs/usage/fault_tolerance.md``.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..telemetry import get_flight_recorder, get_registry

__all__ = [
    "FAULT_POINTS",
    "FaultInjected",
    "FaultInjector",
    "FaultPlan",
    "ACTIVE",
    "install",
    "clear",
]

#: Every injection point wired into the serving stack.  ``FaultPlan.parse``
#: rejects unknown names so a typo in ``ATPU_FAULTS`` fails loudly instead of
#: silently injecting nothing.
FAULT_POINTS = (
    "decode_dispatch",    # raise before the decode-window dispatch (engine)
    "fetch_slow",         # stall the sanctioned blocking fetch by slow_ms
    "fetch_fail",         # raise from the sanctioned blocking fetch
    "page_exhaustion",    # force one preemption as if the page pool ran dry
    "hot_swap_upload",    # raise mid weight upload, after the drain barrier
    "handler_disconnect", # break the SSE socket write (client vanished)
    "replica_kill",       # poison the busiest replica wholesale (router)
    "promote_h2d",        # raise before a spilled-prefix H2D promotion (engine)
    "migrate_d2d",        # raise mid device-to-device page migration (transfer)
    "migrate_bounce",     # raise mid pinned-host-bounce page migration (transfer)
)


class FaultInjected(RuntimeError):
    """Raised by an injection point standing in for a real infrastructure
    failure (XLA dispatch error, device disconnect, torn upload)."""


@dataclass
class FaultPlan:
    """What to inject, with what probability or at which check.

    ``probs`` maps point name -> per-check fire probability in ``[0, 1]``.
    ``at`` maps point name -> 1-based check index that fires exactly once.
    A point may appear in at most one of the two.
    """

    seed: int = 0
    probs: Dict[str, float] = field(default_factory=dict)
    at: Dict[str, int] = field(default_factory=dict)
    slow_ms: float = 10.0

    def __post_init__(self) -> None:
        for name in (*self.probs, *self.at):
            if name not in FAULT_POINTS:
                raise ValueError(
                    f"unknown fault point {name!r}; known: {FAULT_POINTS}"
                )
        dup = set(self.probs) & set(self.at)
        if dup:
            raise ValueError(
                f"fault point(s) {sorted(dup)} listed both probabilistically "
                "and one-shot; pick one form per point"
            )
        for name, p in self.probs.items():
            if not 0.0 <= float(p) <= 1.0:
                raise ValueError(f"{name}={p}: probability must be in [0, 1]")
        for name, n in self.at.items():
            if int(n) < 1:
                raise ValueError(f"{name}@{n}: check index is 1-based")

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``ATPU_FAULTS`` comma-separated plan syntax.

        ``seed=7,decode_dispatch=0.02,replica_kill@40,slow_ms=25``
        """
        seed, slow_ms = 0, 10.0
        probs: Dict[str, float] = {}
        at: Dict[str, int] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "@" in part:
                name, _, idx = part.partition("@")
                at[name.strip()] = int(idx)
            elif "=" in part:
                name, _, val = part.partition("=")
                name = name.strip()
                if name == "seed":
                    seed = int(val)
                elif name == "slow_ms":
                    slow_ms = float(val)
                else:
                    probs[name] = float(val)
            else:
                raise ValueError(
                    f"bad fault plan entry {part!r}: expected point=prob, "
                    "point@n, seed=<int>, or slow_ms=<float>"
                )
        return cls(seed=seed, probs=probs, at=at, slow_ms=slow_ms)


class FaultInjector:
    """Seeded decision engine behind every injection point.

    Each point gets its own ``random.Random(f"{seed}:{point}")`` stream and
    its own check counter, so whether ``fetch_slow`` fires on its 12th check
    is a pure function of ``(seed, point)`` — independent of how many times
    the other points were consulted in between.  ``fire`` is thread-safe:
    injection points are hit from the driver thread, HTTP handler threads,
    and tests concurrently.
    """

    def __init__(self, plan: FaultPlan, registry=None) -> None:
        self.plan = plan
        self._lock = threading.Lock()
        self._checks: Dict[str, int] = {p: 0 for p in FAULT_POINTS}
        self._fired: Dict[str, int] = {p: 0 for p in FAULT_POINTS}
        self._rngs = {
            p: random.Random(f"{plan.seed}:{p}") for p in plan.probs
        }
        self.metrics = registry if registry is not None else get_registry()
        self.recorder = get_flight_recorder()
        self._injected = self.metrics.counter(
            "serve/faults_injected_total",
            help="Faults fired by the chaos injector, all points",
        )

    @property
    def slow_ms(self) -> float:
        return self.plan.slow_ms

    def checks(self, point: str) -> int:
        with self._lock:
            return self._checks[point]

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired[point]

    def fire(self, point: str) -> bool:
        """One consultation of ``point``: returns True when the plan says
        this check is the one that fails, recording the injection."""
        with self._lock:
            self._checks[point] += 1
            n = self._checks[point]
            if point in self.plan.at:
                hit = n == self.plan.at[point]
            elif point in self.plan.probs:
                hit = self._rngs[point].random() < self.plan.probs[point]
            else:
                return False
            if not hit:
                return False
            self._fired[point] += 1
        self._injected.inc()
        self.recorder.record("serve/fault", point=point, check=n)
        return True


#: The process-wide injector consulted by every call site, or None (the
#: default) for zero-cost pass-through.  Initialised from ``ATPU_FAULTS`` at
#: import so chaos plans reach subprocess benchmarks without code changes.
ACTIVE: Optional[FaultInjector] = None


def install(plan, registry=None) -> FaultInjector:
    """Activate fault injection for this process.  ``plan`` is a
    ``FaultPlan`` or the ``ATPU_FAULTS`` string syntax."""
    global ACTIVE
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    ACTIVE = FaultInjector(plan, registry=registry)
    return ACTIVE


def clear() -> None:
    """Deactivate fault injection (restores the zero-cost path)."""
    global ACTIVE
    ACTIVE = None


_env_plan = os.environ.get("ATPU_FAULTS", "").strip()
if _env_plan:
    install(_env_plan)
del _env_plan

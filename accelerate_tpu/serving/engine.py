"""Continuous-batching serving engine over the slot-based KV pool.

The static ``generate`` path is one whole-batch program: every request starts
together and runs exactly ``max_new_tokens`` steps, so at mixed request
lengths the batch's tokens/s collapses to the longest request's schedule.
:class:`ServingEngine` instead runs iteration-level scheduling (Orca-style)
against a fixed set of compiled executables (:mod:`.pool`):

1. a request queue admits FCFS into freed slots, prefilling chunked under a
   per-step token budget (:mod:`.scheduler`);
2. a masked decode window advances every occupied slot; EOS or the length cap
   frees a slot the same step it fires;
3. freed slots are reused by queued requests without disturbing running lanes.

Everything dynamic lives on the host; the device only ever sees
``1 + len(prefill_buckets) + 1`` shapes (decode window, per-bucket prefill,
insert), plus ``len(prefill_buckets)`` fixed copy shapes when the prefix
cache is enabled, plus one verify-window shape when ``speculate_k > 0``
(or a tree-verify + draft-forward pair when ``draft_model`` is set).
See ``docs/usage/serving.md``.

Speculative decoding (``speculate_k > 0``): each cycle the host proposes K
draft tokens per lane by n-gram prompt-lookup (:mod:`.spec` — incrementally
indexed per lane, O(K) per cycle) and, when at least one lane drafts, ONE
verify forward over ``[slots, K+1]`` positions
(:func:`.pool.make_verify_window`) lands 1..K+1 tokens per lane — greedy
outputs token-exact vs plain decode, sampled outputs distribution-exact
(Leviathan accept/resample).  Cycles with no draft fall back to the decode
window, so non-repetitive workloads never regress.

Tree speculation (``draft_model=``): an on-device draft model — by default a
truncated-layer head of the served model (:func:`.spec_exec.build_draft`) —
drafts a ``1 + tree_width * tree_depth``-node token tree per lane in ONE
small jitted forward (:func:`.spec_exec.make_draft_forward`), and a tree
verify window (:func:`.pool.make_tree_verify_window`) scores all nodes under
the ancestor attention mask and commits the best root-to-leaf path:
Leviathan acceptance generalized to branch selection, so outputs stay
token-exact (greedy) / distribution-exact (sampled).  Unlike n-gram lookup,
the draft model speculates on *non-repetitive* text; the compiled budget
grows by exactly two shapes: ``draft_forward`` and ``tree_verify_window``
(which replaces the linear verify window).  See ``docs/usage/serving.md``.

Prefix caching (:mod:`.prefix_cache`): freshly prefilled full chunks are
retained as device KV slabs in a radix tree keyed by the token prefix; later
requests sharing that prefix replay the slabs through one
``dynamic_update_slice`` per chunk instead of re-running prefill.  Outputs
are token-exact with the cache on or off — only redundant prefill compute is
skipped; the decode path never changes.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..logging import get_logger
from ..models.generation import GenerationConfig
from ..models.transformer import KVCache, Transformer
from ..telemetry import (
    CostTable,
    MetricsRegistry,
    RecompileWatchdog,
    detect_device_peaks,
    get_flight_recorder,
    get_registry,
    get_reqtrace,
    get_tracer,
    slo_tick,
    start_debug_server,
)
from . import faults, transfer
from .errors import AdmissionError
from .paging import DraftContextWindow, PagedKVPool
from .pool import (
    ServeShardings,
    audit_donation,
    jit_cache_sizes,
    make_copy_chunk,
    make_copy_page,
    make_decode_window,
    make_insert,
    make_lane_install,
    make_paged_decode_window,
    make_paged_prefill_chunk,
    make_paged_tree_verify_window,
    make_paged_verify_window,
    make_prefill_chunk,
    make_promote_install,
    make_spill_extract,
    make_tree_verify_window,
    make_verify_window,
    plan_chunks,
)
from .prefix_cache import PrefixCache
from .readback import Readback, fetch
from .scheduler import Request, RequestState, Scheduler
from .spec_exec import (
    NgramDrafter,
    TreeDrafter,
    TreeSpec,
    build_draft,
    make_draft_forward,
)

logger = get_logger(__name__)

# Serving latencies live between ~100 us (a CPU-test decode step) and ~100 s
# (a deep queue on a loaded pool): 24 x2 buckets from 100 us cover it.
_LATENCY_BUCKETS = tuple(1e-4 * 2.0**i for i in range(24))

# Process-wide replica ids ("e0", "e1", ...): every flight-recorder event and
# request-trace phase an engine emits is tagged with its id so multi-replica
# rings stay disambiguable (the process-global recorder bit PR 14's bench).
_ENGINE_IDS = itertools.count()


class _Stats(dict):
    """``ServingEngine.stats``: a plain numeric dict (benches reset it in
    place, ``ReplicaRouter.stats`` sums its items) that is *also* callable —
    ``engine.stats()`` returns a copy augmented with the per-request trace
    rollup under ``"requests"``."""

    def __call__(self) -> dict:
        out = dict(self)
        engine = getattr(self, "engine", None)
        out["requests"] = (
            get_reqtrace().summary(engine_id=engine.engine_id)
            if engine is not None else {}
        )
        out["tenants"] = (
            {t: dict(v) for t, v in engine._tenant_stats.items()}
            if engine is not None else {}
        )
        return out


class ServingEngine:
    """Serve many requests through one slot pool with in-flight admission.

    Parameters
    ----------
    model, params: the flagship ``Transformer`` and its (HBM-resident) params.
    num_slots: concurrent request lanes in the KV pool.
    max_len: per-slot KV capacity (default ``config.max_seq_len``).  A request
        needs ``prompt_len + max_new_tokens + decode_window <= max_len``.
    prefill_buckets: fixed chunk sizes for chunked prefill — one compiled
        prefill shape per bucket.  Defaults to ``(128, 512)`` clipped to
        ``max_prompt_len``.
    max_prompt_len: scratch-cache capacity (longest admissible prompt);
        defaults to ``max_len``.
    prefill_token_budget: max prefill tokens charged per engine step (bounds
        decode-latency jitter while prompts stream in); default: the largest
        bucket.
    decode_window: decode steps fused per engine step (one ``lax.scan``
        executable).  Larger windows amortize host round-trips; a request
        finishing mid-window wastes at most ``window - 1`` masked lane-steps.
    slot_order: optional slot-id preference for admission (tests permute this
        to pin down lane independence).
    prefix_cache_mb: byte budget (MiB) for the chunk-granular prefix KV cache
        (:mod:`.prefix_cache`); ``0``/``None`` disables it.  Requests opt out
        per-request via ``submit(..., cache_prefix=False)``.
    prefix_host_mb: byte budget (MiB) for the host-RAM spill tier behind the
        device prefix cache (paged mode only).  Device-tier evictions demote
        their pages host-side via an async D2H gather instead of dropping
        them; a later hit on a spilled prefix promotes it back with an H2D
        scatter-install enqueued BEHIND the in-flight decode window, charging
        zero prefill budget.  ``0`` (the default) disables the tier and keeps
        every existing code path byte-identical.
    prefix_disk_mb: optional disk ring (MiB) behind the host tier; host-tier
        evictions of landed payloads park as ``.npz`` files instead of
        dropping.  Requires ``prefix_host_mb > 0`` and ``prefix_disk_dir``.
    prefix_disk_dir: directory for the disk ring's page files.
    speculate_k: draft length K for self-speculative decoding; ``0`` (the
        default) disables it.  Cycles where at least one lane has an n-gram
        draft run one verify forward over ``[slots, K+1]`` positions instead
        of the decode window, landing 1..K+1 tokens per lane; draftless
        cycles fall back to the decode window.  Greedy outputs are
        token-exact either way; sampled outputs preserve the distribution
        but not the sample stream.  Adds exactly one compiled executable.
        Per-request opt-out: ``submit(..., speculate=False)``.
    speculate_ngram: longest trailing n-gram the draft proposer tries
        (:func:`~accelerate_tpu.serving.spec.propose_ngram_draft`).
    draft_model: switch speculation to an on-device draft model verified
        over a token tree.  ``int n`` — self-speculation: the first ``n``
        layers of the served model (re-sliced on every :meth:`swap_params`);
        ``str path`` — a HF checkpoint dir streamed through
        :mod:`~accelerate_tpu.models.hf_compat` (optionally ``"dir#n"`` to
        truncate to ``n`` layers); ``(cfg, params)`` — an explicit pre-built
        draft.  Replaces the linear verify window with the tree verify
        window plus one draft-forward executable; requires a full-causal
        model (no sliding window / alibi).
    tree_width: sibling branches at the tree's branch point (draft-model
        top-k candidates); ``1`` (default) drafts a single greedy chain —
        the linear window shape, still verified through the tree machinery.
        Requires ``draft_model``.
    tree_depth: draft chain length below each branch candidate; defaults to
        ``speculate_k`` when set, else 4.  The tree verifies
        ``1 + tree_width * tree_depth`` nodes per lane and commits at most
        ``tree_depth + 1`` tokens.  Under ``decode_kernel="pallas"`` the
        node count must stay <= 32 (ancestor masks pack into uint32 rows).
    draft_ctx: host-side sliding context window the stateless draft forward
        re-prefills each cycle (:class:`~.paging.DraftContextWindow`).
    metrics_port: start (or join) the process-wide debug server
        (``/metrics``, ``/healthz``, ``/debug/flight``, ``/debug/stacks``)
        on this port; ``0`` binds an ephemeral port, ``None`` defers to
        ``ATPU_METRICS_PORT`` (off when unset).
    paged: run the KV pool as a refcounted *page pool* with per-lane block
        tables (:mod:`.paging`) instead of per-lane ``max_len`` slabs.  Pages
        are allocated as lanes grow, prefix-cache hits alias shared pages with
        ZERO copies (copy-on-write only on a shared tail page), and page
        pressure preempts the youngest lane — it releases its pages and
        requeues for replay through the prefix cache.  Greedy outputs are
        token-identical paged on/off (the gathered view is exactly the slab
        shape, so the attention program is bitwise the same; keep
        ``max_prompt_len == max_len``, the default, for strict identity) and
        greedy replay after preemption is token-exact; a preempted *sampled*
        lane resumes on a restarted RNG stream (the lane RNG re-seeds from
        the request id at install), so its continuation is
        distribution-correct but not sample-exact — the same contract as
        speculative decoding.
    page_size: tokens per KV page (paged mode).  Must divide every prefill
        bucket and ``max_len``; default ``gcd(prefill_buckets)`` — the prefix
        cache's chunk granularity.
    num_pages: physical pages in the pool (paged mode), the knob that trades
        HBM for concurrency: lanes only consume pages they actually use, so
        ``num_pages`` can be far below ``num_slots * max_len / page_size``
        under mixed-length traffic.  Default is the no-preemption worst case
        (``num_slots * max_len / page_size + 1``).
    decode_kernel: attention program for the paged decode/verify windows.
        ``"xla"`` (default) gathers each lane's pages into a slab-width view
        and runs the legacy attention einsum — bitwise token-identical with
        the slab pool.  ``"pallas"`` reads KV pages *in place* through the
        block tables (:mod:`accelerate_tpu.ops.paged_attention`): no gather
        temporary, no padding reads — one grid program per (lane, kv-head)
        with an online softmax over each lane's live pages only.  Same
        compiled-shape budget (the kernel replaces the decode executables, it
        does not add any); greedy outputs are token-identical in practice
        (asserted by tests and ``bench_inference.py --kernel-ab``) but the
        online softmax is not bitwise the full-view softmax.  Requires
        ``paged=True``; full-causal rope/learned models only.
    prefill_kernel: attention program for the paged *prefill chunk*
        executables.  ``None`` (default) follows the resolved
        ``decode_kernel`` — a pool that decodes through the Pallas kernel
        prefills through its chunk-wide twin
        (:func:`~accelerate_tpu.ops.paged_attention.paged_flash_prefill`),
        a pool on the XLA reference stays on it.  ``"pallas"`` reads prior
        pages in place with a q-blocked flash online softmax and writes the
        chunk's K/V straight into the page pool (scatter-time quantization
        included) — no gather temporary, no scatter round-trip.  ``"xla"``
        forces the gather/scatter reference path (the tp>1 fallback, and the
        bisection knob when a prefill divergence is suspected).  Same
        compiled-shape budget either way (the kernel replaces the per-bucket
        prefill executables' attention, it adds none).  Requires
        ``paged=True``; full-causal rope/learned models only.
    interleave_prefill: dispatch each step's prefill chunks *behind* the
        decode window instead of ahead of it (requires ``paged=True``).
        The decode window is issued first and its tokens stay in flight
        (``async_depth=1``) while the host schedules and enqueues the cycle's
        chunks back-to-back behind it; the scheduler charges decode tokens
        and prefill tokens against ONE joint per-cycle budget
        (:meth:`.Scheduler.begin_step`), so decode lanes never skip a cycle
        while a long prompt prefills, and up to ``num_slots`` requests may
        be mid-prefill at once with chunks picked shortest-remaining-first —
        a chat prompt lands its one chunk next cycle even while a 100k-token
        prompt streams.  Greedy/sampled outputs are token-identical to the
        default prefill-ahead ordering (lane RNG folds from the request id,
        never from arrival order).
    kv_dtype: KV page storage format (requires ``paged=True``).  ``None``
        keeps the model dtype (token-identical); ``"bf16"`` stores bf16;
        ``"int8"`` / ``"fp8"`` quantize pages with per-(page, kv-head) f32
        scales written at scatter time and dequantized at attention — about
        4x (fp32 models) / 2x (bf16) less KV HBM per token, so the same pool
        bytes hold proportionally more concurrent lanes.  Quantized KV is
        lossy: outputs track the native path within a logit tolerance
        (``serve/kv_quant_error`` gauges the per-cycle round-trip error;
        ``--kernel-ab`` hard-enforces a max-logit-divergence threshold).
    mesh: a named :class:`jax.sharding.Mesh` for tensor-parallel serving
        (``None``, the default, keeps single-chip behavior byte-for-byte).
        With a ``tp_axis`` of size > 1: params shard by the
        :data:`~accelerate_tpu.parallel.tensor_parallel.DEFAULT_TP_RULES`,
        the KV pool (slab or paged) shards on the kv-head axis, and every
        window executable compiles with explicit in/out shardings
        (:class:`~accelerate_tpu.serving.pool.ServeShardings`) — one model
        spans the axis while block tables, scheduler, prefix-cache radix
        tree, and telemetry stay host-side and replicated.  Greedy outputs
        are token-identical to tp=1 at every (kernel, kv_dtype, paged)
        combination and the compiled-executable budget is unchanged; both
        are pinned by ``tests/test_serving_mesh.py`` and
        ``bench_inference.py --task serve --tp-ab``.  ``decode_kernel=
        "pallas"`` falls back to the XLA reference under tp > 1 (the Pallas
        grid reads whole head tiles; the einsum partitions head-parallel).
        Head counts must divide the tp degree.
    tp_axis: mesh axis name the KV heads and weight matrices shard over
        (default ``"tp"``); axes absent from the mesh count as size 1.
    async_depth: ``1`` (the default) runs the depth-1 pipelined loop: each
        decode window's tokens stay on device in a :class:`.readback.Readback`
        handle while the host runs ``_emit``, streaming callbacks, and the
        next step's admission, and the NEXT window is dispatched before the
        previous one's tokens are materialized — host work overlaps device
        compute instead of alternating with it.  Outputs are token-identical
        to ``async_depth=0`` (today's strictly synchronous loop) for every
        sampling mode; the observable differences are lag semantics only: a
        lane that hits EOS at window N is retired one cycle later (it may
        execute one extra masked window whose tokens are discarded — written
        to the null page in paged mode, overwritten-before-read in the slab),
        ``finish_step`` lands one step later, and ``cancel`` of a running
        lane drops the in-flight window's tokens.  Speculative cycles
        synchronize on the previous window before dispatching (drafts and the
        verify token block need its tokens), so with ``speculate_k > 0`` the
        overlap covers scheduling/admission but not ``_emit``.  Set
        ``async_depth=0`` when callbacks must observe tokens the same step
        the device produced them, or to bisect a suspected pipelining bug.
        See ``docs/usage/serving.md`` ("Async pipelined serving").
    max_queue: admission backpressure bound — a ``submit`` that would push
        the waiting queue past this raises a *retriable*
        :class:`~accelerate_tpu.serving.errors.AdmissionError` (queue depth
        + retry-after hint attached) instead of queueing unboundedly.  The
        HTTP front door maps it to 429; the
        :class:`~accelerate_tpu.serving.router.ReplicaRouter` failover
        ladder tries the next replica.  ``None`` (default) keeps the queue
        unbounded.  Preemption replay re-enters at the queue FRONT and is
        never refused.
    weights_version: operator-facing label for the parameter set currently
        served — surfaced by ``/v1/models`` and rotated by
        :meth:`swap_params` during zero-downtime weight hot-swap.
    """

    def __init__(
        self,
        model: Transformer,
        params: Any,
        num_slots: int = 4,
        max_len: Optional[int] = None,
        prefill_buckets: Optional[Sequence[int]] = None,
        max_prompt_len: Optional[int] = None,
        prefill_token_budget: Optional[int] = None,
        decode_window: int = 4,
        pad_token_id: int = 0,
        rng_seed: int = 0,
        slot_order: Optional[Sequence[int]] = None,
        registry: Optional[MetricsRegistry] = None,
        prefix_cache_mb: Optional[float] = 64.0,
        prefix_host_mb: Optional[float] = 0.0,
        prefix_disk_mb: Optional[float] = 0.0,
        prefix_disk_dir: Optional[str] = None,
        metrics_port: Optional[int] = None,
        speculate_k: int = 0,
        speculate_ngram: int = 3,
        draft_model: Any = None,
        tree_width: int = 1,
        tree_depth: Optional[int] = None,
        draft_ctx: int = 64,
        paged: bool = False,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        decode_kernel: str = "xla",
        prefill_kernel: Optional[str] = None,
        interleave_prefill: bool = False,
        kv_dtype: Optional[str] = None,
        mesh=None,
        tp_axis: str = "tp",
        async_depth: int = 1,
        max_queue: Optional[int] = None,
        weights_version: str = "v0",
        role: str = "both",
    ):
        cfg = model.config
        self.model = model
        self.params = params
        self.config = cfg
        self.num_slots = int(num_slots)
        self.max_len = int(max_len if max_len is not None else cfg.max_seq_len)
        self.max_prompt_len = int(
            max_prompt_len if max_prompt_len is not None else self.max_len
        )
        if self.max_prompt_len > self.max_len:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} > slot capacity {self.max_len}"
            )
        if prefill_buckets is None:
            prefill_buckets = [b for b in (128, 512) if b <= self.max_prompt_len]
            if not prefill_buckets:
                prefill_buckets = [self.max_prompt_len]
        self.buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
        if self.buckets[-1] > self.max_prompt_len:
            raise ValueError(
                f"largest prefill bucket {self.buckets[-1]} exceeds "
                f"max_prompt_len {self.max_prompt_len}"
            )
        self.window = int(decode_window)
        self.speculate_k = int(speculate_k)
        if self.speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0, got {speculate_k}")
        self.speculate_ngram = int(speculate_ngram)
        self.pad_token_id = int(pad_token_id)
        if slot_order is None:
            slot_order = range(self.num_slots)
        self.slot_order = tuple(int(s) for s in slot_order)
        if sorted(self.slot_order) != list(range(self.num_slots)):
            raise ValueError(
                f"slot_order must permute range({self.num_slots}), got {self.slot_order}"
            )
        self.async_depth = int(async_depth)
        if self.async_depth not in (0, 1):
            raise ValueError(
                f"async_depth must be 0 (synchronous) or 1 (depth-1 pipeline), "
                f"got {async_depth}"
            )
        #: the at-most-one in-flight window handle (depth-1 pipeline); None
        #: when the pipeline is empty (always, under async_depth=0)
        self._inflight: Optional[Readback] = None
        #: the PREVIOUS window's handle, parked between this cycle's dispatch
        #: and its drain at the end of _step_impl — non-None only inside that
        #: span, so admission work running in between (interleaved prefill)
        #: can reach it and any forced flush drains oldest-first
        self._prev_handle: Optional[Readback] = None

        self.paged = bool(paged)
        if decode_kernel not in ("xla", "pallas"):
            raise ValueError(
                f"decode_kernel must be 'xla' or 'pallas', got {decode_kernel!r}"
            )
        if prefill_kernel not in (None, "xla", "pallas"):
            raise ValueError(
                f"prefill_kernel must be None, 'xla' or 'pallas', "
                f"got {prefill_kernel!r}"
            )
        if (decode_kernel != "xla" or prefill_kernel == "pallas"
                or kv_dtype is not None) and not self.paged:
            raise ValueError(
                "decode_kernel/prefill_kernel/kv_dtype act on the paged KV "
                "pool; pass paged=True"
            )
        self.interleave_prefill = bool(interleave_prefill)
        if self.interleave_prefill and not self.paged:
            raise ValueError(
                "interleave_prefill needs the paged pool (the legacy batch-1 "
                "prefill scratch admits one request at a time); pass paged=True"
            )
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got {role!r}"
            )
        if role != "both" and not self.paged:
            raise ValueError(
                "disaggregated roles move lanes between replicas as KV "
                "pages; role='prefill'/'decode' requires paged=True"
            )
        #: "prefill" runs chunked prefill only — freshly installed lanes
        #: never dispatch a decode window here, they wait for the router's
        #: prefill handoff (serving/transfer.py) onto a decode-role peer.
        #: "decode" replicas receive migrated lanes (and can still prefill
        #: adopted replays — role shapes steady-state policy, not recovery).
        self.role = role
        from ..ops.paged_attention import (
            kv_qmax,
            kv_storage_dtype,
            resolve_paged_kernel,
        )

        # shard-aware kernel dispatch: under a tp>1 mesh the Pallas grid would
        # read whole (kv-head, page) tiles of a head-sharded pool, so "pallas"
        # resolves to the XLA reference (head-parallel under GSPMD for free)
        decode_kernel = resolve_paged_kernel(decode_kernel, mesh, tp_axis)
        self.decode_kernel = decode_kernel
        # prefill follows the resolved decode kernel unless forced: a pool
        # decoding through Pallas prefills through its chunk-wide twin, and
        # the tp>1 fallback applies to both independently
        if prefill_kernel is None:
            prefill_kernel = decode_kernel if self.paged else "xla"
        self.prefill_kernel = resolve_paged_kernel(
            prefill_kernel, mesh, tp_axis, role="prefill"
        )
        self.kv_dtype = kv_dtype

        self.quantized = kv_qmax(kv_storage_dtype(kv_dtype, cfg.dtype)) is not None
        # "direct" windows thread the page pool through the model
        # (PagedKVCache) instead of the gather/scatter sandwich: required for
        # in-place Pallas attention and for scale-aware quantized writes.
        # Native-dtype XLA stays on the PR-6 gathered path — bitwise identity
        # with the slab pool, plus the live-page gather mask.
        self._direct = self.quantized or decode_kernel == "pallas"
        # the prefill-side twin of the flag: quantized pools and the flash
        # prefill kernel both need the chunk forward to own the page writes
        self._prefill_direct = self.quantized or self.prefill_kernel == "pallas"
        # ------------------------------------------------- tree speculation
        self._draft_spec = draft_model
        self.tree_width = int(tree_width)
        self.tree_depth = int(
            tree_depth if tree_depth is not None
            else (self.speculate_k if self.speculate_k else 4)
        )
        self.draft_ctx = int(draft_ctx)
        self.tree: Optional[TreeSpec] = None
        if draft_model is None:
            if self.tree_width != 1:
                raise ValueError(
                    "tree_width > 1 needs a draft model to rank sibling "
                    "branches; pass draft_model="
                )
        else:
            if self.draft_ctx < 1:
                raise ValueError(f"draft_ctx must be >= 1, got {draft_ctx}")
            if cfg.sliding_window is not None or cfg.positional == "alibi":
                raise ValueError(
                    "tree speculation needs a full-causal model: the ancestor "
                    "mask replaces the causal row mask, which sliding_window "
                    "and alibi models reshape"
                )
            self.tree = TreeSpec(self.tree_width, self.tree_depth)
            if decode_kernel == "pallas" and self.tree.nodes > 32:
                raise ValueError(
                    f"tree has {self.tree.nodes} nodes but the Pallas tree "
                    f"kernel packs ancestor masks into uint32 rows (<= 32 "
                    f"nodes); shrink tree_width/tree_depth or use "
                    f"decode_kernel='xla'"
                )
        # widest device pass this engine can run in one cycle: a tree verify
        # writes all S node positions at the lane frontier (committing at
        # most depth + 1), a linear verify writes speculate_k + 1
        self._spec_span = (
            self.tree.nodes if self.tree is not None else self.speculate_k + 1
        )
        self._spec_any = self.tree is not None or self.speculate_k > 0
        if self.paged:
            self.page_size = int(
                page_size if page_size is not None
                else math.gcd(*self.buckets) if len(self.buckets) > 1
                else self.buckets[0]
            )
            for b in self.buckets:
                if b % self.page_size != 0:
                    raise ValueError(
                        f"page_size {self.page_size} must divide every prefill "
                        f"bucket, got {self.buckets}"
                    )
            if self.max_len % self.page_size != 0:
                raise ValueError(
                    f"page_size {self.page_size} must divide max_len {self.max_len}"
                )
            self.num_pages = int(
                num_pages if num_pages is not None
                else self.num_slots * (self.max_len // self.page_size) + 1
            )
        # ------------------------------------------------------ mesh / tp
        self.mesh = mesh
        self.tp_axis = tp_axis
        if mesh is not None:
            from ..parallel.mesh import mesh_axis_size
            from ..parallel.sharding import shard_pytree_with_path
            from ..parallel.tensor_parallel import (
                SERVING_TP_RULES,
                make_tp_sharding_fn,
            )

            self.tp_degree = mesh_axis_size(mesh, tp_axis)
            if self.tp_degree > 1 and (
                cfg.num_heads % self.tp_degree != 0
                or cfg.num_kv_heads % self.tp_degree != 0
            ):
                raise ValueError(
                    f"num_heads {cfg.num_heads} / num_kv_heads "
                    f"{cfg.num_kv_heads} must divide evenly over "
                    f"tp={self.tp_degree}"
                )
            # SERVING_TP_RULES, not DEFAULT_TP_RULES: row-parallel psum would
            # break bitwise token identity vs tp=1 (see tensor_parallel.py)
            self.params, param_shardings = shard_pytree_with_path(
                params,
                make_tp_sharding_fn(
                    mesh, axis_name=tp_axis, rules=SERVING_TP_RULES
                ),
            )
            self._shardings = ServeShardings(
                mesh, param_shardings, tp_axis=tp_axis
            )
        else:
            self.tp_degree = 1
            self._shardings = None
        self.metrics = registry if registry is not None else get_registry()
        # device state: per-lane-index slab pool + batch-1 prefill scratch
        # (legacy), or the shared page pool + host block tables (paged — no
        # scratch at all: prefill gathers the lane's own view, shared prefix
        # pages included, and scatters freshly written pages back)
        if self.paged:
            self.pool = None
            self.scratch = None
            self.kv = PagedKVPool(
                cfg, self.num_slots, self.max_len, self.page_size,
                self.num_pages, registry=self.metrics, kv_dtype=kv_dtype,
                mesh=mesh, tp_axis=tp_axis,
            )
        else:
            self.pool = KVCache.create(cfg, self.num_slots, self.max_len, per_lane_index=True)
            self.scratch = KVCache.create(cfg, 1, self.max_prompt_len)
            self.kv = None
            if self._shardings is not None:
                # the slab pool and scratch carry kv heads on dim 3, exactly
                # like the page arrays — place them before the first compile
                self.pool = jax.device_put(self.pool, self._shardings.cache())
                self.scratch = jax.device_put(
                    self.scratch, self._shardings.cache()
                )
        self.tracer = get_tracer()
        # Forensics + cost accounting (docs/usage/observability.md): request
        # lifecycle events land in the process flight recorder, per-executable
        # FLOP/HBM signatures in a private cost table (filled lazily by
        # analyze_costs / a /metrics scrape — never in the serve loop).
        # Every event this engine (and its scheduler) records carries the
        # replica id; the per-request trace registry keys its waterfalls on
        # the same id across failover.
        self.engine_id = f"e{next(_ENGINE_IDS)}"
        self.recorder = get_flight_recorder().tagged(engine=self.engine_id)
        self.reqtrace = get_reqtrace()
        self.cost_table = CostTable(self.metrics)
        self.device_peaks = detect_device_peaks()
        self.debug_server = start_debug_server(
            metrics_port, registry=self.metrics, recorder=self.recorder
        )
        if self.debug_server is not None:
            self.debug_server.add_collector(self.analyze_costs)
        # Window models: the direct paged windows run a Transformer whose
        # config selects the attention kernel (and interpret default).  The
        # fields carry no parameters, so the engine's params serve every
        # variant.  The prefill model picks its own kernel: the chunk-wide
        # flash kernel under prefill_kernel="pallas", the XLA reference
        # otherwise — either way the page writes go through the same insert
        # path, so the written KV is identical across kernels.
        if self.paged and self._direct:
            kmodel = Transformer(dataclasses.replace(cfg, paged_kernel=decode_kernel))
        if self.paged and self._prefill_direct:
            pmodel = Transformer(dataclasses.replace(
                cfg,
                paged_kernel=("flash_prefill" if self.prefill_kernel == "pallas"
                              else "xla"),
            ))
        # budget=1 per executable: the engine's whole design promises exactly
        # one compiled shape each — any second signature is a bug worth a warning
        if self.paged and self._direct:
            # nested watchdog: serve/paged_attn accounts the in-place paged
            # attention executable itself (budget 1 — the kernel REPLACES the
            # decode executable, it must never add shapes); serve/decode_window
            # keeps its usual accounting on top.  Attribute forwarding lets
            # jit_cache_sizes read straight through both layers.
            decode_fn = RecompileWatchdog(
                make_paged_decode_window(kmodel, self.window, direct=True,
                                         shardings=self._shardings),
                name="serve/paged_attn", budget=1, registry=self.metrics,
            )
        elif self.paged:
            decode_fn = make_paged_decode_window(model, self.window,
                                                 shardings=self._shardings)
        else:
            decode_fn = make_decode_window(model, self.window,
                                           shardings=self._shardings)
        self._decode = RecompileWatchdog(
            decode_fn, name="serve/decode_window", budget=1, registry=self.metrics,
        )
        self._prefill = {
            b: RecompileWatchdog(
                make_paged_prefill_chunk(
                    pmodel if self._prefill_direct else model, b,
                    self.page_size, direct=self._prefill_direct,
                    shardings=self._shardings,
                ) if self.paged
                else make_prefill_chunk(model, b, shardings=self._shardings),
                name=f"serve/prefill_{b}", budget=1, registry=self.metrics,
            )
            for b in self.buckets
        }
        self._insert = (
            None if self.paged
            else RecompileWatchdog(
                make_insert(shardings=self._shardings), name="serve/insert",
                budget=1, registry=self.metrics
            )
        )
        self._lane_install = RecompileWatchdog(
            make_lane_install(shardings=self._shardings),
            name="serve/lane_install", budget=1, registry=self.metrics,
        )
        if self.tree is not None:
            # tree mode REPLACES the linear verify window: the compiled
            # budget grows by exactly {draft_forward, tree_verify_window}
            self._verify = RecompileWatchdog(
                make_paged_tree_verify_window(
                    kmodel, self.tree, direct=True, shardings=self._shardings,
                ) if (self.paged and self._direct)
                else make_paged_tree_verify_window(model, self.tree,
                                                   shardings=self._shardings)
                if self.paged
                else make_tree_verify_window(model, self.tree,
                                             shardings=self._shardings),
                name="serve/tree_verify_window", budget=1,
                registry=self.metrics,
            )
            draft_cfg, draft_host = build_draft(
                cfg, self.params, draft_model,
                draft_ctx=self.draft_ctx, depth=self.tree_depth,
            )
            # the draft head is small: replicate it rather than shard — tp
            # collectives would serialize its many tiny dispatches
            self._draft_params = (
                jax.device_put(draft_host) if self._shardings is None
                else jax.device_put(draft_host, self._shardings.replicated)
            )
            self._draft_cfg = draft_cfg
            self._draft_fwd = RecompileWatchdog(
                make_draft_forward(Transformer(draft_cfg), self.tree,
                                   self.draft_ctx, shardings=self._shardings),
                name="serve/draft_forward", budget=1, registry=self.metrics,
            )
            self._draft_window = DraftContextWindow(
                self.num_slots, self.draft_ctx, pad=self.pad_token_id
            )
            self._ngram = None
            self.drafter = TreeDrafter(self.tree, draft_cfg, self._draft_fwd)
        elif self.speculate_k:
            self._verify = RecompileWatchdog(
                make_paged_verify_window(
                    kmodel, self.speculate_k, direct=True,
                    shardings=self._shardings,
                ) if (self.paged and self._direct)
                else make_paged_verify_window(model, self.speculate_k,
                                              shardings=self._shardings)
                if self.paged
                else make_verify_window(model, self.speculate_k,
                                        shardings=self._shardings),
                name="serve/verify_window", budget=1, registry=self.metrics,
            )
            self._draft_fwd = None
            self._draft_window = None
            self._ngram = NgramDrafter(max_ngram=self.speculate_ngram)
            self.drafter = self._ngram
        else:
            self._verify = None
            self._draft_fwd = None
            self._draft_window = None
            self._ngram = None
            self.drafter = None
        self._copy_page = (
            RecompileWatchdog(
                make_copy_page(shardings=self._shardings),
                name="serve/copy_page", budget=1,
                registry=self.metrics,
            )
            if self.paged
            else None
        )
        self.prefix_host_bytes = int((prefix_host_mb or 0.0) * 2**20)
        prefix_disk_bytes = int((prefix_disk_mb or 0.0) * 2**20)
        if self.prefix_host_bytes and not (self.paged and prefix_cache_mb):
            raise ValueError(
                "prefix_host_mb spills prefix *pages*; it requires paged=True "
                "and an enabled prefix cache (prefix_cache_mb > 0)"
            )
        if prefix_disk_bytes and not self.prefix_host_bytes:
            raise ValueError(
                "prefix_disk_mb sits behind the host ring; set prefix_host_mb"
            )
        if self.prefix_host_bytes:
            # one D2H gather + one H2D scatter-install shape per prefill
            # bucket: the documented compiled-budget growth of the host tier
            self._spill_extract = {
                b: RecompileWatchdog(
                    make_spill_extract(b // self.page_size,
                                       shardings=self._shardings),
                    name=f"serve/spill_{b}", budget=1, registry=self.metrics,
                )
                for b in self.buckets
            }
            self._promote_install = {
                b: RecompileWatchdog(
                    make_promote_install(b // self.page_size,
                                         shardings=self._shardings),
                    name=f"serve/promote_{b}", budget=1, registry=self.metrics,
                )
                for b in self.buckets
            }
        else:
            self._spill_extract = {}
            self._promote_install = {}
        if prefix_cache_mb:
            self.prefix_cache: Optional[PrefixCache] = PrefixCache(
                int(prefix_cache_mb * 2**20), registry=self.metrics,
                on_evict=self._on_prefix_evict if self.paged else None,
                host_capacity_bytes=self.prefix_host_bytes,
                spill=self._spill_node if self.prefix_host_bytes else None,
                disk_capacity_bytes=prefix_disk_bytes,
                disk_dir=prefix_disk_dir,
            )
            # paged hits alias pages through the block table — no copy
            # executables exist; legacy replays slabs through one
            # dynamic_update_slice shape per bucket
            self._copy = (
                {}
                if self.paged
                else {
                    b: RecompileWatchdog(
                        make_copy_chunk(b, shardings=self._shardings),
                        name=f"serve/copy_{b}", budget=1, registry=self.metrics,
                    )
                    for b in self.buckets
                }
            )
        else:
            self.prefix_cache = None
            self._copy = {}

        self.scheduler = Scheduler(
            self.buckets,
            prefill_token_budget if prefill_token_budget is not None else self.buckets[-1],
            prefix_cache=self.prefix_cache,
            recorder=self.recorder,
            max_queue=max_queue,
            # interleaved mode keeps up to one open prefill per slot so a
            # short prompt's chunk can land SRTF ahead of a long one's
            max_prefills=self.num_slots if self.interleave_prefill else 1,
        )
        #: label of the parameter set currently served; rotated by swap_params
        self.weights_version = str(weights_version)
        #: True while a drain / hot-swap holds new prefills back (queued
        #: requests stay queued; in-flight lanes run to completion)
        self.admission_paused = False

        n = self.num_slots
        # host-side per-slot lane state, shipped to the decode window each step
        self._slot_req: List[Optional[Request]] = [None] * n
        self._slot_ever_used = np.zeros(n, bool)
        self._pending_tok = np.zeros(n, np.int32)
        self._active = np.zeros(n, bool)
        self._eos = np.full(n, -1, np.int32)
        self._do_sample = np.zeros(n, bool)
        self._temperature = np.ones(n, np.float32)
        self._top_k = np.zeros(n, np.int32)
        self._top_p = np.ones(n, np.float32)
        self._rngs = np.zeros((n, 2), np.uint32)
        # host mirror of each lane's KV write index (paged mode): install sets
        # it to prompt_len - 1, decode/verify advance it by exactly what the
        # device committed — integer arithmetic, so the mirror is always exact
        self._lane_len = np.zeros(n, np.int32)
        #: high-water mark of simultaneously active lanes (the paged-vs-slab
        #: concurrency headline; tracked in both modes for A/B benches)
        self.peak_active_lanes = 0
        self._base_rng = jax.random.PRNGKey(rng_seed)
        # slots held for requests mid-prefill (one per open prefill; a set
        # because interleaved mode keeps several prefills in flight at once)
        self._reserved_slots: set = set()
        # device-resident mirror of the lane vectors above (uploaded once,
        # then edited in place: decode/verify carry pending/rng device-side,
        # installs scatter one slot, frees re-upload the active mask) —
        # lane state never round-trips through the host mid-serve
        self._lane_device: Optional[list] = None

        self._next_rid = 0
        self._step_count = 0
        # ``stats`` stays a plain mutable dict — benches reset it in place —
        # while ``_bump`` mirrors every increment into cumulative counters.
        # (_Stats additionally answers ``stats()`` with a trace summary.)
        self.stats = _Stats({
            "requests_submitted": 0,
            "requests_completed": 0,
            "tokens_generated": 0,
            "prefill_chunks": 0,
            "prefill_tokens": 0,
            "interleaved_chunks": 0,
            "decode_steps": 0,
            "occupied_lane_steps": 0,
            "slots_reused": 0,
            "prefix_hit_tokens": 0,
            "prefix_hit_tokens_host": 0,
            "prefix_miss_tokens": 0,
            "cancelled": 0,
            "spec_drafted": 0,
            "spec_accepted": 0,
            "preemptions": 0,
            "cow_copies": 0,
            "prefreed_lanes": 0,
            "hot_swaps": 0,
            "deadline_shed": 0,
            "requests_replayed": 0,
        })
        self.stats.engine = self
        self._counters = {
            k: self.metrics.counter(f"serve/{k}_total") for k in self.stats
        }
        self._ttft_hist = self.metrics.histogram(
            "serve/ttft_s", buckets=_LATENCY_BUCKETS,
            help="submit-to-first-token wall time",
        )
        # per-traffic-class TTFT histograms, created lazily on the first
        # request carrying each class label (serve/ttft_s_class_<class>)
        self._class_ttft_hists: dict = {}
        # tenant attribution: per-tenant counter/histogram families, created
        # lazily on the first request carrying each tenant label (same
        # pattern as the class hists) —
        # serve/<key>_tenant_<tenant>_total and serve/ttft_s_tenant_<tenant>.
        # ``_tenant_stats`` mirrors the bumps numerically so
        # ``stats()["tenants"]`` is a lock-free rollup that sums EXACTLY to
        # the global counters (every _bump_tenant site sits beside a _bump).
        self._tenant_counters: dict = {}
        self._tenant_ttft_hists: dict = {}
        self._tenant_stats: dict = {}
        self._tenant_kv_gauges: dict = {}
        self._token_hist = self.metrics.histogram(
            "serve/token_latency_s", buckets=_LATENCY_BUCKETS,
            help="inter-token wall time (first token = TTFT)",
        )
        # Derived per-phase histograms, observed as request-trace phases close
        # (telemetry/reqtrace.py): together they decompose serve/ttft_s.
        self._queue_wait_hist = self.metrics.histogram(
            "serve/queue_wait_s", buckets=_LATENCY_BUCKETS,
            help="submit to first prefill chunk taken (trace queue_wait phase)",
        )
        self._prefill_phase_hist = self.metrics.histogram(
            "serve/prefill_compute_s", buckets=_LATENCY_BUCKETS,
            help="per-chunk prefill share of a request's waterfall "
                 "(fresh compute, cached replay, or promoted chunks alike)",
        )
        self._decode_tok_hist = self.metrics.histogram(
            "serve/decode_s_per_token", buckets=_LATENCY_BUCKETS,
            help="per-request decode-window share amortized over the tokens "
                 "the window committed (closes at drain, async-depth-aware)",
        )
        self._promote_wait_hist = self.metrics.histogram(
            "serve/promote_wait_s", buckets=_LATENCY_BUCKETS,
            help="host-tier promotion dispatch to landed-at-drain wait",
        )
        self._queue_gauge = self.metrics.gauge(
            "serve/queue_depth", help="requests queued or mid-prefill"
        )
        self._occupancy_gauge = self.metrics.gauge(
            "serve/slot_occupancy", help="fraction of slots active this window"
        )
        self._hit_rate_gauge = self.metrics.gauge(
            "serve/prefix_hit_rate",
            help="prefix_hit_tokens / (hit + miss) over cache-eligible prefill",
        )
        self._hit_rate_device_gauge = self.metrics.gauge(
            "serve/prefix_hit_rate_device",
            help="device-tier share of the prefix hit rate: tokens served by "
                 "zero-copy page aliasing / (hit + miss)",
        )
        self._hit_rate_host_gauge = self.metrics.gauge(
            "serve/prefix_hit_rate_host",
            help="spilled-tier share of the prefix hit rate: tokens served by "
                 "host/disk promotion (H2D install, no prefill FLOPs) / "
                 "(hit + miss)",
        )
        self._decode_flops_gauge = self.metrics.gauge(
            "serve/decode_flops_per_token",
            help="decode-window XLA FLOPs / (window * num_slots)",
        )
        self._hbm_gauge = self.metrics.gauge(
            "serve/hbm_peak_bytes",
            help="largest per-executable HBM peak across the serving pool, "
                 "per device (divided by the tp degree when sharded)",
        )
        self._accept_rate_gauge = self.metrics.gauge(
            "serve/spec_accept_rate",
            help="accepted / proposed draft tokens (cumulative) under "
                 "speculative decoding",
        )
        self._accept_len_hist = self.metrics.histogram(
            "serve/spec_accept_len",
            buckets=tuple(float(i) for i in range(33)),
            help="accepted draft tokens per drafted lane per verify cycle "
                 "(0..K linear, 0..tree_depth along the winning tree path); "
                 "the distribution the acceptance-vs-speedup curve samples",
        )
        self._draft_ms_hist = self.metrics.histogram(
            "serve/draft_ms",
            buckets=tuple(1e-2 * 2.0**i for i in range(20)),
            help="host wall time per cycle to assemble + dispatch the draft "
                 "forward (tree speculation only; device time hides under "
                 "the verify dispatch that follows)",
        )
        self._tree_nodes_counter = self.metrics.counter(
            "serve/spec_tree_nodes",
            help="token-tree nodes verified (occupied lanes x tree nodes, "
                 "cumulative) — the tree verify window's work volume",
        )
        self.metrics.gauge(
            "serve/decode_kernel",
            help="info gauge: decode attention program — 1 = pallas "
                 "(in-place paged kernel), 0 = xla (gather reference)",
        ).set(1.0 if self.decode_kernel == "pallas" else 0.0)
        self.metrics.gauge(
            "serve/prefill_kernel",
            help="info gauge: prefill attention program — 1 = pallas "
                 "(paged flash prefill), 0 = xla (gather/scatter reference)",
        ).set(1.0 if self.prefill_kernel == "pallas" else 0.0)
        self._pf_rate_gauge = self.metrics.gauge(
            "serve/prefill_tokens_per_s",
            help="prefill throughput over the trailing steps that ran at "
                 "least one chunk (valid tokens / wall time between them)",
        )
        self._interleave_gauge = self.metrics.gauge(
            "serve/prefill_interleave_ratio",
            help="fraction of prefill chunks dispatched BEHIND a same-cycle "
                 "decode window (interleaved chunked prefill); 0 by "
                 "definition under the default prefill-ahead ordering",
        )
        # trailing-rate state for serve/prefill_tokens_per_s
        self._pf_last_t: Optional[float] = None
        self._pf_last_tokens = 0
        # device quant-error handles from this cycle's prefill chunks; they
        # attach to the next dispatched window's Readback and are folded into
        # the quant-error gauge at drain (fetching here would sync the pipe)
        self._pending_prefill_qerr: List = []
        # hierarchical prefix cache deferrals, same discipline: spill gathers
        # enqueued at eviction time (``(node, handles)``) land their payloads
        # at the next drain; promotion-install records are acknowledged there.
        # Fetching either eagerly would sync the pipeline mid-cycle.
        self._pending_spills: List = []
        self._pending_promotions: List = []
        # tokens charged by the decode window dispatched this cycle; _admit
        # subtracts it from the scheduler's joint per-cycle budget when the
        # interleaved ordering dispatched decode first
        self._cycle_decode_tokens = 0
        self.metrics.gauge(
            "serve/tp_degree",
            help="info gauge: tensor-parallel degree the params and KV pool "
                 "shard over (1 = single-chip)",
        ).set(float(self.tp_degree))
        self.metrics.gauge(
            "serve/role",
            help="info gauge: disaggregated serving role — 0 = both "
                 "(monolithic), 1 = prefill-only, 2 = decode-only",
        ).set({"both": 0.0, "prefill": 1.0, "decode": 2.0}[self.role])
        self._kv_quant_gauge = (
            self.metrics.gauge(
                "serve/kv_quant_error",
                help="max abs KV round-trip quantization error of the values "
                     "written this cycle (an upper-bound logit-divergence "
                     "proxy; the --kernel-ab bench measures true logit "
                     "deltas) — only published under quantized kv_dtype",
            )
            if self.quantized
            else None
        )
        # pipeline overlap accounting (async_depth=1): host_s accumulates the
        # dispatch->drain host-work time each window, wait_s the blocking tail
        # of each fetch; their ratio is the fraction of host work the device
        # covered.  _t_pipeline_empty timestamps the moment the pipeline went
        # empty so the next dispatch can charge the gap as device idle — under
        # async_depth=0 that is every host gap (the honest baseline number),
        # at steady depth-1 state it stays ~0.
        self._overlap_host_s = 0.0
        self._overlap_wait_s = 0.0
        self._device_idle_s = 0.0
        self._t_pipeline_empty: Optional[float] = None
        # set when a lane is freed while its window is still in flight: the
        # active mask is host-authoritative, so the next dispatch refreshes
        # just that one device vector instead of a full (blocking) resync
        self._mask_stale = False
        # old device handles replaced by a lane-install scatter or a mask
        # re-upload while a window is in flight.  They must not be *dropped*
        # yet — releasing the last reference to a handle a pending
        # computation consumes blocks until that computation finishes — so
        # they stage here and ride out on the next window's Readback, dying
        # only after its drain.
        self._stale_handles: List = []
        self._overlap_gauge = self.metrics.gauge(
            "serve/host_overlap_ratio",
            help="fraction of serve-loop host work (emit/callbacks/admission) "
                 "hidden under device execution: host_s / (host_s + "
                 "readback_wait_s), cumulative; 0 under async_depth=0",
        )
        self._idle_gauge = self.metrics.gauge(
            "serve/device_idle_ms",
            help="cumulative ms the device sat with no window dispatched or "
                 "in flight (pipeline-empty gaps between drain and the next "
                 "dispatch); grows every step under async_depth=0, stays "
                 "near-flat once the depth-1 pipeline fills",
        )
        # lane-migration gather/scatter pair, built lazily by
        # serving/transfer.py on this engine's first migration (most
        # replicas never migrate; the compiled budget grows only on the
        # ones that do, by exactly this documented set)
        self._migrate_extract: Optional[RecompileWatchdog] = None
        self._migrate_install: Optional[RecompileWatchdog] = None
        # fault containment: the first exception to escape a step parks here
        # and every later step() re-raises it — a poisoned engine never
        # half-runs.  The router supervisor reads it to trigger ejection.
        self._poisoned: Optional[BaseException] = None
        # deadline shedding: EMA of request wall time (admission's
        # queue-depth feasibility estimate) and a flag that keeps the
        # per-step deadline sweep off the hot path until a deadline exists
        self._service_ema = 0.0
        self._has_deadlines = False

    def _bump(self, key: str, n: int = 1) -> None:
        self.stats[key] += n
        self._counters[key].inc(n)

    def _bump_tenant(self, tenant: Optional[str], key: str, n: int = 1) -> None:
        """Mirror a ``_bump`` into the caller tenant's lazily created counter
        family (``serve/<key>_tenant_<tenant>_total``) and the numeric rollup
        behind ``stats()["tenants"]``.  Steady-state cost is two dict lookups;
        ``tenant=None`` (untenanted traffic) is one ``is None`` check."""
        if tenant is None:
            return
        counters = self._tenant_counters.get(tenant)
        if counters is None:
            counters = self._tenant_counters[tenant] = {}
            self._tenant_stats[tenant] = {}
        counter = counters.get(key)
        if counter is None:
            counter = counters[key] = self.metrics.counter(
                f"serve/{key}_tenant_{tenant}_total"
            )
            self._tenant_stats[tenant][key] = 0
        self._tenant_stats[tenant][key] += n
        counter.inc(n)

    def _tenant_ttft(self, tenant: Optional[str], value: float) -> None:
        """Per-tenant TTFT histogram family (``serve/ttft_s_tenant_<t>``),
        created lazily like the per-class family."""
        if tenant is None:
            return
        hist = self._tenant_ttft_hists.get(tenant)
        if hist is None:
            hist = self._tenant_ttft_hists[tenant] = self.metrics.histogram(
                f"serve/ttft_s_tenant_{tenant}", buckets=_LATENCY_BUCKETS,
            )
        hist.observe(value)

    def _put(self, x):
        """Upload host data for a window call.  Under a mesh every control
        operand must be *replicated over the mesh's devices* — a plain
        ``jnp.asarray`` commits to one device, which the explicitly-sharded
        executables reject as an incompatible placement.

        numpy inputs are copied first: the host mirrors (``_active``,
        ``_lane_len``, the paged block tables) stay mutable while a window
        is in flight, and CPU ``device_put`` may alias an aligned numpy
        buffer zero-copy — without the copy, a post-dispatch host mutation
        (lane retirement, ``_lane_len`` advance, ``lane_detach`` nulling a
        table row) could be read mid-execution by the in-flight window."""
        if isinstance(x, np.ndarray):
            x = x.copy()
        if self._shardings is None:
            return jnp.asarray(x)
        return jax.device_put(jnp.asarray(x), self._shardings.replicated)

    # ------------------------------------------------------------- submission
    def submit(
        self,
        prompt,
        config: Optional[GenerationConfig] = None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        cache_prefix: bool = True,
        speculate: bool = True,
        deadline_s: Optional[float] = None,
        request_class: Optional[str] = None,
        tenant: Optional[str] = None,
        **overrides: Any,
    ) -> Request:
        """Queue one request; returns its :class:`Request` handle (filled in
        as the engine runs).  ``overrides`` patch the ``GenerationConfig``
        exactly like :func:`~accelerate_tpu.models.generation.generate`.
        ``cache_prefix=False`` opts this request out of prefix-KV reuse and
        population (e.g. prompts carrying secrets that must not be retained);
        ``speculate=False`` opts it out of n-gram drafting (it still rides
        along in verify windows other lanes trigger — with pad drafts, which
        verification rejects).  ``deadline_s`` is an SLO budget from submit:
        admission sheds (retriable refusal) when the queue-depth estimate
        says it cannot be met, and the per-step deadline sweep cancels the
        request (``deadline_exceeded`` set) if a running lane blows it.
        ``request_class`` is a free-form traffic label (e.g. ``"chat"``,
        ``"batch"``): TTFT is additionally observed into a per-class
        histogram ``serve/ttft_s_class_<class>`` so one tenant's long
        prompts can't hide another's latency regression in the blended
        percentile.  ``tenant`` attributes this request to a caller: every
        global counter the request moves (submissions, tokens, preemptions,
        sheds, completions, replays) is mirrored into
        ``serve/<key>_tenant_<tenant>_total`` and the
        ``stats()["tenants"]`` rollup, and TTFT additionally lands in
        ``serve/ttft_s_tenant_<tenant>`` — the accounting substrate for
        fair-share enforcement."""
        gen = config or GenerationConfig()
        if overrides:
            gen = dataclasses.replace(gen, **overrides)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self.max_prompt_len:
            raise AdmissionError(
                f"prompt length {prompt.size} > max_prompt_len {self.max_prompt_len}",
                queue_depth=self.scheduler.queue_depth,
                retriable=False,
            )
        # headroom for the widest device pass this engine can run: a verify
        # cycle writes speculate_k + 1 KV positions in one forward, a tree
        # verify all tree.nodes node positions at the lane frontier
        span = max(self.window, self._spec_span)
        need = prompt.size + gen.max_new_tokens + span
        if need > self.max_len:
            raise AdmissionError(
                f"prompt {prompt.size} + max_new_tokens {gen.max_new_tokens} + "
                f"max(decode_window, speculation span) {span} = {need} exceeds "
                f"slot capacity {self.max_len}",
                queue_depth=self.scheduler.queue_depth,
                retriable=False,
            )
        # the chunk plan pads the final chunk up to its bucket; that padding
        # must still fit the prefill write target (the scratch cache, or the
        # paged lane view) or the tail writes would silently clamp/corrupt
        padded = sum(b for b, _ in plan_chunks(prompt.size, self.buckets))
        cap = self.max_len if self.paged else self.max_prompt_len
        if padded > cap:
            raise AdmissionError(
                f"prompt {prompt.size} pads to {padded} prefill tokens under "
                f"buckets {self.buckets}, exceeding capacity {cap}",
                queue_depth=self.scheduler.queue_depth,
                retriable=False,
            )
        if deadline_s is not None:
            # feasibility check against the waiting line: each queued request
            # costs ~one observed end-to-end service time (EMA) before this
            # one's lane even starts.  Optimistic before the first completion
            # (EMA 0 admits everything); a shed is retriable — the queue
            # drains, the same deadline may be meetable in a moment.
            est = self.scheduler.queue_depth * self._service_ema
            if est > float(deadline_s):
                self._bump("deadline_shed")
                self._bump_tenant(tenant, "deadline_shed")
                self.recorder.record(
                    "serve/deadline_shed", where="admission",
                    deadline_s=float(deadline_s), estimate_s=est,
                    queue_depth=self.scheduler.queue_depth,
                )
                raise AdmissionError(
                    f"deadline {deadline_s}s unmeetable: ~{est:.2f}s of queued "
                    f"work ahead ({self.scheduler.queue_depth} requests)",
                    queue_depth=self.scheduler.queue_depth,
                    retry_after_s=min(30.0, max(est - float(deadline_s), 0.1)),
                    retriable=True,
                )
        now = time.perf_counter()
        req = Request(rid=self._next_rid, prompt=prompt, config=gen, on_token=on_token,
                      submit_step=self._step_count, submit_time=now, last_token_time=now,
                      cache_prefix=bool(cache_prefix), speculate=bool(speculate),
                      deadline_s=None if deadline_s is None else float(deadline_s),
                      request_class=request_class, tenant=tenant)
        self._next_rid += 1
        # the waterfall opens here: queue_wait runs until the first prefill
        # chunk is taken (None when tracing is off — every hook guards on it)
        req.trace = self.reqtrace.begin(
            rid=req.rid, engine=self.engine_id,
            prompt_len=int(prompt.size), submit_t=now,
        )
        self.scheduler.submit(req)
        self._bump("requests_submitted")
        self._bump_tenant(tenant, "requests_submitted")
        if deadline_s is not None:
            self._has_deadlines = True
        return req

    def cancel(self, request) -> bool:
        """Cancel a queued OR running request (a :class:`Request` or its rid).

        Queued requests are dropped before burning any prefill budget; a
        RUNNING lane is frozen immediately — it stops decoding this very
        step, its slot frees for the next admission, and in paged mode every
        KV page it held returns to the allocator (shared prefix pages survive
        under the cache's own references).  Tokens already streamed stay
        streamed.  Returns True when the request was cancelled (state becomes
        ``CANCELLED``); False when it is mid-prefill, done, or unknown."""
        rid = request.rid if isinstance(request, Request) else int(request)
        req = self.scheduler.cancel(rid)
        if req is not None:
            self._bump("cancelled")
            self.reqtrace.complete(req.trace, status="cancelled")
            return True
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if req is None or req.rid != rid or not self._active[s]:
                continue
            # with a window in flight the lane's tokens from that window are
            # dropped at drain (ownership check in _emit); its KV pages stay
            # held until the window retires (lane_detach deferral)
            self._retire_lane(s)
            req.state = RequestState.CANCELLED
            req.finish_step = self._step_count
            self._bump("cancelled")
            self.recorder.record(
                "serve/cancel_running", rid=rid, slot=s, step=self._step_count,
                tokens=len(req.tokens),
            )
            self.reqtrace.complete(req.trace, status="cancelled")
            return True
        return False

    # ------------------------------------------------------- drain / hot-swap
    def pause_admission(self) -> None:
        """Stop starting new prefills.  Queued requests stay queued, a
        request already mid-prefill finishes its chunks, and active lanes
        decode to completion — after enough ``step()`` calls the engine
        reaches quiescence (:attr:`drained`).  The drain-replica and weight
        hot-swap paths both start here."""
        self.admission_paused = True

    def resume_admission(self) -> None:
        """Re-open admission; queued requests start prefilling next step."""
        self.admission_paused = False

    @property
    def drained(self) -> bool:
        """True when no lane is active, no prefill is mid-flight, and no
        decode window is in the pipeline — the quiescence :meth:`swap_params`
        requires.  Queued requests do NOT block drain: they have no device
        state and run under whatever weights are live when admission
        resumes."""
        return (
            not self._active.any()
            and self._inflight is None
            and self._prev_handle is None
            and not self.scheduler.prefills
            and not self._reserved_slots
        )

    def swap_params(self, params: Any, version: Optional[str] = None) -> None:
        """Zero-downtime weight hot-swap: rebind this engine's parameters.

        Requires quiescence (:attr:`drained` — pause admission and ``step()``
        until lanes finish); raises ``RuntimeError`` otherwise rather than
        splice weights mid-request.  The new params ride the same upload path
        as ``__init__`` (tp-sharded under a mesh via ``SERVING_TP_RULES``),
        so every compiled executable — prefill buckets, decode windows, copy
        chunks — is REUSED as-is: a swap costs one host-to-device transfer,
        never a recompile.  The prefix cache is flushed first (queued pins
        dropped): retained KV was computed under the old weights, and
        replaying it would silently corrupt tokens.  Queued requests survive
        and decode under the new weights.  Admission stays wherever the
        caller put it — resume explicitly after cutover.
        """
        if not self.drained:
            raise RuntimeError(
                "swap_params requires a drained engine (pause_admission, then "
                "step until engine.drained): active lanes or an in-flight "
                "window would mix weight versions mid-request"
            )
        if faults.ACTIVE is not None and faults.ACTIVE.fire("hot_swap_upload"):
            # fail BEFORE touching any state: a torn upload must leave the
            # engine serving the old weights intact, cache included
            raise faults.FaultInjected(
                "injected hot-swap upload failure (weights unchanged)"
            )
        if self.prefix_cache is not None:
            # queued requests hold pins from admission-time matching; drop
            # them (they re-match against fresh KV at prefill) so flush can
            # take every node
            self.scheduler.drop_cache_pins()
            flushed = self.prefix_cache.flush()
        else:
            flushed = 0
        if self.mesh is not None:
            from ..parallel.sharding import shard_pytree_with_path
            from ..parallel.tensor_parallel import (
                SERVING_TP_RULES,
                make_tp_sharding_fn,
            )

            self.params, _ = shard_pytree_with_path(
                params,
                make_tp_sharding_fn(
                    self.mesh, axis_name=self.tp_axis, rules=SERVING_TP_RULES
                ),
            )
        else:
            self.params = jax.device_put(params)
        if self.tree is not None and isinstance(self._draft_spec, int):
            # self-speculative draft: re-slice the head from the NEW weights
            # so the draft keeps tracking the served model across the swap
            # (a stale head would only cost acceptance, but why pay it)
            _, draft_host = build_draft(
                self.config, self.params, self._draft_spec,
                draft_ctx=self.draft_ctx, depth=self.tree_depth,
            )
            self._draft_params = (
                jax.device_put(draft_host) if self._shardings is None
                else jax.device_put(draft_host, self._shardings.replicated)
            )
        old = self.weights_version
        if version is not None:
            self.weights_version = str(version)
        self._bump("hot_swaps")
        self.recorder.record(
            "serve/hot_swap", old_version=old, new_version=self.weights_version,
            step=self._step_count, cache_nodes_flushed=flushed,
        )

    # -------------------------------------------------------- fault tolerance
    def kill(self, reason: str = "replica killed") -> None:
        """Poison this engine as if its device vanished mid-window: every
        subsequent :meth:`step` raises without touching the pool.  The router
        supervisor sees ``_poisoned``, exports the in-flight requests, and
        replays them on surviving replicas.  Chaos tests and the
        ``replica_kill`` fault point call this; :meth:`revive` undoes it."""
        self._poisoned = faults.FaultInjected(reason)
        self.recorder.record(
            "serve/engine_poisoned", error=reason, step=self._step_count,
        )

    def export_inflight(self) -> List[Request]:
        """Snapshot every request this engine still owes an answer, detached
        and ready for :meth:`adopt` on a survivor.  The marshalling lives in
        :func:`serving.transfer.export_inflight` — the state-movement module
        shared with live page migration; this method is its engine-facing
        entry point."""
        return transfer.export_inflight(self)

    def adopt(self, request: Request) -> Request:
        """Admit a request exported from a dead replica, at the FRONT of the
        queue.  Greedy lanes replay token-exact; sampled lanes resume on a
        re-seeded stream (distribution-correct, not sample-exact — live
        migration via :class:`serving.transfer.PageMigrator` is the
        bit-identical alternative when the source's pages are readable).
        The marshalling lives in :func:`serving.transfer.adopt`."""
        return transfer.adopt(self, request)

    def revive(self) -> None:
        """Tear a poisoned engine back down to a serviceable idle state.

        The half-open circuit breaker's probe path: settle whatever the dead
        step left in flight (a failed fetch is recorded, not fatal — the
        window's pages still settle), retire every lane, drop the prefill
        plan and any stragglers in the queue, flush the prefix cache (its
        retained KV may be torn mid-write), and clear the poison.  The lane
        device mirrors are dropped wholesale — the next dispatch re-uploads
        them fresh rather than trusting vectors a dying window may have
        corrupted."""
        handles = [h for h in (self._prev_handle, self._inflight)
                   if h is not None]
        self._prev_handle = self._inflight = None
        for hd in handles:
            try:
                fetch(hd.toks)  # sync: proves the window's writes landed
            except Exception as exc:
                self.recorder.record(
                    "serve/revive_fetch_failed", error=repr(exc),
                )
            if self.paged and hd.deferred_pages:
                hd.settle(self.kv.allocator)
        self._stale_handles.clear()
        self._pending_prefill_qerr.clear()
        try:
            self._settle_spills(self._pending_spills)
        except Exception as exc:
            # the gathers rode the poisoned dispatch stream: their payloads
            # can't be trusted, so the nodes drop instead of staying spilled
            self.recorder.record("serve/revive_spill_failed", error=repr(exc))
            if self.prefix_cache is not None:
                for node, handles in self._pending_spills:
                    if node.host is handles:
                        self.prefix_cache.discard_spilled(node)
        self._pending_spills = []
        self._pending_promotions = []
        self._cycle_decode_tokens = 0
        for s in range(self.num_slots):
            if self._active[s] or self._slot_req[s] is not None:
                self._retire_lane(s)
        self.scheduler.take_prefills()
        self._reserved_slots.clear()
        for req in list(self.scheduler.queue):
            # export_inflight normally emptied this; anything left has no
            # owner to stream to — drop it cleanly with its pins
            self.scheduler.cancel(req.rid)
        if self.prefix_cache is not None:
            self.scheduler.drop_cache_pins()
            self.prefix_cache.flush()
        self._lane_device = None
        self._mask_stale = False
        self._t_pipeline_empty = None
        self._poisoned = None
        self.admission_paused = False
        self.recorder.record("serve/revive", step=self._step_count)

    def _shed_blown_deadlines(self) -> None:
        """Per-step deadline sweep (only runs while a deadline is live):
        cancel running lanes and queued requests past their ``deadline_s``,
        marking ``deadline_exceeded`` so the API layer answers 504."""
        now = time.perf_counter()
        any_live = False
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if req is None or req.deadline_s is None or not self._active[s]:
                continue
            elapsed = now - req.submit_time
            if elapsed <= req.deadline_s:
                any_live = True
                continue
            self._retire_lane(s)
            req.deadline_exceeded = True
            req.state = RequestState.CANCELLED
            req.finish_step = self._step_count
            self._bump("deadline_shed")
            self._bump_tenant(req.tenant, "deadline_shed")
            self.recorder.record(
                "serve/deadline_shed", where="running", rid=req.rid, slot=s,
                deadline_s=req.deadline_s, elapsed_s=elapsed,
                tokens=len(req.tokens),
            )
            if req.trace is not None:
                req.trace.annotate("deadline_shed", where="running",
                                   deadline_s=req.deadline_s)
                self.reqtrace.complete(req.trace, status="shed")
        for req in list(self.scheduler.queue):
            if req.deadline_s is None:
                continue
            elapsed = now - req.submit_time
            if elapsed <= req.deadline_s:
                any_live = True
                continue
            self.scheduler.cancel(req.rid)
            req.deadline_exceeded = True
            self._bump("deadline_shed")
            self._bump_tenant(req.tenant, "deadline_shed")
            self.recorder.record(
                "serve/deadline_shed", where="queued", rid=req.rid,
                deadline_s=req.deadline_s, elapsed_s=elapsed,
            )
            if req.trace is not None:
                req.trace.annotate("deadline_shed", where="queued",
                                   deadline_s=req.deadline_s)
                self.reqtrace.complete(req.trace, status="shed")
        if any(r.deadline_s is not None for r in self.scheduler.prefills):
            any_live = True  # finishes its chunks; the running sweep catches it
        self._has_deadlines = any_live

    # -------------------------------------------------------------- admission
    def _next_free_slot(self) -> Optional[int]:
        # a lane freed while its window is still in flight is immediately
        # admissible: the host mask/slot_req are authoritative (the stale
        # device mask only costs the dead lane one extra masked window), and
        # in-flight writes to the slot are overwritten by insert/prefill,
        # which queue behind the window on device
        for s in self.slot_order:
            if (not self._active[s] and self._slot_req[s] is None
                    and s not in self._reserved_slots):
                return s
        return None

    def _admit(self) -> None:
        # paused admission (drain / hot-swap): never START a prefill, but a
        # request already mid-prefill finishes — abandoning it would leak its
        # reserved slot and cache pins
        if self.admission_paused and not self.scheduler.prefills:
            return
        # joint per-cycle budget: in interleaved mode the decode window
        # dispatched before admission and charged its tokens; the default
        # ordering charges zero (decode dispatches after)
        budget = self.scheduler.begin_step(self._cycle_decode_tokens)
        while True:
            if not self.admission_paused:
                # open prefills up to the scheduler's cap (1, or one per slot
                # in interleaved mode) while slots and pages allow
                while (self.scheduler.queue
                       and len(self.scheduler.prefills)
                       < self.scheduler.max_prefills):
                    slot = self._next_free_slot()
                    if slot is None:
                        break
                    if self.paged and not self._admission_pages_ok(
                            self.scheduler.queue[0]):
                        break
                    self.scheduler.start_next(slot)
                    self._reserved_slots.add(slot)
                    if not self.paged:
                        # scratch restarts at position 0; stale KV beyond each
                        # new write is unreachable (causal mask == valid-entry
                        # mask)
                        self.scratch = self.scratch.replace(
                            index=self._put(jnp.zeros((), jnp.int32))
                        )
            if not self.scheduler.prefills:
                return
            took = self.scheduler.take_chunk(
                budget,
                ready=self._ensure_prefill_pages if self.paged else None,
            )
            if took is None:
                return  # budget spent or page pressure: retry next step
            req, bucket, valid, start, cached = took
            tr = req.trace
            if tr is not None and not tr.queue_done:
                # first chunk taken: the queue_wait phase ends here
                tr.queue_done = True
                self._queue_wait_hist.observe(
                    tr.phase("queue_wait", queue_depth=self.scheduler.queue_depth)
                )
            ptoks = req.prefill_tokens
            if cached:
                node = req.cache_nodes[req.next_chunk - 1]
                spilled = self.paged and node.tier != "device"
                if spilled and not self._promote_node(req, node, bucket):
                    # degraded promotion (fault, page pressure, or a torn
                    # payload): fall through to a plain cache miss — the chunk
                    # re-prefills below, charging budget, and _populate_cache
                    # heals the node with the fresh pages.  Token-identical:
                    # the lane's KV is recomputed, never partially installed.
                    cached = False
                    self.recorder.record(
                        "serve/promote_degraded", rid=req.rid, bucket=bucket,
                        step=self._step_count,
                    )
                elif self.paged:
                    if not spilled:
                        # the zero-copy hit: alias the node's physical pages
                        # into this lane's block table — no device work at all
                        self.kv.lane_append_shared(req.slot, node.pages)
                else:
                    # replay the retained slab: one dynamic_update_slice at the
                    # scratch index, zero budget charged (no forward pass ran)
                    self.cost_table.capture(
                        f"serve/copy_{bucket}", self._copy[bucket],
                        (self.scratch, node.k, node.v),
                    )
                    with self.tracer.span("serve/copy_chunk", bucket=bucket, start=start):
                        self.scratch = self._copy[bucket](self.scratch, node.k, node.v)
                if cached:
                    self._bump("prefix_hit_tokens", valid)
                    if spilled:
                        self._bump("prefix_hit_tokens_host", valid)
            if not cached:
                chunk = np.zeros(bucket, np.int32)
                chunk[:valid] = ptoks[start:start + valid]
                if self.paged:
                    self._paged_prefill_chunk(req, bucket, valid, chunk, start)
                else:
                    self.cost_table.capture(
                        f"serve/prefill_{bucket}", self._prefill[bucket],
                        (self.params, chunk[None], self.scratch),
                    )
                    with self.tracer.span("serve/prefill_chunk", bucket=bucket, valid=valid):
                        self.scratch = self._prefill[bucket](self.params, chunk[None], self.scratch)
                budget -= bucket
                self._bump("prefill_chunks")
                if self.interleave_prefill and self._cycle_decode_tokens:
                    # a decode window was dispatched this same cycle and this
                    # chunk queued behind it: the interleave actually happened
                    self._bump("interleaved_chunks")
                if self.prefix_cache is not None and req.cache_prefix:
                    self._bump("prefix_miss_tokens", valid)
                    self._populate_cache(req, bucket, valid, start, ptoks)
            self._bump("prefill_tokens", valid)
            if tr is not None:
                # one tiled phase per admitted chunk with hit-tier attribution
                # (a degraded promotion re-entered the fresh path above)
                source = ("fresh" if not cached
                          else "promoted" if spilled else "cached")
                self._prefill_phase_hist.observe(tr.phase(
                    "prefill", chunk=req.next_chunk - 1, bucket=bucket,
                    tokens=valid, source=source,
                ))
            done = self.scheduler.finish_prefill()
            if done is not None:
                self._install(done)

    # ---------------------------------------------------------- paged admission
    def _on_prefix_evict(self, node) -> None:
        """Prefix-cache eviction hook (paged mode): drop the cache's allocator
        reference on each retained page.  Pages still aliased by running lanes
        survive; unreferenced ones return to the free list.  Spilled nodes
        arrive here with ``pages = None`` — their refs were already dropped at
        demotion time by :meth:`_spill_node`."""
        if node.pages:
            self.kv.allocator.deref(node.pages)

    # ----------------------------------------------------- hierarchical cache
    def _spill_node(self, node):
        """PrefixCache ``spill`` hook: demote a device-tier node into the
        host ring.  Enqueues the bucket's D2H page gather and releases the
        cache's page refs immediately — the device executes in dispatch
        order, so any later prefill recycling those pages is ordered BEHIND
        the gather and the extracted payload is exact.  Nothing blocks here:
        the gather's device handles become the node's interim payload and the
        actual host copy lands at the next drain (``Readback.spills``).
        Returns ``None`` (node drops instead) when the node's page count
        matches no prefill bucket."""
        bucket = len(node.pages) * self.page_size
        if bucket not in self._spill_extract:
            return None
        kv = self.kv
        ids = self._put(np.asarray(node.pages, np.int32))
        with self.tracer.span("serve/spill_d2h", bucket=bucket):
            handles = self._spill_extract[bucket](
                kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales, ids,
            )
        self.kv.allocator.deref(node.pages)
        self._pending_spills.append((node, handles))
        self.recorder.record(
            "serve/spill", bucket=bucket, step=self._step_count,
            behind_window=self._inflight is not None
            or self._prev_handle is not None,
        )
        return handles

    def _put_kv_chunk(self, x: np.ndarray):
        """Upload one spilled chunk's page data with the pool's placement
        (head-axis sharded under a mesh, so the promote install's donated
        in-place aliasing holds per shard)."""
        if self._shardings is not None:
            return jax.device_put(np.ascontiguousarray(x), self._shardings.kv)
        return jnp.asarray(x)

    def _put_scale_chunk(self, x: np.ndarray):
        if self._shardings is not None:
            return jax.device_put(
                np.ascontiguousarray(x), self._shardings.scales
            )
        return jnp.asarray(x)

    def _promote_node(self, req: Request, node, bucket: int) -> bool:
        """Promote one spilled prefix chunk host -> device for ``req``:
        allocate fresh pages, upload the payload, and enqueue the
        scatter-install BEHIND the in-flight decode window — the depth-1
        discipline: the old pool handles park on ``_stale_handles`` and ride
        out on the next window's ``Readback.consumed``, and completion is
        acknowledged at that window's drain (``Readback.promotions``).  Never
        syncs.  Returns False — degrading the chunk to a plain miss, with
        NOTHING installed and the engine state untouched — on an injected
        ``promote_h2d`` fault, a torn payload, or unrecoverable page
        pressure."""
        if faults.ACTIVE is not None and faults.ACTIVE.fire("promote_h2d"):
            self.recorder.record(
                "serve/fault", point="promote_h2d", rid=req.rid,
                step=self._step_count,
            )
            return False
        payload = self.prefix_cache.node_payload(node)
        if payload is None:
            return False
        npg = bucket // self.page_size
        ids = self.kv.allocator.alloc(npg)
        if ids is None:
            if not self._reclaim_pages(npg, allow_preempt=False):
                return False
            ids = self.kv.allocator.alloc(npg)
            if ids is None:
                return False
        kv = self.kv
        ck, cv, cks, cvs = payload
        if isinstance(ck, np.ndarray):
            # landed (or disk-reloaded) payload: H2D upload, pool placement
            ck, cv = self._put_kv_chunk(ck), self._put_kv_chunk(cv)
            cks = self._put_scale_chunk(cks)
            cvs = self._put_scale_chunk(cvs)
        # else: the spill gather hasn't drained yet — its device outputs feed
        # the install directly, ordered behind the gather by dispatch order
        behind = self._inflight is not None or self._prev_handle is not None
        # admission may run under an in-flight window that consumes the pool
        # handles: park them so the rebind below never drops a consumed handle
        self._stale_handles += [kv.pages_k, kv.pages_v,
                                kv.k_scales, kv.v_scales]
        with self.tracer.span("serve/promote_h2d", bucket=bucket,
                              behind_window=behind):
            (kv.pages_k, kv.pages_v, kv.k_scales,
             kv.v_scales) = self._promote_install[bucket](
                kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales,
                ck, cv, cks, cvs, self._put(np.asarray(ids, np.int32)),
            )
        self.kv.lane_append_owned(req.slot, ids)  # lane takes the alloc ref
        if self.prefix_cache.promote_node(node, ids):
            # re-admitted to the device tier: the cache holds its own ref per
            # page (dropped again by _on_prefix_evict); on failure the node
            # stays spilled and only the lane owns the pages
            self.kv.allocator.ref(ids)
        self._pending_promotions.append({
            "rid": req.rid, "bucket": bucket, "behind_window": behind,
            "step": self._step_count, "trace": req.trace,
        })
        if req.trace is not None:
            req.trace.annotate("promote_dispatch", bucket=bucket,
                               behind_window=behind)
        self.recorder.record(
            "serve/promote_h2d", rid=req.rid, bucket=bucket,
            behind_window=behind, step=self._step_count,
        )
        return True

    def _settle_spills(self, entries: list) -> None:
        """Land pending spill payloads (drain side): the producing gathers
        retired behind the window that just drained, so each fetch returns
        without a real wait.  Entries whose node moved on (promoted, healed,
        or dropped while the gather was in flight) are fetched and discarded
        — fetching first keeps the handle-drop from ever blocking on a
        consumer still in flight."""
        for node, handles in entries:
            arrays = fetch(*handles)
            if self.prefix_cache is not None and node.host is handles:
                self.prefix_cache.settle_payload(node, arrays)

    def _admission_pages_ok(self, req: Request) -> bool:
        """Can the queue head's whole prefill be paged in?  Conservative
        (cached chunks alias pages and cost nothing; the count uses the match
        from submit, which admission may improve).  Reclaims WITHOUT
        preemption — evicting a running lane to admit behind it would invert
        FCFS and can livelock under steady overload."""
        padded = sum(b for b, _ in req.chunks)
        # only device-tier cached chunks alias for free; spilled chunks
        # promote into freshly allocated pages and must be charged
        cached = sum(
            b for i, (b, _) in enumerate(req.chunks[:req.cached_chunks])
            if i < len(req.cache_nodes) and req.cache_nodes[i].tier == "device"
        )
        need = (padded - cached) // self.page_size
        if self.kv.allocator.free_count >= need:
            return True
        return self._reclaim_pages(need, allow_preempt=False)

    def _ensure_prefill_pages(self, req: Request) -> bool:
        """Pages for ``req``'s NEXT chunk (the scheduler's ``ready`` predicate
        inside ``take_chunk``).  False skips this request for this engine step
        — running lanes keep decoding, their completions free pages, and the
        stalled chunk retries next step (or SRTF picks a smaller prefill)."""
        if req.next_chunk >= len(req.chunks):
            return True
        if req.next_chunk < req.cached_chunks:
            node = (req.cache_nodes[req.next_chunk]
                    if req.next_chunk < len(req.cache_nodes) else None)
            if node is None or node.tier == "device":
                return True  # device-tier hit: aliases pages, allocates none
            # spilled chunk: promotion scatter-installs into fresh pages
        bucket, _ = req.chunks[req.next_chunk]
        need = bucket // self.page_size
        if self.kv.allocator.free_count >= need:
            return True
        return self._reclaim_pages(need, allow_preempt=False)

    def _paged_prefill_chunk(self, req: Request, bucket: int, valid: int,
                             chunk: np.ndarray, start: int) -> None:
        """Prefill one fresh chunk straight into newly allocated lane pages.
        The executable gathers the lane's full view — shared prefix pages
        included, which is how a partial hit feeds context to the chunks after
        it — and scatters back only the chunk's own (page-aligned) span."""
        s = req.slot
        ids = self.kv.allocator.alloc(bucket // self.page_size)
        if ids is None:  # _ensure_prefill_pages runs first; this cannot happen
            raise RuntimeError("KV page pool exhausted mid-prefill")
        self.kv.lane_append_owned(s, ids)
        kv = self.kv
        table = self._put(kv.tables[s])
        base = self._put(jnp.int32(start))
        if self._prefill_direct:
            args = (self.params, chunk[None], kv.pages_k, kv.pages_v,
                    kv.k_scales, kv.v_scales, table, base)
            self.cost_table.capture(
                f"serve/prefill_{bucket}", self._prefill[bucket], args,
            )
            with self.tracer.span("serve/prefill_chunk", bucket=bucket, valid=valid):
                (kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales,
                 qerr) = self._prefill[bucket](*args)
            if self.quantized:
                # don't fetch() here — that would sync the pipeline right
                # behind the chunk; park the handle and fold it into the
                # gauge when the next window drains
                self._pending_prefill_qerr.append(qerr)
            return
        self.cost_table.capture(
            f"serve/prefill_{bucket}", self._prefill[bucket],
            (self.params, chunk[None], kv.pages_k, kv.pages_v, table, base),
        )
        with self.tracer.span("serve/prefill_chunk", bucket=bucket, valid=valid):
            kv.pages_k, kv.pages_v = self._prefill[bucket](
                self.params, chunk[None], kv.pages_k, kv.pages_v, table, base,
            )

    def _reclaim_pages(self, need: int, allow_preempt: bool) -> bool:
        """Recover free pages until at least ``need`` are available.  The
        ladder, cheapest first: (1) evict unpinned prefix-cache leaves —
        dropping the cache's reference frees any page no lane still aliases;
        (2) drain the in-flight window so pages parked on its deferral list
        (lanes freed/preempted after it dispatched) return to the pool — one
        pipeline sync, but nothing running is sacrificed; (3) preempt the
        youngest running lane (its pages free NOW; it requeues at the front
        and replays through the cache); (4) strip queued requests' cache pins
        so step 1 can reach more leaves.  Returns False when the ladder is
        exhausted short of ``need``."""
        while self.kv.allocator.free_count < need:
            if self.prefix_cache is not None and self.prefix_cache.evict_one():
                continue
            if ((self._inflight is not None and self._inflight.deferred_pages)
                    or (self._prev_handle is not None
                        and self._prev_handle.deferred_pages)):
                self._drain_inflight()
                continue
            if allow_preempt and self._preempt():
                continue
            if self.scheduler.drop_cache_pins() > 0:
                continue
            return False
        return True

    def _preempt(self) -> bool:
        """Preempt the youngest replayable running lane: release its pages,
        requeue it at the FRONT for replay over prompt + generated tokens
        (ideally hitting the cache chunks it populated in its first life).
        Youngest-first keeps FCFS intact — the last admitted is the first
        sacrificed.  Greedy replay is token-exact; a sampled victim resumes
        on a re-seeded RNG stream (``_install`` folds the base rng with the
        rid again), so its continuation is distribution-correct but not
        sample-exact.  Returns False with no replayable victim."""
        victims = sorted(
            (s for s in np.nonzero(self._active)[0] if self._slot_req[s] is not None),
            key=lambda s: self._slot_req[s].rid, reverse=True,
        )
        for s in victims:
            req = self._slot_req[s]
            eff = len(req.prefill_tokens)
            padded = sum(b for b, _ in plan_chunks(eff, self.buckets))
            if eff > self.max_prompt_len or padded > self.max_len:
                continue  # grew past replayability (max_prompt_len < max_len)
            # tokens the in-flight window lands for the victim are dropped at
            # drain and regenerated by the replay (token-exact under greedy)
            freed = self._retire_lane(s)
            self.scheduler.requeue(req)
            self._bump("preemptions")
            self._bump_tenant(req.tenant, "preemptions")
            self.recorder.record(
                "serve/preempt", rid=req.rid, slot=int(s), step=self._step_count,
                pages_freed=freed, effective_len=eff,
            )
            if req.trace is not None:
                req.trace.annotate("preempt", slot=int(s), pages_freed=freed,
                                   generated=len(req.tokens))
            return True
        return False

    def _ensure_decode_capacity(self, width: int) -> None:
        """Map pages for every active lane's next ``width`` KV writes
        (positions ``lane_len .. lane_len + width - 1``).  Under pressure the
        full reclaim ladder runs, preemption included — the youngest lane
        funds the older ones, and if a lane preempts ITSELF the loop simply
        moves on (its pages are already free)."""
        page = self.page_size
        for s in np.nonzero(self._active)[0]:
            need = (int(self._lane_len[s]) + width - 1) // page + 1
            while self._active[s]:
                missing = need - int(self.kv.lane_npages[s])
                if missing <= 0:
                    break
                ids = self.kv.allocator.alloc(missing)
                if ids is not None:
                    self.kv.lane_append_owned(s, ids)
                    break
                if not self._reclaim_pages(missing, allow_preempt=True):
                    raise RuntimeError(
                        "KV page pool exhausted: no cache leaf, lane, or pin "
                        "left to reclaim for a decoding lane"
                    )

    def _populate_cache(self, req: Request, bucket: int, valid: int, start: int,
                        ptoks: np.ndarray) -> None:
        """Retain a freshly prefilled FULL chunk in the prefix cache.

        Legacy: the slab slice ``scratch[:, :, start:start+bucket]`` is an
        eager device-side copy (a handful of static offsets per geometry,
        never a per-request shape).  Paged: zero copies — the cache node
        records the lane's own physical page ids and takes one allocator
        reference per page, so the KV outlives the lane.  Padded final chunks
        are skipped — their KV past ``valid`` is garbage — and once one chunk
        fails to retain (budget or collision) the rest of the request's chain
        is abandoned: a child without its ancestors could never be matched.
        """
        if valid != bucket or req.cache_chain_broken:
            return
        parent = req.cache_nodes[-1] if req.cache_nodes else None
        if self.paged:
            npg = bucket // self.page_size
            ids = self.kv.chunk_ids(req.slot, start // self.page_size, npg)
            node = self.prefix_cache.insert_pages(
                parent, ptoks[start:start + bucket], ids,
                nbytes=self.kv.chunk_bytes(npg),
            )
            if node is not None and node.pages == tuple(ids):
                # a NEW node was created: the cache holds its own reference
                # per page (dropped by _on_prefix_evict); a deduped re-insert
                # keeps the resident node's pages and refs untouched
                self.kv.allocator.ref(ids)
        else:
            k = self.scratch.k[:, :, start:start + bucket]
            v = self.scratch.v[:, :, start:start + bucket]
            if bucket == self.scratch.k.shape[2]:
                # a full-extent slice can alias the scratch buffer itself
                # (XLA elides the identity slice) — the cache must own a real
                # copy, or the next hit's copy executable sees its own donated
                # scratch arrive again as the node argument and aborts with
                # `f(donate(a), a)`.  Only possible when a prefill bucket
                # equals max_prompt_len; strict sub-slices always copy.
                k, v = jnp.copy(k), jnp.copy(v)
            node = self.prefix_cache.insert(
                parent, ptoks[start:start + bucket], k, v,
            )
        if node is None:
            req.cache_chain_broken = True
        else:
            self.prefix_cache.acquire([node])
            req.cache_nodes.append(node)

    def _cow_tail_page(self, s: int, plen: int) -> None:
        """Copy-on-write for the single spot sharing and writing can collide:
        the page holding position ``plen - 1``, the lane's first decode-write
        target.  Chunk starts are page-aligned (buckets are multiples of the
        page size), so every OTHER shared page lies strictly before the write
        frontier and every later page is freshly allocated.  Re-checks after
        each reclaim — eviction can dissolve the sharing and make the copy
        unnecessary."""
        pslot = (plen - 1) // self.page_size
        pid = int(self.kv.tables[s, pslot])
        while int(self.kv.allocator.refs[pid]) > 1:
            new = self.kv.allocator.alloc(1)
            if new is None:
                if not self._reclaim_pages(1, allow_preempt=True):
                    raise RuntimeError("KV page pool exhausted during copy-on-write")
                continue
            kv = self.kv
            # admission runs under the previous step's in-flight window, which
            # consumes these page handles: park them until its drain so the
            # rebind below never drops a consumed handle (see _stale_handles)
            self._stale_handles += [kv.pages_k, kv.pages_v,
                                    kv.k_scales, kv.v_scales]
            with self.tracer.span("serve/copy_page", src=pid, dst=new[0]):
                kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales = self._copy_page(
                    kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales,
                    self._put(jnp.int32(pid)), self._put(jnp.int32(new[0]))
                )
            kv.lane_replace(s, pslot, new[0])
            self._bump("cow_copies")
            return

    def _install(self, req: Request) -> None:
        """Hand a fully prefilled request its lane.  Legacy: one
        ``dynamic_update_slice`` of the scratch slab into the pool.  Paged:
        the lane's pages ARE the prefilled KV — nothing moves; only the
        shared tail page (if any) is copy-on-write duplicated before decode
        starts writing at ``plen - 1``."""
        s = req.slot
        ptoks = req.prefill_tokens
        plen = len(ptoks)
        if self.paged:
            self._cow_tail_page(s, plen)
            self._lane_len[s] = plen - 1
        else:
            slot_i = self._put(jnp.int32(s))
            length_i = self._put(jnp.int32(plen - 1))
            self.cost_table.capture(
                "serve/insert", self._insert,
                (self.pool, self.scratch.k, self.scratch.v, slot_i, length_i),
            )
            # the in-flight window (if any) consumes the current pool handle;
            # park it until drain rather than dropping it with the rebind
            self._stale_handles.append(self.pool)
            self.pool = self._insert(
                self.pool, self.scratch.k, self.scratch.v, slot_i, length_i,
            )
        self.recorder.record(
            "serve/install", rid=req.rid, slot=s, step=self._step_count,
            prompt_len=plen,
        )
        gen = req.config
        rng = np.asarray(jax.random.fold_in(self._base_rng, req.rid), np.uint32)
        eos_v = -1 if gen.eos_token_id is None else gen.eos_token_id
        top_k_v = 0 if gen.top_k is None else gen.top_k
        top_p_v = 1.0 if gen.top_p is None else gen.top_p
        if self._lane_device is not None:
            # Admission must not sync the pipeline: pending/rng are carried
            # on device between windows, and fetching them here would block
            # on the in-flight window.  A one-slot device-side scatter edits
            # the carried vectors instead — it enqueues behind the in-flight
            # window and costs the host only a dispatch.
            ld = self._lane_device
            # the replaced handles are inputs of the scatter (and outputs of
            # the in-flight window): park them until the next drain so their
            # destructors never wait on pending device work
            self._stale_handles += [ld[0], ld[1], ld[2], ld[3], ld[4],
                                    ld[5], ld[6], ld[8]]
            (ld[0], ld[1], ld[2], ld[3], ld[4], ld[5], ld[6],
             ld[8]) = self._lane_install(
                ld[0], ld[1], ld[2], ld[3], ld[4], ld[5], ld[6], ld[8],
                self._put(np.int32(s)), self._put(np.int32(ptoks[-1])),
                self._put(np.int32(eos_v)), self._put(np.bool_(gen.do_sample)),
                self._put(np.float32(gen.temperature)),
                self._put(np.int32(top_k_v)), self._put(np.float32(top_p_v)),
                self._put(rng),
            )
        self._pending_tok[s] = ptoks[-1]
        if self._draft_window is not None:
            # seed the draft context from the prompt tail: its last token IS
            # the lane's pending token, which the draft forward echoes as the
            # tree root — the invariant the tree verify's tokens[:, 0] needs
            self._draft_window.begin(s, ptoks)
        self._active[s] = True
        self._eos[s] = eos_v
        self._do_sample[s] = gen.do_sample
        self._temperature[s] = gen.temperature
        self._top_k[s] = top_k_v
        self._top_p[s] = top_p_v
        self._rngs[s] = rng
        if self._slot_ever_used[s]:
            self._bump("slots_reused")
        self._slot_ever_used[s] = True
        self._slot_req[s] = req
        self._reserved_slots.discard(s)
        # the slot owns a full KV copy now; the radix nodes this request read
        # or populated can be evicted without affecting it
        if self.prefix_cache is not None and req.cache_nodes:
            self.prefix_cache.release(req.cache_nodes)
            req.cache_nodes = []
        req.state = RequestState.RUNNING

    # ----------------------------------------------------------------- decode
    def _lane_arrays(self) -> list:
        """Device-resident lane vectors in decode/verify argument order
        (pending, active, eos, do_sample, temperature, top_k, top_p, pad,
        rngs).  Uploaded from the host mirrors once; after that the
        pending-token and rng entries are refreshed in place from each
        window's device-side outputs, installs edit one slot via the
        ``lane_install`` scatter, and a lane freed since the last dispatch
        re-uploads just the active mask — steady-state cycles upload
        nothing and nothing ever blocks on an in-flight window."""
        if self._lane_device is None:
            self._lane_device = [
                self._put(self._pending_tok), self._put(self._active),
                self._put(self._eos), self._put(self._do_sample),
                self._put(self._temperature), self._put(self._top_k),
                self._put(self._top_p),
                self._put(jnp.full((self.num_slots,), self.pad_token_id, jnp.int32)),
                self._put(self._rngs),
            ]
            self._mask_stale = False
        elif self._mask_stale:
            # a lane was freed while its window was in flight.  The active
            # mask is host-authoritative (no executable writes it), so the
            # dead lane is masked out by re-uploading this one vector — no
            # device sync, and the lane ran exactly one extra masked window.
            self._stale_handles.append(self._lane_device[1])
            self._lane_device[1] = self._put(self._active)
            self._mask_stale = False
        return self._lane_device

    def _retire_lane(self, slot: int) -> int:
        """Tear down one running lane (finish / cancel / preempt), deferring
        whatever the in-flight window still needs.  If the window was
        dispatched believing this lane live, its KV pages move to the
        window's deferral list (they free at drain, after the window's
        masked writes provably landed) and the device active mask is
        refreshed at the next dispatch instead of forcing a blocking mirror
        resync.  Returns pages freed *now* (0 when deferred)."""
        freed = 0
        inflight = self._inflight
        if inflight is not None and inflight.lane_live(slot):
            self._mask_stale = True
            if self.paged:
                inflight.deferred_pages.extend(self.kv.lane_detach(slot))
        else:
            # no window holds this lane: pages free immediately, and the
            # device mirror only needs its active bit dropped (the dead
            # lane's pending/rng entries are masked out until reinstall)
            self._mask_stale = True
            if self.paged:
                freed = self.kv.lane_release(slot)
        self._active[slot] = False
        self._slot_req[slot] = None
        if self._ngram is not None:
            self._ngram.retire(slot)
        if self._draft_window is not None:
            self._draft_window.retire(slot)
        if self.paged:
            self._lane_len[slot] = 0
        return freed

    def _free(self, slot: int, req: Request) -> None:
        self._retire_lane(slot)
        self._finish_request(slot, req)

    def _finish_request(self, slot: int, req: Request) -> None:
        req.state = RequestState.DONE
        req.finish_step = self._step_count
        # end-to-end service time EMA: the per-queued-request cost behind
        # submit()'s deadline feasibility estimate
        dur = max(time.perf_counter() - req.submit_time, 0.0)
        self._service_ema = (
            dur if self._service_ema == 0.0
            else 0.8 * self._service_ema + 0.2 * dur
        )
        self._bump("requests_completed")
        self._bump_tenant(req.tenant, "requests_completed")
        self.recorder.record(
            "serve/finish", rid=req.rid, slot=slot, step=self._step_count,
            tokens=len(req.tokens), steps=self._step_count - req.submit_step,
        )
        if req.trace is not None:
            req.trace.tokens = len(req.tokens)
            self.reqtrace.complete(req.trace, status="done")

    def _prefree_exhausted(self) -> None:
        """Retire lanes whose in-flight window provably exhausts their token
        budget — BEFORE this step's admission, so the slot refills this cycle
        instead of next.

        Without this, the depth-1 pipeline pays an occupancy lag the sync
        loop doesn't: a lane finishing inside window N is only discovered at
        N's drain, which runs after window N+1 dispatched AND after this
        step's admission — the slot sits dead for a full extra window.  But
        completion by length cap is host-arithmetic: a lane with no EOS
        configured lands exactly ``width`` tokens per decode window, so
        ``len(tokens) + width >= max_new_tokens`` proves death in flight.
        Such lanes retire here (pages deferred to the window, exactly the
        cancel-mid-flight path) and their slot admits a new request whose
        prefill/insert/scatter chain behind the in-flight window on device —
        the async admission schedule converges to the sync loop's.  The
        window's tokens still land at drain via the ``prefreed`` mark on the
        handle.  EOS-configured lanes and speculative lanes (commit counts
        are decided on device) keep the conservative one-window lag."""
        hd = self._inflight
        if hd is None or hd.kind != "decode":
            return
        for s in np.nonzero(self._active)[0]:
            s = int(s)
            req = self._slot_req[s]
            if req is None or not hd.lane_live(s) or hd.reqs[s] is not req:
                continue
            if self._eos[s] >= 0 or (self._spec_any and req.speculate):
                continue
            if len(req.tokens) + hd.width >= req.config.max_new_tokens:
                hd.prefreed.add(s)
                self._retire_lane(s)
                self._bump("prefreed_lanes")

    def _dispatch_decode(self) -> Optional["Readback"]:
        """Dispatch one decode phase over the pool — a speculative verify
        cycle when any lane has an n-gram draft, the plain decode window
        otherwise — and return the handle the caller must drain (the
        *previous* window under the depth-1 pipeline, this window itself
        under ``async_depth=0``, ``None`` when the pool is idle).

        Dispatch and drain are split so the step loop can run admission
        between them: with ``interleave_prefill`` the prefill chunk enqueues
        *behind* the window dispatched here, decode lanes never skip a cycle
        while a long prompt prefills, and the chunk still finishes under the
        host work of draining the previous window.  Speculative cycles drain
        first instead: drafting and the verify token block need the previous
        window's tokens.

        Side effect: ``self._cycle_decode_tokens`` is set to the token count
        charged by this cycle's window (0 when idle) — ``_admit`` subtracts
        it from the scheduler's joint per-cycle budget."""
        self._cycle_decode_tokens = 0
        if self._spec_any and self._inflight is not None:
            self._drain_inflight()
        if not self._active.any():
            self._drain_inflight()
            return None
        if self.paged:
            # map pages for the widest pass this cycle could run (the same
            # span the admission check reserved headroom for); this may
            # preempt the youngest lane under pressure, so re-check occupancy
            self._ensure_decode_capacity(max(self.window, self._spec_span))
            if not self._active.any():
                self._drain_inflight()
                return None
        n_occupied = int(self._active.sum())
        self.peak_active_lanes = max(self.peak_active_lanes, n_occupied)
        self._occupancy_gauge.set(n_occupied / self.num_slots)
        if faults.ACTIVE is not None and faults.ACTIVE.fire("decode_dispatch"):
            raise faults.FaultInjected(
                f"injected decode-window dispatch failure "
                f"(step {self._step_count}, {n_occupied} lanes)"
            )
        if self.tree is not None:
            drafted = self._tree_lanes()
            hd = (
                self._tree_cycle(drafted, n_occupied) if drafted.any()
                else self._decode_cycle(n_occupied)
            )
        else:
            drafts = self._propose_drafts() if self.speculate_k else None
            if drafts is not None:
                hd = self._verify_cycle(*drafts, n_occupied=n_occupied)
            else:
                hd = self._decode_cycle(n_occupied)
        self._cycle_decode_tokens = n_occupied * hd.width
        if self.async_depth == 0:
            return hd
        prev, self._inflight = self._inflight, hd
        return prev

    def _decode_window(self) -> None:
        """Dispatch one decode phase and drain the handle it returns — the
        non-interleaved step ordering (admission already ran)."""
        prev = self._dispatch_decode()
        if prev is not None:
            self._drain(prev)

    def _update_prefill_gauges(self) -> None:
        """Publish prefill throughput and the interleave ratio.

        ``serve/prefill_tokens_per_s`` is valid prompt tokens through the
        prefill executables over wall time between steps that made prefill
        progress (idle stretches slide the window start so they don't dilute
        the rate).  ``serve/prefill_interleave_ratio`` is the fraction of
        forward-pass prefill chunks dispatched in the same cycle as a decode
        window — ~1.0 means long prompts rode along under decode; ~0.0 means
        chunks ran on an otherwise idle device (no interleaving to do, or
        ``interleave_prefill`` off)."""
        chunks = self.stats["prefill_chunks"]
        if chunks:
            self._interleave_gauge.set(
                self.stats["interleaved_chunks"] / chunks
            )
        tokens = self.stats["prefill_tokens"]
        now = time.perf_counter()
        if self._pf_last_t is None or tokens < self._pf_last_tokens:
            self._pf_last_t, self._pf_last_tokens = now, tokens
            return
        if tokens == self._pf_last_tokens:
            self._pf_last_t = now  # no prefill this step: slide the window
            return
        dt = now - self._pf_last_t
        if dt > 0.0:
            self._pf_rate_gauge.set((tokens - self._pf_last_tokens) / dt)
        self._pf_last_t, self._pf_last_tokens = now, tokens

    def _drain_inflight(self) -> None:
        """Flush the pipeline: materialize the in-flight window (if any) and
        land its tokens.  Called before speculative cycles, when the pool
        goes idle, and by the page-reclaim ladder to settle deferred pages.
        Oldest first: a previous window parked mid-step (interleaved
        admission runs between dispatch and drain) lands before the window
        dispatched after it, or tokens would interleave out of order."""
        prev, self._prev_handle = self._prev_handle, None
        if prev is not None:
            self._drain(prev)
        hd, self._inflight = self._inflight, None
        if hd is not None:
            self._drain(hd)

    def _note_dispatch(self) -> None:
        """Charge the gap since the pipeline last went empty as device idle
        time (the bubble the depth-1 pipeline exists to close)."""
        if self._t_pipeline_empty is not None:
            self._device_idle_s += time.perf_counter() - self._t_pipeline_empty
            self._idle_gauge.set(self._device_idle_s * 1e3)
            self._t_pipeline_empty = None

    def _drain(self, hd: Readback) -> None:
        """Land one window's deferred outputs: the ONE blocking readback per
        window, then all host-side bookkeeping against the window's
        dispatch-time lane snapshot (a lane freed/cancelled/preempted or
        re-installed since dispatch fails the ownership check in ``_emit``
        and its tokens are dropped — exactly what the sync loop would never
        have produced)."""
        try:
            self._drain_impl(hd)
        except BaseException:
            # a failed drain poisons this engine (step()'s wrapper) with the
            # handle already detached from ``_inflight`` — a pre-freed lane's
            # request lives ONLY on that handle, so requeue it here or
            # export_inflight never sees it and its caller waits forever
            for s in hd.prefreed:
                req = hd.reqs[s]
                if req is not None and req.state is RequestState.RUNNING:
                    self.scheduler.requeue(req)
            raise

    def _drain_impl(self, hd: Readback) -> None:
        if faults.ACTIVE is not None:
            if faults.ACTIVE.fire("fetch_slow"):
                time.sleep(faults.ACTIVE.slow_ms / 1e3)  # stalled interconnect
            if faults.ACTIVE.fire("fetch_fail"):
                raise faults.FaultInjected(
                    f"injected readback failure (step {self._step_count})"
                )
        t0 = time.perf_counter()
        with self.tracer.span("serve/readback", kind=hd.kind,
                              occupied=hd.n_occupied):
            if hd.kind == "verify":
                toks, counts = fetch(hd.toks, hd.counts)
            else:
                toks = fetch(hd.toks)
                counts = np.full(self.num_slots, hd.width)
        t1 = time.perf_counter()
        # overlap accounting: host work since dispatch ran under the device;
        # the blocking tail is what the pipeline failed to hide.  Under
        # async_depth=0 the drain follows dispatch immediately, so host ~ 0
        # and the ratio publishes ~0 — the honest baseline.
        host = max(t0 - hd.dispatch_t, 0.0)
        wait = max(t1 - t0, 0.0)
        self._overlap_host_s += host
        self._overlap_wait_s += wait
        denom = self._overlap_host_s + self._overlap_wait_s
        if denom > 0.0:
            self._overlap_gauge.set(self._overlap_host_s / denom)
        self.recorder.record(
            "serve/readback", step=self._step_count, window=hd.kind,
            wait_ms=wait * 1e3, overlapped_ms=host * 1e3,
        )
        hd.consumed.clear()
        if hd.spills:
            # the producing gathers retired behind the window that just
            # drained: land the host payloads now, off the device
            self._settle_spills(hd.spills)
            hd.spills = []
        for rec in hd.promotions:
            # install retired with the window it was enqueued behind
            tr = rec.pop("trace", None)
            if tr is not None and not tr.finished:
                self._promote_wait_hist.observe(
                    tr.phase("promote_wait", bucket=rec["bucket"])
                )
            self.recorder.record("serve/promote_land", **rec)
        hd.promotions = []
        if hd.qerr is not None and self._kv_quant_gauge is not None:
            self._kv_quant_gauge.set(float(fetch(hd.qerr)))
        if hd.prefill_qerrs and self._kv_quant_gauge is not None:
            # chunks attached to this handle dispatched no later than the
            # cycle after it, so their quant errors are (nearly) landed here;
            # publish the worst chunk of the batch
            self._kv_quant_gauge.set(
                max(float(fetch(e)) for e in hd.prefill_qerrs)
            )
            hd.prefill_qerrs = []
        if hd.kind == "verify":
            if self.paged:
                # the write-index mirror advances by what the device actually
                # committed — but only for lanes still owned by the request
                # the window was dispatched for (a cancelled lane's mirror
                # was reset to 0 and must stay there)
                for s in np.nonzero(hd.active)[0]:
                    if hd.reqs[s] is not None and self._slot_req[s] is hd.reqs[s]:
                        self._lane_len[s] += int(counts[s])
            accepted = int(np.maximum(counts[hd.drafted] - 1, 0).sum())
            self._bump("spec_accepted", accepted)
            for s in np.nonzero(hd.drafted)[0]:
                self._accept_len_hist.observe(
                    float(max(int(counts[s]) - 1, 0))
                )
            if self.stats["spec_drafted"]:
                self._accept_rate_gauge.set(
                    self.stats["spec_accepted"] / self.stats["spec_drafted"]
                )
            self.recorder.record(
                "serve/verify", step=self._step_count,
                drafted_lanes=hd.n_drafted, committed=int(counts.sum()),
                accepted=accepted,
            )
        self._trace_drain(hd, counts, t0, t1)
        self._emit(toks, counts, mask=hd.active, reqs=hd.reqs, eos=hd.eos,
                   prefreed=hd.prefreed)
        if self.paged and hd.deferred_pages:
            # fetch() above proved the window retired: its masked writes to
            # detached lanes' pages have landed, so the pages can recycle
            hd.settle(self.kv.allocator)
        if self._inflight is None:
            self._t_pipeline_empty = time.perf_counter()

    def _trace_drain(self, hd: Readback, counts: np.ndarray,
                     t0: float, t1: float) -> None:
        """Close per-request decode/spec_verify waterfall phases at DRAIN —
        under ``async_depth=1`` a window's cost is only known when its
        readback lands, so this is where attribution is honest.  Each live
        lane's phase spans its trace cursor to ``t1`` (the blocking fetch
        tail included, so tiled phases keep summing to wall time); the tail
        rides along as the phase's ``wait_s`` attribute, from which the
        debug endpoints synthesize the ``readback_wait`` overlay — one dict
        per lane per window here, not two.  Runs before ``_emit`` so the
        phases land ahead of the first-token mark."""
        phase = "spec_verify" if hd.kind == "verify" else "decode"
        wait = max(t1 - t0, 0.0)
        for s, req in hd.live_requests():
            tr = req.trace
            if tr is None or tr.finished:
                continue
            dur = tr.phase(phase, now=t1, step=self._step_count,
                           lanes=hd.n_occupied, wait_s=wait)
            n = max(int(counts[s]), 1)
            self._decode_tok_hist.observe(dur / n, n)

    def _decode_cycle(self, n_occupied: int) -> Readback:
        """Dispatch one decode window and return its in-flight handle.  The
        tokens stay on device: the caller decides when to drain (immediately
        under ``async_depth=0``, one cycle later under the pipeline).  The
        window's KV/pending/rng outputs rebind here, at dispatch — so the
        next dispatch donates the new handles, never a buffer the in-flight
        window still owns."""
        lanes = self._lane_arrays()
        self._note_dispatch()
        qerr = None
        if self.paged and self._direct:
            kv = self.kv
            audit_donation(kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales)
            consumed = [kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales,
                        lanes[0], lanes[-1]]
            tables = self._put(kv.tables)
            index = self._put(self._lane_len)
            consumed += [tables, index]
            args = (self.params, kv.pages_k, kv.pages_v, kv.k_scales,
                    kv.v_scales, tables, index, *lanes)
            if not self.cost_table.captured("serve/decode_window"):
                self.cost_table.capture("serve/decode_window", self._decode, args)
            with self.tracer.span("serve/decode_window", occupied=n_occupied):
                with self.tracer.span("serve/paged_attn", kernel=self.decode_kernel):
                    (kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales, toks,
                     pending, rngs, qerr) = self._decode(*args)
            self._lane_len[self._active] += self.window
        elif self.paged:
            kv = self.kv
            audit_donation(kv.pages_k, kv.pages_v)
            consumed = [kv.pages_k, kv.pages_v, lanes[0], lanes[-1]]
            # block tables + write indices ride up fresh each cycle (a few KB
            # of int32 — allocation is host-side and can change every cycle)
            tables = self._put(kv.tables)
            index = self._put(self._lane_len)
            consumed += [tables, index]
            if not self.cost_table.captured("serve/decode_window"):
                self.cost_table.capture(
                    "serve/decode_window", self._decode,
                    (self.params, kv.pages_k, kv.pages_v, tables, index, *lanes),
                )
            with self.tracer.span("serve/decode_window", occupied=n_occupied):
                kv.pages_k, kv.pages_v, toks, pending, rngs = self._decode(
                    self.params, kv.pages_k, kv.pages_v, tables, index, *lanes
                )
            self._lane_len[self._active] += self.window
        else:
            audit_donation(self.pool)
            consumed = [self.pool, lanes[0], lanes[-1]]
            if not self.cost_table.captured("serve/decode_window"):
                self.cost_table.capture(
                    "serve/decode_window", self._decode, (self.params, self.pool, *lanes)
                )
            with self.tracer.span("serve/decode_window", occupied=n_occupied):
                self.pool, toks, pending, rngs = self._decode(
                    self.params, self.pool, *lanes
                )
        # the carried pending token / rng live on into the next cycle without
        # touching the host (the host pending mirror is refreshed by _emit)
        lanes[0], lanes[-1] = pending, rngs
        self._bump("decode_steps", self.window)
        self._bump("occupied_lane_steps", n_occupied * self.window)
        consumed += self._stale_handles
        self._stale_handles = []
        return Readback(
            kind="decode", toks=toks, width=self.window, qerr=qerr,
            active=self._active.copy(), reqs=list(self._slot_req),
            eos=self._eos.copy(), n_occupied=n_occupied, consumed=consumed,
        )

    def _propose_drafts(self):
        """Host-side n-gram drafts for this cycle: ``(drafts [N, K], drafted
        [N])`` or ``None`` when no active opted-in lane found a match (the
        cycle falls back to the plain decode window).  Lanes without a match
        carry pad drafts — verification rejects them, and the lane still
        lands its >= 1 guaranteed token from the verify forward.

        Drafting goes through the per-lane incremental suffix index
        (:class:`~accelerate_tpu.serving.spec.NgramIndex` via
        :class:`~accelerate_tpu.serving.spec_exec.NgramDrafter`): each call
        feeds the index only the tokens committed since the previous cycle,
        so the host cost is O(K) per lane regardless of context length —
        token-identical to the O(context) rescan it replaced."""
        k = self.speculate_k
        drafts = np.full((self.num_slots, k), self.pad_token_id, np.int32)
        drafted = np.zeros(self.num_slots, bool)
        for s in np.nonzero(self._active)[0]:
            req = self._slot_req[s]
            if req is None or not req.speculate:
                continue
            d = self._ngram.propose(int(s), req.output_ids, k)
            if d is not None:
                drafts[s] = d
                drafted[s] = True
        if not drafted.any():
            return None
        return drafts, drafted

    def _tree_lanes(self) -> np.ndarray:
        """Active lanes opted into speculation this cycle (tree mode).  The
        draft model drafts for every lane in the batch anyway; this mask only
        scopes the accounting (``spec_drafted``/accept stats) and the
        all-opted-out fallback to the plain decode window."""
        drafted = np.zeros(self.num_slots, bool)
        for s in np.nonzero(self._active)[0]:
            req = self._slot_req[s]
            if req is not None and req.speculate:
                drafted[s] = True
        return drafted

    def _tree_cycle(self, drafted: np.ndarray, n_occupied: int) -> Readback:
        """Dispatch one draft forward + tree verify window pair; returns the
        verify handle.  The draft's ``[N, S]`` token tree never touches the
        host — the draft forward's output handle feeds the verify window
        directly, so the host cost of a tree cycle is two dispatches plus
        the usual control-state uploads.

        The draft context window's tail token equals each active lane's
        pending token (seeded at install, advanced in ``_emit``), so the
        draft output's column 0 — the tree root — is exactly the pending
        token the verify forward must score first.  Inactive lanes carry
        garbage roots; their writes are masked (paged: NULL_PAGE-routed)
        and their commits never emit."""
        tree = self.tree
        lanes = self._lane_arrays()
        self._note_dispatch()
        t0 = time.perf_counter()
        dw = self._draft_window
        ctx = self._put(dw.tokens)
        length = self._put(dw.length)
        if not self.cost_table.captured("serve/draft_forward"):
            self.cost_table.capture(
                "serve/draft_forward", self._draft_fwd,
                (self._draft_params, ctx, length),
            )
        with self.tracer.span("serve/draft_forward", occupied=n_occupied):
            tokens = self.drafter.propose_device(self._draft_params, ctx, length)
        self._draft_ms_hist.observe((time.perf_counter() - t0) * 1e3)
        n_drafted = int(drafted.sum())
        qerr = None
        if self.paged and self._direct:
            kv = self.kv
            audit_donation(kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales)
            consumed = [kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales,
                        lanes[0], lanes[-1]]
            tables = self._put(kv.tables)
            index = self._put(self._lane_len)
            consumed += [tables, index, tokens]
            args = (self.params, kv.pages_k, kv.pages_v, kv.k_scales,
                    kv.v_scales, tables, index, tokens, *lanes[1:])
            if not self.cost_table.captured("serve/tree_verify_window"):
                self.cost_table.capture(
                    "serve/tree_verify_window", self._verify, args
                )
            with self.tracer.span("serve/tree_verify_window",
                                  occupied=n_occupied, drafted=n_drafted):
                with self.tracer.span("serve/paged_attn",
                                      kernel=self.decode_kernel):
                    (kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales, out,
                     n_commit, pending, rngs, qerr) = self._verify(*args)
        elif self.paged:
            kv = self.kv
            audit_donation(kv.pages_k, kv.pages_v)
            consumed = [kv.pages_k, kv.pages_v, lanes[0], lanes[-1]]
            tables = self._put(kv.tables)
            index = self._put(self._lane_len)
            consumed += [tables, index, tokens]
            if not self.cost_table.captured("serve/tree_verify_window"):
                self.cost_table.capture(
                    "serve/tree_verify_window", self._verify,
                    (self.params, kv.pages_k, kv.pages_v, tables, index,
                     tokens, *lanes[1:]),
                )
            with self.tracer.span("serve/tree_verify_window",
                                  occupied=n_occupied, drafted=n_drafted):
                kv.pages_k, kv.pages_v, out, n_commit, pending, rngs = (
                    self._verify(
                        self.params, kv.pages_k, kv.pages_v, tables, index,
                        tokens, *lanes[1:]
                    )
                )
        else:
            audit_donation(self.pool)
            consumed = [self.pool, lanes[0], lanes[-1], tokens]
            if not self.cost_table.captured("serve/tree_verify_window"):
                self.cost_table.capture(
                    "serve/tree_verify_window", self._verify,
                    (self.params, self.pool, tokens, *lanes[1:]),
                )
            with self.tracer.span("serve/tree_verify_window",
                                  occupied=n_occupied, drafted=n_drafted):
                self.pool, out, n_commit, pending, rngs = self._verify(
                    self.params, self.pool, tokens, *lanes[1:]
                )
        lanes[0], lanes[-1] = pending, rngs
        self._bump("decode_steps", tree.depth + 1)
        self._bump("occupied_lane_steps", n_occupied * (tree.depth + 1))
        # accounting uses depth (the max acceptable along one path), not
        # tree nodes: accept rate stays in [0, 1] and comparable across
        # linear and tree arms; node volume has its own counter
        self._bump("spec_drafted", n_drafted * tree.depth)
        self._tree_nodes_counter.inc(n_occupied * tree.nodes)
        consumed += self._stale_handles
        self._stale_handles = []
        return Readback(
            kind="verify", toks=out, width=tree.depth + 1, counts=n_commit,
            qerr=qerr, active=self._active.copy(), reqs=list(self._slot_req),
            eos=self._eos.copy(), n_occupied=n_occupied,
            drafted=drafted.copy(), n_drafted=n_drafted, consumed=consumed,
        )

    def _verify_cycle(self, drafts: np.ndarray, drafted: np.ndarray,
                      n_occupied: int) -> Readback:
        """Dispatch one speculative verify window; returns its in-flight
        handle.  ``n_commit`` stays on device with the tokens — the paged
        write-index mirror therefore advances at *drain*, which is why
        speculative cycles drain the previous window before dispatching."""
        k = self.speculate_k
        lanes = self._lane_arrays()
        self._note_dispatch()
        # the host pending mirror is always fresh here (a pending verify
        # handle was drained before drafting); only the [N, K+1] token block
        # uploads per verify cycle
        tokens = self._put(
            np.concatenate([self._pending_tok[:, None], drafts], axis=1)
        )
        n_drafted = int(drafted.sum())
        qerr = None
        if self.paged and self._direct:
            kv = self.kv
            audit_donation(kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales)
            consumed = [kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales,
                        lanes[0], lanes[-1]]
            tables = self._put(kv.tables)
            index = self._put(self._lane_len)
            consumed += [tables, index, tokens]
            args = (self.params, kv.pages_k, kv.pages_v, kv.k_scales,
                    kv.v_scales, tables, index, tokens, *lanes[1:])
            if not self.cost_table.captured("serve/verify_window"):
                self.cost_table.capture("serve/verify_window", self._verify, args)
            with self.tracer.span("serve/verify_window", occupied=n_occupied,
                                  drafted=n_drafted):
                with self.tracer.span("serve/paged_attn", kernel=self.decode_kernel):
                    (kv.pages_k, kv.pages_v, kv.k_scales, kv.v_scales, out,
                     n_commit, pending, rngs, qerr) = self._verify(*args)
        elif self.paged:
            kv = self.kv
            audit_donation(kv.pages_k, kv.pages_v)
            consumed = [kv.pages_k, kv.pages_v, lanes[0], lanes[-1]]
            tables = self._put(kv.tables)
            index = self._put(self._lane_len)
            consumed += [tables, index, tokens]
            if not self.cost_table.captured("serve/verify_window"):
                self.cost_table.capture(
                    "serve/verify_window", self._verify,
                    (self.params, kv.pages_k, kv.pages_v, tables, index,
                     tokens, *lanes[1:]),
                )
            with self.tracer.span("serve/verify_window", occupied=n_occupied,
                                  drafted=n_drafted):
                kv.pages_k, kv.pages_v, out, n_commit, pending, rngs = self._verify(
                    self.params, kv.pages_k, kv.pages_v, tables, index,
                    tokens, *lanes[1:]
                )
        else:
            audit_donation(self.pool)
            consumed = [self.pool, lanes[0], lanes[-1], tokens]
            if not self.cost_table.captured("serve/verify_window"):
                self.cost_table.capture(
                    "serve/verify_window", self._verify,
                    (self.params, self.pool, tokens, *lanes[1:]),
                )
            with self.tracer.span("serve/verify_window", occupied=n_occupied,
                                  drafted=n_drafted):
                self.pool, out, n_commit, pending, rngs = self._verify(
                    self.params, self.pool, tokens, *lanes[1:]
                )
        lanes[0], lanes[-1] = pending, rngs
        self._bump("decode_steps", k + 1)
        self._bump("occupied_lane_steps", n_occupied * (k + 1))
        self._bump("spec_drafted", n_drafted * k)
        consumed += self._stale_handles
        self._stale_handles = []
        return Readback(
            kind="verify", toks=out, width=k + 1, counts=n_commit, qerr=qerr,
            active=self._active.copy(), reqs=list(self._slot_req),
            eos=self._eos.copy(), n_occupied=n_occupied,
            drafted=drafted.copy(), n_drafted=n_drafted, consumed=consumed,
        )

    def _emit(self, toks: np.ndarray, counts: np.ndarray,
              mask: Optional[np.ndarray] = None,
              reqs: Optional[List[Optional[Request]]] = None,
              eos: Optional[np.ndarray] = None,
              prefreed: Optional[set] = None) -> None:
        """Land device-produced tokens on their requests. ``toks[s, :counts[s]]``
        is lane ``s``'s output this cycle (a full decode window, or a verify
        cycle's committed prefix).  Per-lane take counts — EOS cut plus the
        per-request length cap — are computed in one numpy pass so host time
        stays flat in window size / speculate_k; only genuine per-request
        bookkeeping (streaming callbacks, histograms, frees) runs in Python.

        ``mask``/``reqs``/``eos`` are the window's dispatch-time snapshots
        (:class:`Readback`): under the pipeline the live lane state may have
        moved on — a lane freed/cancelled/preempted since dispatch no longer
        owns its slot, so the ownership check drops its tokens."""
        if mask is None:
            mask = self._active
        if reqs is None:
            reqs = self._slot_req
        if eos is None:
            eos = self._eos
        width = toks.shape[1]
        pos = np.arange(width)[None, :]
        valid = (pos < np.asarray(counts).reshape(-1, 1)) & mask[:, None]
        is_eos = valid & (toks == eos[:, None]) & (eos >= 0)[:, None]
        has_eos = is_eos.any(axis=1)
        first_eos = np.where(has_eos, is_eos.argmax(axis=1), width)
        n_take = np.minimum(valid.sum(axis=1), first_eos + 1)
        now = time.perf_counter()
        for s in np.nonzero(n_take > 0)[0]:
            req = reqs[s]
            if req is None:
                continue
            owner = self._slot_req[s] is req
            # a slot with a new owner normally drops this window's tokens
            # (the lane was cancelled/preempted) — unless the lane was
            # PRE-FREED: retired early because this very window provably
            # finishes it, in which case its tokens are the request's tail
            if not owner and not (
                prefreed and int(s) in prefreed
                and req.state is RequestState.RUNNING
            ):
                continue
            # the device can land more than the request's remaining budget in
            # one verify cycle; the cap truncation below keeps outputs exactly
            # what sequential decode would have produced
            n = min(int(n_take[s]), req.config.max_new_tokens - len(req.tokens))
            if n <= 0:
                continue
            if not req.tokens:
                self._ttft_hist.observe(now - req.submit_time)
                if req.trace is not None:
                    req.trace.mark_first_token(now)
                if req.request_class:
                    hist = self._class_ttft_hists.get(req.request_class)
                    if hist is None:
                        hist = self.metrics.histogram(
                            f"serve/ttft_s_class_{req.request_class}",
                            buckets=_LATENCY_BUCKETS,
                        )
                        self._class_ttft_hists[req.request_class] = hist
                    hist.observe(now - req.submit_time)
                self._tenant_ttft(req.tenant, now - req.submit_time)
            for t in toks[s, :n]:
                req.emit(int(t))
            if owner and self._draft_window is not None:
                # keep the draft context's tail == the lane's pending token
                # (the committed suffix ends with the next pending token)
                self._draft_window.push(int(s), toks[s, :n])
            self._bump("tokens_generated", n)
            self._bump_tenant(req.tenant, "tokens_generated", n)
            # a cycle lands n tokens on this lane at once: each is charged its
            # amortized share of the wall time since the lane's last arrival
            self._token_hist.observe(max(now - req.last_token_time, 0.0) / n, n)
            req.last_token_time = now
            hit_eos = bool(has_eos[s]) and n == int(n_take[s])
            if hit_eos or len(req.tokens) >= req.config.max_new_tokens:
                if owner:
                    self._free(s, req)
                else:
                    # pre-freed: the lane was already retired and the slot
                    # reassigned — only the request itself completes here
                    self._finish_request(int(s), req)
            elif owner:
                self._pending_tok[s] = int(toks[s, n - 1])

    # ------------------------------------------------------------------ drive
    def step(self) -> None:
        """One engine iteration: budgeted chunked-prefill admission, then one
        masked decode window over the pool.

        Fault containment: the first exception to escape the step body parks
        in ``_poisoned`` and re-raises — this engine never half-runs again
        until :meth:`revive`.  The router supervisor treats a poisoned
        replica as dead, exports its in-flight requests, and replays them on
        survivors (:meth:`export_inflight` / :meth:`adopt`)."""
        if self._poisoned is not None:
            raise self._poisoned
        try:
            self._step_impl()
        except Exception as exc:
            self._poisoned = exc
            self.recorder.record(
                "serve/engine_poisoned", error=repr(exc), step=self._step_count,
            )
            raise

    def _step_impl(self) -> None:
        if self._has_deadlines:
            self._shed_blown_deadlines()
        if (faults.ACTIVE is not None and self.paged and self._active.any()
                and faults.ACTIVE.fire("page_exhaustion")):
            # stand-in for the pool running dry: run the reclaim ladder's
            # last resort (preempt the youngest lane for front-of-queue
            # replay) exactly as _ensure_decode_capacity would under pressure.
            # Drain first, as the ladder's step 2 does: with the prior window
            # still in flight the victim could re-install into its old slot
            # before the drain, and the stale window's tokens would pass the
            # ownership check and land twice.
            if self._inflight is not None:
                self._drain_inflight()
            self._preempt()
        queue_depth = self.scheduler.queue_depth
        self._queue_gauge.set(queue_depth)
        self._prefree_exhausted()
        if self.role == "prefill":
            # disaggregated prefill replica: chunked prefill only.  Lanes
            # whose last chunk landed sit installed-but-undecoded until the
            # router hands them off to a decode replica (transfer.handoff);
            # dispatching a decode window here would both waste the step and
            # advance lanes the destination expects at their prefill
            # frontier.  No window means nothing to charge decode for.
            self._cycle_decode_tokens = 0
            self._admit()
            self._prev_handle = None
        elif self.interleave_prefill:
            # decode-interleaved chunked prefill: dispatch this cycle's
            # window FIRST, then admit — the chunk enqueues *behind* the
            # window, so decode lanes never skip a cycle while a long
            # prompt prefills, and the chunk runs under the host work of
            # draining the previous window
            # the previous window parks on the engine while admission runs:
            # any forced flush inside _admit (page-reclaim ladder) must land
            # it BEFORE the window just dispatched
            self._prev_handle = self._dispatch_decode()
            self._admit()
        else:
            # decode dispatches after admission: charge it nothing (the
            # counter still holds LAST cycle's width otherwise)
            self._cycle_decode_tokens = 0
            self._admit()
            self._prev_handle = self._dispatch_decode()
        tgt = (self._inflight if self._inflight is not None
               else self._prev_handle)
        if self._pending_prefill_qerr:
            # hand the chunk quant-error handles to a window that retires
            # no earlier than the chunks do — fetched at ITS drain
            if tgt is not None:
                tgt.prefill_qerrs.extend(self._pending_prefill_qerr)
                self._pending_prefill_qerr.clear()
        if self._pending_spills or self._pending_promotions:
            # same discipline for hierarchical-cache traffic: spill payloads
            # land, and promotions are acknowledged, at the drain of a window
            # that provably retires after them
            if tgt is not None:
                tgt.spills.extend(self._pending_spills)
                tgt.promotions.extend(self._pending_promotions)
            else:
                # no window in flight (idle engine / async_depth=0 gap):
                # nothing to hide the fetch behind, settle on the spot
                self._settle_spills(self._pending_spills)
                for rec in self._pending_promotions:
                    tr = rec.pop("trace", None)
                    if tr is not None and not tr.finished:
                        self._promote_wait_hist.observe(
                            tr.phase("promote_wait", bucket=rec["bucket"])
                        )
                    self.recorder.record("serve/promote_land", **rec)
            self._pending_spills = []
            self._pending_promotions = []
        prev, self._prev_handle = self._prev_handle, None
        if prev is not None:
            self._drain(prev)
        if self.prefix_cache is not None:
            covered = self.stats["prefix_hit_tokens"] + self.stats["prefix_miss_tokens"]
            if covered:
                hit = self.stats["prefix_hit_tokens"]
                host_hit = self.stats["prefix_hit_tokens_host"]
                self._hit_rate_gauge.set(hit / covered)
                self._hit_rate_device_gauge.set((hit - host_hit) / covered)
                self._hit_rate_host_gauge.set(host_hit / covered)
        self._update_prefill_gauges()
        if self.paged:
            self.kv.publish_gauges()
        self._step_count += 1
        # Progress heartbeat for the stall detector / /healthz; also the
        # ring's per-step record of what the pool looked like.
        self.recorder.heartbeat(
            "serve/step", step=self._step_count, queue=queue_depth,
            occupied=int(self._active.sum()),
        )

    @property
    def has_work(self) -> bool:
        # an in-flight window is work: its tokens haven't landed yet, so the
        # driver keeps stepping until the pipeline flushes (the trailing step
        # finds no active lane and drains)
        return (self.scheduler.has_queued or bool(self._active.any())
                or self._inflight is not None)

    def _update_tenant_kv_gauges(self) -> None:
        """Per-tenant KV occupancy gauges (``serve/kv_pages_tenant_<t>``):
        pages held by each tenant's active lanes in paged mode, lanes held in
        legacy slab mode.  Walks the slot array — metrics-tick cadence only,
        never the per-step hot path.  A tenant with no live lane reads 0
        (the gauge is not deleted: dashboards want the series to zero, not
        vanish)."""
        if not self._tenant_stats:
            return
        held: dict = {}
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if req is None or req.tenant is None:
                continue
            n = int(self.kv.lane_npages[s]) if self.paged else 1
            held[req.tenant] = held.get(req.tenant, 0) + n
        for tenant in self._tenant_stats:
            gauge = self._tenant_kv_gauges.get(tenant)
            if gauge is None:
                gauge = self._tenant_kv_gauges[tenant] = self.metrics.gauge(
                    f"serve/kv_pages_tenant_{tenant}"
                )
            gauge.set(held.get(tenant, 0))

    def _log_health(self, dt: float, d_tokens: int) -> None:
        """One-line serve-health summary (the ``metrics_interval`` heartbeat)."""
        queued = self.scheduler.queue_depth
        occupancy = float(self._active.mean()) if self.num_slots else 0.0
        p99_ms = self._token_hist.percentile(99) * 1e3
        logger.info(
            f"serve health: queue={queued} occupancy={occupancy:.2f} "
            f"tokens/s={d_tokens / dt if dt > 0 else 0.0:.1f} "
            f"token_p99={p99_ms:.2f}ms "
            f"completed={self.stats['requests_completed']}"
            f"/{self.stats['requests_submitted']}"
        )

    def run(
        self,
        max_steps: Optional[int] = None,
        metrics_interval: Optional[float] = None,
    ) -> None:
        """Drive :meth:`step` until every submitted request completes.

        ``metrics_interval`` (seconds) logs a one-line health summary — queue
        depth, slot occupancy, tokens/s, p99 token latency — at that cadence
        through :func:`~accelerate_tpu.logging.get_logger`.  Off by default.
        """
        steps = 0
        last_log = time.perf_counter()
        last_tokens = self.stats["tokens_generated"]
        while self.has_work:
            self.step()
            steps += 1
            if metrics_interval is not None:
                now = time.perf_counter()
                if now - last_log >= metrics_interval:
                    self._log_health(now - last_log,
                                     self.stats["tokens_generated"] - last_tokens)
                    # the fleet-health layer rides the same tick: refresh the
                    # per-tenant KV gauges, then sample/evaluate the SLO
                    # engine if one is installed (a no-op branch otherwise)
                    self._update_tenant_kv_gauges()
                    slo_tick()
                    last_log = now
                    last_tokens = self.stats["tokens_generated"]
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} steps")

    def serve(
        self,
        prompts: Sequence,
        configs=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        metrics_interval: Optional[float] = None,
    ) -> List[Request]:
        """Convenience: submit every prompt (``configs`` is one shared or a
        per-request list of ``GenerationConfig``), run to completion, return
        the requests in submission order.  ``metrics_interval`` is forwarded
        to :meth:`run` (periodic health logging; off by default)."""
        reqs = []
        for i, p in enumerate(prompts):
            cfg = configs[i] if isinstance(configs, (list, tuple)) else configs
            reqs.append(self.submit(p, config=cfg, on_token=on_token))
        self.run(metrics_interval=metrics_interval)
        return reqs

    # ------------------------------------------------------------------ stats
    def mean_slot_occupancy(self) -> float:
        """Occupied lane-steps / total lane-steps across decode windows."""
        total = self.stats["decode_steps"] * self.num_slots
        return self.stats["occupied_lane_steps"] / total if total else 0.0

    def prefix_cache_stats(self) -> dict:
        """Prefix-cache health: residency + hit/miss token counts (zeros when
        the cache is disabled)."""
        out = {"prefix_hit_tokens": self.stats["prefix_hit_tokens"],
               "prefix_miss_tokens": self.stats["prefix_miss_tokens"]}
        covered = out["prefix_hit_tokens"] + out["prefix_miss_tokens"]
        out["hit_rate"] = out["prefix_hit_tokens"] / covered if covered else 0.0
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.stats())
        return out

    def analyze_costs(self) -> dict:
        """XLA cost/memory analysis over every executable the pool has run
        (decode window, hit prefill/copy buckets, insert) and publish the
        ``serve/decode_flops_per_token`` / ``serve/hbm_peak_bytes`` gauges.

        Best-effort and idempotent — re-lowers from recorded abstract
        signatures, so call it off the serve loop (benches do; the debug
        server runs it as a scrape collector).  Returns the cost-table
        snapshot."""
        snap = self.cost_table.analyze_all()
        decode_flops = self.cost_table.flops("serve/decode_window")
        if decode_flops:
            self._decode_flops_gauge.set(
                decode_flops / (self.window * self.num_slots)
            )
        hbm = self.cost_table.max_hbm_peak_bytes()
        if hbm:
            # per-device: XLA's analysis sees logical (whole-array) shapes;
            # under tp the KV pool and weights split evenly across the axis
            self._hbm_gauge.set(hbm / self.tp_degree)
        return snap

    def kv_pool_bytes(self) -> int:
        """PER-DEVICE HBM the KV state occupies: the page pool (paged — the
        knob ``num_pages`` sizes), or the slab pool plus the prefill scratch
        (legacy).  Under a tp mesh the pool shards on the kv-head axis, so
        each device holds exactly ``1 / tp_degree`` of the logical bytes —
        the like-for-like number capacity benches compare.  The A/B bench
        holds this equal across both arms."""
        if self.paged:
            return self.kv.kv_bytes_per_device()
        return (int(self.pool.k.nbytes) + int(self.pool.v.nbytes)
                + int(self.scratch.k.nbytes)
                + int(self.scratch.v.nbytes)) // self.tp_degree

    def compiled_executable_counts(self) -> dict:
        """Per-executable jit-cache sizes — the no-retrace contract: after any
        workload each entry is at most 1 (copy entries exist only while the
        prefix cache is enabled and stay 0 until the first hit; the
        verify_window entry exists only when ``speculate_k > 0`` and stays 0
        until the first drafted cycle; tree speculation swaps it for exactly
        two entries, ``tree_verify_window`` and ``draft_forward``).  Paged mode swaps insert and the
        per-bucket copies for a single ``copy_page`` (0 until the first
        copy-on-write); cache hits alias pages, so the hit path adds no
        executable at all.  ``lane_install`` is the one-slot lane-vector
        scatter admissions enqueue once the device mirror exists — 0 when
        every install landed before the first window.  The host spill tier
        (``prefix_host_mb > 0``) adds exactly one ``spill_<bucket>`` D2H
        gather and one ``promote_<bucket>`` H2D scatter-install per prefill
        bucket — the documented, bounded growth of the compiled budget; each
        stays 0 until the first spill/promotion of that bucket.  Live lane
        migration adds exactly one ``migrate_extract`` D2H/D2D gather and one
        ``migrate_install`` donated scatter at full ``pages_per_lane`` width
        (page-id padding keeps the signature fixed) — built lazily by
        ``serving.transfer.migration_executables``, so engines that never
        participate in a migration gain neither entry."""
        out = {"decode_window": jit_cache_sizes(self._decode),
               "lane_install": jit_cache_sizes(self._lane_install)}
        if self.paged:
            out["copy_page"] = jit_cache_sizes(self._copy_page)
        else:
            out["insert"] = jit_cache_sizes(self._insert)
        if self._verify is not None:
            out["tree_verify_window" if self.tree is not None
                else "verify_window"] = jit_cache_sizes(self._verify)
        if self._draft_fwd is not None:
            out["draft_forward"] = jit_cache_sizes(self._draft_fwd)
        for b, f in self._prefill.items():
            out[f"prefill_{b}"] = jit_cache_sizes(f)
        for b, f in self._copy.items():
            out[f"copy_{b}"] = jit_cache_sizes(f)
        for b, f in self._spill_extract.items():
            out[f"spill_{b}"] = jit_cache_sizes(f)
        for b, f in self._promote_install.items():
            out[f"promote_{b}"] = jit_cache_sizes(f)
        if self._migrate_extract is not None:
            out["migrate_extract"] = jit_cache_sizes(self._migrate_extract)
        if self._migrate_install is not None:
            out["migrate_install"] = jit_cache_sizes(self._migrate_install)
        return out

"""Continuous-batching serving engine over the slot-based KV pool.

The static ``generate`` path is one whole-batch program: every request starts
together and runs exactly ``max_new_tokens`` steps, so at mixed request
lengths the batch's tokens/s collapses to the longest request's schedule.
:class:`ServingEngine` instead runs iteration-level scheduling (Orca-style)
against a fixed set of compiled executables (:mod:`.pool`):

1. a request queue admits FCFS into freed slots, prefilling chunked under a
   per-step token budget (:mod:`.scheduler`);
2. a masked decode window advances every occupied slot; EOS or the length cap
   frees a slot the same step it fires;
3. freed slots are reused by queued requests without disturbing running lanes.

Everything dynamic lives on the host; the device only ever sees
``1 + len(prefill_buckets) + 1`` shapes (decode window, per-bucket prefill,
insert).  See ``docs/usage/serving.md``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generation import GenerationConfig
from ..models.transformer import KVCache, Transformer
from .pool import jit_cache_sizes, make_decode_window, make_insert, make_prefill_chunk
from .scheduler import Request, RequestState, Scheduler


class ServingEngine:
    """Serve many requests through one slot pool with in-flight admission.

    Parameters
    ----------
    model, params: the flagship ``Transformer`` and its (HBM-resident) params.
    num_slots: concurrent request lanes in the KV pool.
    max_len: per-slot KV capacity (default ``config.max_seq_len``).  A request
        needs ``prompt_len + max_new_tokens + decode_window <= max_len``.
    prefill_buckets: fixed chunk sizes for chunked prefill — one compiled
        prefill shape per bucket.  Defaults to ``(128, 512)`` clipped to
        ``max_prompt_len``.
    max_prompt_len: scratch-cache capacity (longest admissible prompt);
        defaults to ``max_len``.
    prefill_token_budget: max prefill tokens charged per engine step (bounds
        decode-latency jitter while prompts stream in); default: the largest
        bucket.
    decode_window: decode steps fused per engine step (one ``lax.scan``
        executable).  Larger windows amortize host round-trips; a request
        finishing mid-window wastes at most ``window - 1`` masked lane-steps.
    slot_order: optional slot-id preference for admission (tests permute this
        to pin down lane independence).
    """

    def __init__(
        self,
        model: Transformer,
        params: Any,
        num_slots: int = 4,
        max_len: Optional[int] = None,
        prefill_buckets: Optional[Sequence[int]] = None,
        max_prompt_len: Optional[int] = None,
        prefill_token_budget: Optional[int] = None,
        decode_window: int = 4,
        pad_token_id: int = 0,
        rng_seed: int = 0,
        slot_order: Optional[Sequence[int]] = None,
    ):
        cfg = model.config
        self.model = model
        self.params = params
        self.config = cfg
        self.num_slots = int(num_slots)
        self.max_len = int(max_len if max_len is not None else cfg.max_seq_len)
        self.max_prompt_len = int(
            max_prompt_len if max_prompt_len is not None else self.max_len
        )
        if self.max_prompt_len > self.max_len:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} > slot capacity {self.max_len}"
            )
        if prefill_buckets is None:
            prefill_buckets = [b for b in (128, 512) if b <= self.max_prompt_len]
            if not prefill_buckets:
                prefill_buckets = [self.max_prompt_len]
        self.buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
        if self.buckets[-1] > self.max_prompt_len:
            raise ValueError(
                f"largest prefill bucket {self.buckets[-1]} exceeds "
                f"max_prompt_len {self.max_prompt_len}"
            )
        self.window = int(decode_window)
        self.pad_token_id = int(pad_token_id)
        if slot_order is None:
            slot_order = range(self.num_slots)
        self.slot_order = tuple(int(s) for s in slot_order)
        if sorted(self.slot_order) != list(range(self.num_slots)):
            raise ValueError(
                f"slot_order must permute range({self.num_slots}), got {self.slot_order}"
            )

        # device state: the pool (per-lane index) + the batch-1 prefill scratch
        self.pool = KVCache.create(cfg, self.num_slots, self.max_len, per_lane_index=True)
        self.scratch = KVCache.create(cfg, 1, self.max_prompt_len)
        self._decode = make_decode_window(model, self.window)
        self._prefill = {b: make_prefill_chunk(model, b) for b in self.buckets}
        self._insert = make_insert()

        self.scheduler = Scheduler(
            self.buckets,
            prefill_token_budget if prefill_token_budget is not None else self.buckets[-1],
        )

        n = self.num_slots
        # host-side per-slot lane state, shipped to the decode window each step
        self._slot_req: List[Optional[Request]] = [None] * n
        self._slot_ever_used = np.zeros(n, bool)
        self._pending_tok = np.zeros(n, np.int32)
        self._active = np.zeros(n, bool)
        self._eos = np.full(n, -1, np.int32)
        self._do_sample = np.zeros(n, bool)
        self._temperature = np.ones(n, np.float32)
        self._top_k = np.zeros(n, np.int32)
        self._top_p = np.ones(n, np.float32)
        self._rngs = np.zeros((n, 2), np.uint32)
        self._base_rng = jax.random.PRNGKey(rng_seed)
        self._reserved_slot: Optional[int] = None

        self._next_rid = 0
        self._step_count = 0
        self.stats = {
            "requests_submitted": 0,
            "requests_completed": 0,
            "tokens_generated": 0,
            "prefill_chunks": 0,
            "prefill_tokens": 0,
            "decode_steps": 0,
            "occupied_lane_steps": 0,
            "slots_reused": 0,
        }

    # ------------------------------------------------------------- submission
    def submit(
        self,
        prompt,
        config: Optional[GenerationConfig] = None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        **overrides: Any,
    ) -> Request:
        """Queue one request; returns its :class:`Request` handle (filled in
        as the engine runs).  ``overrides`` patch the ``GenerationConfig``
        exactly like :func:`~accelerate_tpu.models.generation.generate`."""
        gen = config or GenerationConfig()
        if overrides:
            gen = dataclasses.replace(gen, **overrides)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.size} > max_prompt_len {self.max_prompt_len}"
            )
        need = prompt.size + gen.max_new_tokens + self.window
        if need > self.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {gen.max_new_tokens} + "
                f"decode_window {self.window} = {need} exceeds slot capacity "
                f"{self.max_len}"
            )
        req = Request(rid=self._next_rid, prompt=prompt, config=gen, on_token=on_token,
                      submit_step=self._step_count)
        self._next_rid += 1
        self.scheduler.submit(req)
        self.stats["requests_submitted"] += 1
        return req

    # -------------------------------------------------------------- admission
    def _next_free_slot(self) -> Optional[int]:
        for s in self.slot_order:
            if not self._active[s] and self._slot_req[s] is None and s != self._reserved_slot:
                return s
        return None

    def _admit(self) -> None:
        budget = self.scheduler.begin_step()
        while True:
            if self.scheduler.prefilling is None:
                slot = self._next_free_slot()
                if slot is None or not self.scheduler.queue:
                    return
                self.scheduler.start_next(slot)
                self._reserved_slot = slot
                # scratch restarts at position 0; stale KV beyond each new
                # write is unreachable (causal mask == valid-entry mask)
                self.scratch = self.scratch.replace(index=jnp.zeros((), jnp.int32))
            took = self.scheduler.take_chunk(budget)
            if took is None:
                return
            req, bucket, valid, start = took
            chunk = np.zeros(bucket, np.int32)
            chunk[:valid] = req.prompt[start:start + valid]
            self.scratch = self._prefill[bucket](self.params, chunk[None], self.scratch)
            budget -= bucket
            self.stats["prefill_chunks"] += 1
            self.stats["prefill_tokens"] += valid
            done = self.scheduler.finish_prefill()
            if done is not None:
                self._install(done)

    def _install(self, req: Request) -> None:
        """Insert a fully prefilled request into its reserved slot: one
        ``dynamic_update_slice`` into the pool + host lane-state updates."""
        s = req.slot
        plen = len(req.prompt)
        self.pool = self._insert(
            self.pool, self.scratch.k, self.scratch.v,
            jnp.int32(s), jnp.int32(plen - 1),
        )
        gen = req.config
        self._pending_tok[s] = req.prompt[-1]
        self._active[s] = True
        self._eos[s] = -1 if gen.eos_token_id is None else gen.eos_token_id
        self._do_sample[s] = gen.do_sample
        self._temperature[s] = gen.temperature
        self._top_k[s] = 0 if gen.top_k is None else gen.top_k
        self._top_p[s] = 1.0 if gen.top_p is None else gen.top_p
        self._rngs[s] = np.asarray(jax.random.fold_in(self._base_rng, req.rid))
        if self._slot_ever_used[s]:
            self.stats["slots_reused"] += 1
        self._slot_ever_used[s] = True
        self._slot_req[s] = req
        self._reserved_slot = None
        req.state = RequestState.RUNNING

    # ----------------------------------------------------------------- decode
    def _free(self, slot: int, req: Request) -> None:
        self._active[slot] = False
        self._slot_req[slot] = None
        req.state = RequestState.DONE
        req.finish_step = self._step_count
        self.stats["requests_completed"] += 1

    def _decode_window(self) -> None:
        if not self._active.any():
            return
        n_occupied = int(self._active.sum())
        self.pool, toks, rngs = self._decode(
            self.params, self.pool,
            jnp.asarray(self._pending_tok), jnp.asarray(self._active),
            jnp.asarray(self._eos), jnp.asarray(self._do_sample),
            jnp.asarray(self._temperature), jnp.asarray(self._top_k),
            jnp.asarray(self._top_p),
            jnp.full((self.num_slots,), self.pad_token_id, jnp.int32),
            jnp.asarray(self._rngs),
        )
        toks = np.asarray(jax.device_get(toks))
        # copy: device_get hands back read-only buffers, but _install writes
        # per-slot keys into this array on admission
        self._rngs = np.array(jax.device_get(rngs), np.uint32)
        self.stats["decode_steps"] += self.window
        self.stats["occupied_lane_steps"] += n_occupied * self.window
        for k in range(self.window):
            for s in range(self.num_slots):
                req = self._slot_req[s]
                if req is None or not self._active[s]:
                    continue
                tok = int(toks[s, k])
                finishing = req.finished(tok)
                req.emit(tok)
                self.stats["tokens_generated"] += 1
                if finishing:
                    self._free(s, req)
                else:
                    self._pending_tok[s] = tok

    # ------------------------------------------------------------------ drive
    def step(self) -> None:
        """One engine iteration: budgeted chunked-prefill admission, then one
        masked decode window over the pool."""
        self._admit()
        self._decode_window()
        self._step_count += 1

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_queued or bool(self._active.any())

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive :meth:`step` until every submitted request completes."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} steps")

    def serve(
        self,
        prompts: Sequence,
        configs=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
    ) -> List[Request]:
        """Convenience: submit every prompt (``configs`` is one shared or a
        per-request list of ``GenerationConfig``), run to completion, return
        the requests in submission order."""
        reqs = []
        for i, p in enumerate(prompts):
            cfg = configs[i] if isinstance(configs, (list, tuple)) else configs
            reqs.append(self.submit(p, config=cfg, on_token=on_token))
        self.run()
        return reqs

    # ------------------------------------------------------------------ stats
    def mean_slot_occupancy(self) -> float:
        """Occupied lane-steps / total lane-steps across decode windows."""
        total = self.stats["decode_steps"] * self.num_slots
        return self.stats["occupied_lane_steps"] / total if total else 0.0

    def compiled_executable_counts(self) -> dict:
        """Per-executable jit-cache sizes — the no-retrace contract: after any
        workload each entry is at most 1."""
        out = {"decode_window": jit_cache_sizes(self._decode),
               "insert": jit_cache_sizes(self._insert)}
        for b, f in self._prefill.items():
            out[f"prefill_{b}"] = jit_cache_sizes(f)
        return out

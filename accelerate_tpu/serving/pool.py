"""Slot-based KV pool: the fixed-shape compiled executables behind the engine.

Iteration-level scheduling (Orca) and block-structured KV management (vLLM)
win their 2-10x serving throughput by decoupling request lifetimes from the
batch program: a request that finishes frees its KV capacity *immediately* and
a queued request takes its place without restarting anyone else.  The TPU-first
translation keeps everything inside a handful of fixed-shape executables — no
per-request retracing:

* **pool** — one :class:`~accelerate_tpu.models.transformer.KVCache` of
  ``num_slots`` lanes with a *per-lane* ``index`` vector (each slot sits at its
  own sequence position).  The model's cache path writes each lane at its own
  index and masks attention per lane, so a single batched forward serves
  whatever mix of requests currently occupies the pool.
* **decode window** (:func:`make_decode_window`) — ONE jitted executable:
  ``lax.scan`` over ``window`` masked decode steps.  Per-request sampling
  knobs (eos / temperature / top-k / top-p) enter as traced *vectors*, so a
  new request never forces a retrace.  Inactive or EOS-done lanes are frozen:
  their index stops advancing and their emissions are masked to the pad token.
  Greedy lanes take the same argmax ``generate`` takes — token-exact.
* **prefill chunks** (:func:`make_prefill_chunk`) — one executable per chunk
  *bucket* (e.g. 128/512).  A prompt prefills into a batch-1 scratch cache in
  fixed-size chunks; only the final chunk is padded, and padded positions are
  never attended (the causal mask is the valid-entry mask).
* **insert** (:func:`make_insert`) — one executable: ``dynamic_update_slice``
  of the scratch KV into a freed slot + setting that lane's length, without
  disturbing running lanes.
* **copy chunk** (:func:`make_copy_chunk`) — one executable per chunk bucket:
  ``dynamic_update_slice`` of a cached prefix-KV slab (:mod:`.prefix_cache`)
  into the scratch cache at its index — a cache hit replays retained KV
  instead of re-running the prefill forward.
* **verify window** (:func:`make_verify_window`) — one executable per
  configured ``speculate_k``: a single forward over ``[slots, K+1]`` drafted
  positions (pending token + K host-drafted tokens, :mod:`.spec`), the
  token-exact acceptance prefix per lane, and an index rollback past the
  first rejected draft.  Lands a variable 1..K+1 tokens per lane per call
  while preserving exactly the tokens sequential decode would emit.

Compiled-shape budget for an engine instance: ``1 (decode window) +
len(prefill_buckets) + 1 (insert)``, plus ``len(prefill_buckets)`` copy
executables when the prefix cache is enabled, plus ``1`` verify executable
when ``speculate_k > 0`` — asserted by the serving tests via the jit cache
counters.  Model-based tree speculation (``draft_model=``) swaps the verify
executable for exactly two: ``1`` tree verify window
(:func:`make_tree_verify_window` — the ``[slots, tree_nodes]`` bucket is
static per engine, never call-varying) and ``1`` draft forward
(:func:`~accelerate_tpu.serving.spec_exec.make_draft_forward`).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..models.generation import sample_tokens_batched
from ..models.transformer import KVCache, PagedKVCache, Transformer
from ..parallel.mesh import mesh_axis_size
from ..utils.jax_compat import jit_cache_size
from .paging import NULL_PAGE


class ServeShardings:
    """The engine's placement vocabulary under a tensor-parallel mesh.

    Every serving executable moves arrays from exactly three families: KV
    slabs/pools ``[L, *, *, Hkv, D]`` (sharded on the kv-head axis — dim 3 in
    both the slab ``[L, N, max_len, H, D]`` and page ``[L, NP, page, H, D]``
    layouts), per-page quantization scales ``[L, NP, Hkv]`` (head axis last),
    and host-side control state (tokens, tables, indices, sampling knobs —
    replicated).  Params carry the :data:`~accelerate_tpu.parallel
    .tensor_parallel.DEFAULT_TP_RULES` placement computed by the engine.

    Factories take ``shardings=None`` (single-chip, plain ``jax.jit``) or an
    instance of this class, in which case every executable compiles with
    explicit in/out shardings — donated KV buffers alias in place per shard,
    and atpu-lint's ``sharding-annotations`` rule pins the discipline.
    """

    def __init__(self, mesh, params, tp_axis: str = "tp"):
        self.mesh = mesh
        self.tp_axis = tp_axis
        self.tp_degree = mesh_axis_size(mesh, tp_axis)
        ax = tp_axis if self.tp_degree > 1 else None
        self.replicated = NamedSharding(mesh, PartitionSpec())
        self.kv = NamedSharding(mesh, PartitionSpec(None, None, None, ax, None))
        self.scales = NamedSharding(mesh, PartitionSpec(None, None, ax))
        self.params = params

    def rep(self, n: int) -> tuple:
        """``n`` replicated placements — the control-state tail of a signature."""
        return (self.replicated,) * n

    def cache(self) -> KVCache:
        """Placement pytree for a slab :class:`KVCache` (scratch or pool)."""
        return KVCache(k=self.kv, v=self.kv, index=self.replicated)


def _serve_jit(fn, *, donate_argnums=(), in_shardings=None, out_shardings=None):
    """``jax.jit`` with optional explicit shardings.  ``None`` shardings mean
    single-chip: compile without placement constraints (committed inputs keep
    their devices, exactly the pre-mesh behavior)."""
    if in_shardings is None and out_shardings is None:
        return jax.jit(fn, donate_argnums=donate_argnums)  # noqa: sharding-annotations (single-chip)
    return jax.jit(
        fn,
        donate_argnums=donate_argnums,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
    )


def audit_donation(*trees) -> None:
    """Assert no leaf of ``trees`` has already been donated (its buffer
    deleted by a prior dispatch).  The engine calls this on the KV state it
    is about to donate into a window: under the pipelined loop
    (``async_depth=1``) every window's outputs rebind ``self.pool`` / the
    page arrays *at dispatch*, so the next dispatch always donates the fresh
    handles — this audit turns any future violation of that invariant (a
    double donation, which XLA reports as a use-after-free much later and
    far from the cause) into an immediate, attributable error.  Host-only
    and O(leaves): no device sync."""
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            deleted = getattr(leaf, "is_deleted", None)
            if deleted is not None and deleted():
                raise RuntimeError(
                    "KV buffer was already donated to an earlier dispatch "
                    "(use-after-donation): a window's outputs must be rebound "
                    "before the next window dispatches"
                )


def _decode_scan(model: Transformer, window: int, params, cache, tokens, active,
                 eos, do_sample, temperature, top_k, top_p, pad, rngs):
    """The masked decode scan shared by the slab and paged decode windows —
    one traced program, so the paged path cannot drift from the legacy
    numerics.  Returns ``(cache, out_tokens [N, window], pending, rngs)``."""

    def step(carry, _):
        cache, tok, done, rngs = carry
        prev_index = cache.index
        if isinstance(cache, PagedKVCache):
            # direct paged cache: route frozen lanes' writes to the null page
            # per step.  In the slab (and gathered-view) paths a frozen lane
            # harmlessly overwrites its own dead slot, but a quantized page
            # write REQUANTIZES the whole touched page — pad-token garbage
            # must not keep churning a page that still holds real history.
            cache = cache.replace(active=~done)
        logits, cache = model.apply({"params": params}, tok[:, None], cache=cache)
        # model.apply advanced every lane; frozen lanes roll back
        cache = cache.replace(
            index=jnp.where(done, prev_index, prev_index + 1)
        )
        split = jax.vmap(lambda r: jax.random.split(r, 2))(rngs)
        nxt = sample_tokens_batched(
            logits[:, -1], split[:, 0],
            do_sample=do_sample, temperature=temperature,
            top_k=top_k, top_p=top_p,
        )
        nxt = jnp.where(done, pad, nxt)
        done = done | ((eos >= 0) & (nxt == eos))
        return (cache, nxt, done, split[:, 1]), nxt

    done0 = ~active
    (cache, tok, _, rngs), toks = jax.lax.scan(
        step, (cache, tokens, done0, rngs), None, length=window
    )
    return cache, toks.T, tok, rngs


def make_decode_window(model: Transformer, window: int,
                       shardings: Optional[ServeShardings] = None):
    """One jitted ``window``-step masked decode over the whole slot pool.

    ``(params, cache, tokens [N], active [N], eos [N], do_sample [N],
    temperature [N], top_k [N], top_p [N], pad [N], rngs [N,2])
    -> (cache, out_tokens [N, window], new_pending [N], new_rngs)``

    ``new_pending`` is the scan's final carry token per lane — the token the
    next window will feed — returned device-side so the engine's lane-state
    mirrors never round-trip through the host between windows.

    Return packing is readback-friendly by design: ``out_tokens`` is its own
    output leaf (never folded into the carried cache/lane state), so the
    pipelined engine can park just that handle in a :class:`.readback.Readback`
    and dispatch the next window — which donates and rebinds the cache —
    without the deferred token fetch ever touching a donated buffer.  All
    outputs of one call materialize together, so fetching ``out_tokens``
    also proves the window's KV writes landed.

    Semantics per scan step (matching ``generate``'s loop body lane-by-lane):
    the pending token is fed at each lane's own position, its KV is written
    there, the next token is sampled per-lane, and lanes that are inactive or
    have emitted their EOS freeze — index stops advancing and outputs are
    masked to ``pad``.  Frozen lanes still execute (static shapes) but only
    ever overwrite their own dead slot, so running lanes are untouched.
    """

    def decode_window(params, cache, tokens, active, eos, do_sample, temperature,
                      top_k, top_p, pad, rngs):
        return _decode_scan(model, window, params, cache, tokens, active, eos,
                            do_sample, temperature, top_k, top_p, pad, rngs)

    s = shardings
    return _serve_jit(
        decode_window,
        donate_argnums=(1,),
        in_shardings=None if s is None else (s.params, s.cache(), *s.rep(9)),
        out_shardings=None if s is None else (s.cache(), *s.rep(3)),
    )


def make_verify_window(model: Transformer, k: int,
                       shardings: Optional[ServeShardings] = None):
    """One jitted speculative verify pass: K+1 positions per lane, one forward.

    ``(params, cache, tokens [N, K+1], active [N], eos [N], do_sample [N],
    temperature [N], top_k [N], top_p [N], pad [N], rngs [N,2])
    -> (cache, out [N, K+1], n_commit [N], new_pending [N], new_rngs)``

    ``tokens[:, 0]`` is each lane's pending token, ``tokens[:, 1:]`` its K
    host-drafted tokens (:mod:`.spec`).  The single forward writes KV for all
    K+1 positions at each lane's own index and yields the true next-token
    logits at every position; logits at position ``i`` are trustworthy iff
    drafts ``1..i`` were all correct — exactly the prefix the acceptance rule
    commits, so speculation never changes what gets emitted:

    * **greedy lanes** — the committed token at each position is the argmax,
      bitwise the same decision the decode window takes; a draft is accepted
      while it equals that argmax (longest exact match).  Token-exact by
      construction.
    * **sampled lanes** — the Leviathan accept/resample rule specialized to a
      deterministic (point-mass) drafter: draft ``d`` at position ``i`` is
      accepted with probability ``p_i(d)`` under the *filtered* per-lane
      distribution (same temperature/top-k/top-p pipeline as
      :func:`~accelerate_tpu.models.generation.sample_tokens_batched`); on
      rejection the committed token is resampled from ``p_i`` with ``d``
      removed (the renormalized residual ``max(p - q, 0)``), which preserves
      the output distribution exactly.  One bonus token is sampled at the
      final position when every draft is accepted.

    Committed tokens stop at the first emitted EOS; positions past the commit
    point emit ``pad``.  The cache index rolls back to
    ``prev_index + n_commit`` — KV for the pending token and accepted drafts
    stays (it was computed from correct inputs), KV past the first rejection
    is unreachable and gets overwritten by subsequent decode.  Frozen lanes
    (``~active``) commit nothing and keep their index.
    """
    def verify_window(params, cache, tokens, active, eos, do_sample,
                      temperature, top_k, top_p, pad, rngs):
        return _verify_body(model, k, params, cache, tokens, active, eos,
                            do_sample, temperature, top_k, top_p, pad, rngs)

    s = shardings
    return _serve_jit(
        verify_window,
        donate_argnums=(1,),
        in_shardings=None if s is None else (s.params, s.cache(), *s.rep(9)),
        out_shardings=None if s is None else (s.cache(), *s.rep(4)),
    )


def _verify_body(model: Transformer, k: int, params, cache, tokens, active, eos,
                 do_sample, temperature, top_k, top_p, pad, rngs):
    """Forward + accept/commit of one speculative verify pass — shared by the
    slab and paged verify windows (one traced program, no numeric drift)."""
    from ..models.generation import filter_logits_batched

    kp1 = k + 1
    n = tokens.shape[0]
    prev_index = cache.index
    logits, cache = model.apply({"params": params}, tokens, cache=cache)
    logits = logits.astype(jnp.float32)                  # [N, K+1, V]
    vocab = logits.shape[-1]
    drafts = tokens[:, 1:]                               # [N, K]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    use_sample = do_sample & (temperature > 0.0)
    split = jax.vmap(lambda r: jax.random.split(r, 2))(rngs)
    draw_rngs, new_rngs = split[:, 0], split[:, 1]

    def _greedy(_):
        return greedy, greedy[:, :k] == drafts

    def _sampled(_):
        rep = lambda x: jnp.repeat(x, kp1, axis=0)
        filt = filter_logits_batched(
            logits.reshape(n * kp1, vocab),
            temperature=rep(temperature), top_k=rep(top_k), top_p=rep(top_p),
        ).reshape(n, kp1, vocab)
        probs = jax.nn.softmax(filt, axis=-1)
        # per lane: K accept draws + K residual resamples + 1 bonus draw
        keys = jax.vmap(lambda r: jax.random.split(r, 2 * k + 1))(draw_rngs)
        u = jax.vmap(lambda ks: jax.vmap(jax.random.uniform)(ks))(keys[:, :k])
        p_draft = jnp.take_along_axis(
            probs[:, :k], drafts[..., None], axis=-1
        )[..., 0]
        accepted = u < p_draft                           # [N, K]
        neg_inf = jnp.finfo(jnp.float32).min
        residual = jnp.where(                            # p with the draft removed
            jax.nn.one_hot(drafts, vocab, dtype=bool), neg_inf, filt[:, :k]
        )
        res = jax.vmap(jax.vmap(jax.random.categorical))(
            keys[:, k:2 * k], residual
        ).astype(jnp.int32)
        bonus = jax.vmap(jax.random.categorical)(
            keys[:, 2 * k], filt[:, k]
        ).astype(jnp.int32)
        emit = jnp.concatenate(
            [jnp.where(accepted, drafts, res), bonus[:, None]], axis=1
        )
        emit = jnp.where(use_sample[:, None], emit, greedy)
        acc = jnp.where(use_sample[:, None], accepted, greedy[:, :k] == drafts)
        return emit, acc

    # all-greedy pools (the common serving mix) skip the full-vocab
    # filtering/sampling machinery at runtime, mirroring sample_tokens_batched
    emit, acc = jax.lax.cond(jnp.any(use_sample), _sampled, _greedy, None)
    n_accept = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)
    pos = jnp.arange(kp1)[None, :]
    committable = pos <= n_accept[:, None]
    is_eos = (emit == eos[:, None]) & (eos >= 0)[:, None]
    eos_before = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos) > 0
    commit = committable & ~eos_before & active[:, None]
    n_commit = commit.sum(axis=1).astype(jnp.int32)
    out = jnp.where(commit, emit, pad[:, None])
    # model.apply advanced every lane by K+1; roll back past rejections
    # (and fully, for frozen lanes — their garbage writes are unreachable)
    cache = cache.replace(index=prev_index + n_commit)
    last = jnp.maximum(n_commit - 1, 0)
    new_pending = jnp.take_along_axis(out, last[:, None], axis=1)[:, 0]
    return cache, out, n_commit, new_pending, new_rngs


def make_tree_verify_window(model: Transformer, tree,
                            shardings: Optional[ServeShardings] = None):
    """One jitted *tree* speculative verify pass: ``S = tree.nodes`` drafted
    tree positions per lane, one forward — the generalization of
    :func:`make_verify_window` from a linear ``[slots, K+1]`` window to a
    token tree ``[slots, S]``.

    ``(params, cache, tokens [N, S], active [N], eos [N], do_sample [N],
    temperature [N], top_k [N], top_p [N], pad [N], rngs [N, 2])
    -> (cache, out [N, D+1], n_commit [N], new_pending [N], new_rngs)``

    ``tree`` is a :class:`~accelerate_tpu.serving.spec_exec.TreeSpec`:
    ``tokens[:, 0]`` is each lane's pending token (tree root), node ``i``'s
    draft token at ``tokens[:, i]`` extends its parent's branch
    (:meth:`TreeSpec` chains topology — ``width`` sibling branches of
    ``depth`` model-drafted tokens).  The single forward writes all ``S``
    nodes' KV contiguously at each lane's frontier, attends under the
    ancestor mask (``tree_mask`` through the model), and the acceptance rule
    selects ONE root-to-leaf path to commit:

    * **greedy lanes** — the branch with the longest exact prefix match
      against the model's argmax chain wins (ties: lowest branch id); the
      committed tokens are the argmaxes along that path, bitwise the tokens
      sequential greedy decode would emit.
    * **sampled lanes** — multi-try speculative sampling at the branch point
      (each sibling candidate is tried against the running residual
      distribution — exact for the point-mass drafts a draft model emits),
      then the linear Leviathan accept/residual-resample down the chosen
      branch; one bonus token at the deepest path node.  Output distribution
      preserved exactly.

    After acceptance the winning path's KV rows are *compacted* to the lane
    frontier (losing branches' rows are overwritten or left dead past the
    rolled-back index) and the index advances by ``n_commit`` — so the cache
    layout a subsequent window sees is byte-for-byte what linear decode would
    have produced.
    """
    def tree_verify_window(params, cache, tokens, active, eos, do_sample,
                           temperature, top_k, top_p, pad, rngs):
        return _tree_verify_body(model, tree, params, cache, tokens, active,
                                 eos, do_sample, temperature, top_k, top_p,
                                 pad, rngs)

    s = shardings
    return _serve_jit(
        tree_verify_window,
        donate_argnums=(1,),
        in_shardings=None if s is None else (s.params, s.cache(), *s.rep(9)),
        out_shardings=None if s is None else (s.cache(), *s.rep(4)),
    )


def _tree_verify_body(model: Transformer, tree, params, cache, tokens, active,
                      eos, do_sample, temperature, top_k, top_p, pad, rngs):
    """Forward + branch-select/commit of one tree verify pass — shared by the
    slab, gathered-paged and direct-paged tree windows (one traced accept
    program, no numeric drift between pool layouts)."""
    from ..models.generation import filter_logits_batched

    w, depth = tree.width, tree.depth
    s_nodes = tree.nodes
    dp1 = depth + 1
    n = tokens.shape[0]
    prev_index = cache.index
    paths_j = jnp.asarray(tree.paths, jnp.int32)         # [W, D+1]
    # node i sits at sequence position frontier + depth(i); positions must be
    # explicit — consecutive-slot defaults would misplace sibling branches
    positions = prev_index[:, None] + jnp.asarray(tree.depth_arr, jnp.int32)[None, :]
    logits, cache = model.apply(
        {"params": params}, tokens, positions=positions, cache=cache,
        tree_mask=tree.anc,
    )
    logits = logits.astype(jnp.float32)                  # [N, S, V]
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # ok[i]: node i's draft token equals the model's argmax at its parent —
    # the tree analog of ``greedy[:, :k] == drafts``
    ok = tokens == jnp.take(greedy, jnp.asarray(tree.parent, jnp.int32), axis=1)
    chain = jnp.asarray(tree.paths[:, 1:].reshape(-1), jnp.int32)   # [W*D]
    ok_chain = ok[:, chain].reshape(n, w, depth)
    acc_len = jnp.cumprod(ok_chain.astype(jnp.int32), axis=2).sum(axis=2)
    best_greedy = jnp.argmax(acc_len, axis=1).astype(jnp.int32)     # [N]
    use_sample = do_sample & (temperature > 0.0)
    split = jax.vmap(lambda r: jax.random.split(r, 2))(rngs)
    draw_rngs, new_rngs = split[:, 0], split[:, 1]

    def _path_emit(best):
        path = jnp.take(paths_j, best, axis=0)                      # [N, D+1]
        emit = jnp.take_along_axis(greedy, path, axis=1)            # [N, D+1]
        acc = jnp.take_along_axis(ok, path[:, 1:], axis=1)          # [N, D]
        return path, emit, acc

    def _greedy(_):
        _, emit, acc = _path_emit(best_greedy)
        return emit, acc, best_greedy

    def _sampled(_):
        rep = lambda x: jnp.repeat(x, s_nodes, axis=0)
        filt = filter_logits_batched(
            logits.reshape(n * s_nodes, vocab),
            temperature=rep(temperature), top_k=rep(top_k), top_p=rep(top_p),
        ).reshape(n, s_nodes, vocab)
        neg_inf = jnp.finfo(jnp.float32).min
        # per lane: W branch tries + 1 branch fallback + (D-1) * (accept draw
        # + residual resample) + 1 bonus draw = W + 2D keys
        keys = jax.vmap(lambda r: jax.random.split(r, w + 2 * depth))(draw_rngs)

        # --- branch point: multi-try speculative sampling over the W sibling
        # candidates.  Trying candidate b against the running residual (all
        # previously tried tokens masked out) and falling through to a final
        # residual sample reproduces the root distribution exactly — the
        # multi-candidate generalization of the Leviathan point-mass rule.
        rem = filt[:, 0]                                 # [N, V]
        acc1 = jnp.zeros(n, bool)
        pick = jnp.zeros(n, jnp.int32)
        tok1 = jnp.zeros(n, jnp.int32)
        for b in range(w):
            d_b = tokens[:, int(tree.paths[b, 1])]
            p_b = jnp.take_along_axis(
                jax.nn.softmax(rem, axis=-1), d_b[:, None], axis=1
            )[:, 0]
            u_b = jax.vmap(jax.random.uniform)(keys[:, b])
            take = (~acc1) & (u_b < p_b)
            pick = jnp.where(take, b, pick)
            tok1 = jnp.where(take, d_b, tok1)
            acc1 = acc1 | take
            rem = jnp.where(jax.nn.one_hot(d_b, vocab, dtype=bool), neg_inf, rem)
        res1 = jax.vmap(jax.random.categorical)(keys[:, w], rem).astype(jnp.int32)
        tok1 = jnp.where(acc1, tok1, res1)
        path_s = jnp.take(paths_j, pick, axis=0)         # [N, D+1]

        # --- down the chosen branch: the linear point-mass accept/resample
        emit_cols = [tok1]
        acc_cols = [acc1]
        for t in range(1, depth):
            node_t = path_s[:, t]
            filt_t = jnp.take_along_axis(
                filt, node_t[:, None, None], axis=1
            )[:, 0]                                      # [N, V]
            d_t = jnp.take_along_axis(
                tokens, path_s[:, t + 1][:, None], axis=1
            )[:, 0]
            p_t = jnp.take_along_axis(
                jax.nn.softmax(filt_t, axis=-1), d_t[:, None], axis=1
            )[:, 0]
            u_t = jax.vmap(jax.random.uniform)(keys[:, w + 2 * t - 1])
            acc_t = u_t < p_t
            resid = jnp.where(jax.nn.one_hot(d_t, vocab, dtype=bool), neg_inf, filt_t)
            res_t = jax.vmap(jax.random.categorical)(
                keys[:, w + 2 * t], resid
            ).astype(jnp.int32)
            emit_cols.append(jnp.where(acc_t, d_t, res_t))
            acc_cols.append(acc_t)
        filt_deep = jnp.take_along_axis(
            filt, path_s[:, depth][:, None, None], axis=1
        )[:, 0]
        bonus = jax.vmap(jax.random.categorical)(
            keys[:, w + 2 * depth - 1], filt_deep
        ).astype(jnp.int32)
        emit_cols.append(bonus)
        emit_s = jnp.stack(emit_cols, axis=1)            # [N, D+1]
        acc_s = jnp.stack(acc_cols, axis=1)              # [N, D]

        _, emit_g, acc_g = _path_emit(best_greedy)
        emit = jnp.where(use_sample[:, None], emit_s, emit_g)
        acc = jnp.where(use_sample[:, None], acc_s, acc_g)
        best = jnp.where(use_sample, pick, best_greedy)
        return emit, acc, best

    emit, acc, best = jax.lax.cond(jnp.any(use_sample), _sampled, _greedy, None)
    path = jnp.take(paths_j, best, axis=0)               # [N, D+1]
    n_accept = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)
    pos = jnp.arange(dp1)[None, :]
    committable = pos <= n_accept[:, None]
    is_eos = (emit == eos[:, None]) & (eos >= 0)[:, None]
    eos_before = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos) > 0
    commit = committable & ~eos_before & active[:, None]
    n_commit = commit.sum(axis=1).astype(jnp.int32)
    out = jnp.where(commit, emit, pad[:, None])
    # commit the winning path's KV to the lane frontier, roll back the rest:
    # the layout any later window sees is what linear decode would have built
    if isinstance(cache, PagedKVCache):
        cache = _tree_commit_paged(cache, prev_index, path)
        cache = cache.replace(index=prev_index + n_commit)
    else:
        def _compact(kv):
            def lane(kv_lane, idx, p):
                rows = jnp.take(kv_lane, idx + p, axis=1)    # [L, D+1, H, Dh]
                return jax.lax.dynamic_update_slice(kv_lane, rows, (0, idx, 0, 0))

            return jax.vmap(lane, in_axes=(1, 0, 0), out_axes=1)(
                kv, prev_index, path
            )

        cache = cache.replace(
            k=_compact(cache.k), v=_compact(cache.v),
            index=prev_index + n_commit,
        )
    last = jnp.maximum(n_commit - 1, 0)
    new_pending = jnp.take_along_axis(out, last[:, None], axis=1)[:, 0]
    return cache, out, n_commit, new_pending, new_rngs


def make_prefill_chunk(model: Transformer, chunk_len: int,
                       shardings: Optional[ServeShardings] = None):
    """Jitted ``(params, tokens [1, chunk_len], scratch) -> scratch`` prefill.

    Writes the chunk's KV into the batch-1 scratch cache at
    ``scratch.index .. scratch.index + chunk_len`` and advances the index.
    The final chunk of a prompt may be padded past the prompt's end: padded
    positions write garbage KV *beyond* the valid length, which the causal
    mask never lets any later query read (and :func:`make_insert` copies but
    decode progressively overwrites).  Logits are discarded — the first
    generated token comes from the shared decode step re-processing the last
    prompt token, so prefill and decode share one sampling path.
    """

    def prefill_chunk(params, tokens, scratch):
        _, scratch = model.apply({"params": params}, tokens, cache=scratch)
        return scratch

    s = shardings
    return _serve_jit(
        prefill_chunk,
        donate_argnums=(2,),
        in_shardings=None if s is None else (s.params, s.replicated, s.cache()),
        out_shardings=None if s is None else s.cache(),
    )


def make_insert(shardings: Optional[ServeShardings] = None):
    """Jitted ``insert_request``: copy a prefilled scratch KV into a freed slot.

    ``(pool, scratch_k [L,1,Mp,H,D], scratch_v, slot, length) -> pool`` —
    ``dynamic_update_slice`` at ``(0, slot, 0, 0, 0)`` writes one lane only;
    running lanes' KV and indices are untouched (the property the slot-reuse
    and permutation tests pin down).  ``length`` is ``prompt_len - 1``: the
    last prompt token is left pending so the decode window computes the first
    generated token through the same executable as every later token.
    """

    def insert_request(pool: KVCache, scratch_k, scratch_v, slot, length):
        k = jax.lax.dynamic_update_slice(
            pool.k, scratch_k.astype(pool.k.dtype), (0, slot, 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            pool.v, scratch_v.astype(pool.v.dtype), (0, slot, 0, 0, 0)
        )
        return pool.replace(k=k, v=v, index=pool.index.at[slot].set(length))

    s = shardings
    return _serve_jit(
        insert_request,
        donate_argnums=(0,),
        in_shardings=None if s is None else (s.cache(), s.kv, s.kv, *s.rep(2)),
        out_shardings=None if s is None else s.cache(),
    )


def make_lane_install(shardings: Optional[ServeShardings] = None):
    """Jitted one-slot edit of the device-resident lane vectors.

    ``(pending [N], active [N], eos [N], do_sample [N], temperature [N],
    top_k [N], top_p [N], rngs [N,2], slot, tok, eos_v, do_sample_v,
    temperature_v, top_k_v, top_p_v, rng [2]) -> (the eight vectors,
    updated at ``slot``)``

    Admission under the pipelined loop must not read lane state back from
    the device: the pending/rng vectors are carried on device between
    windows, so a host round-trip blocks on the in-flight window and turns
    every install into a depth-1 pipeline sync.  This scatter instead
    *enqueues* the edit — it consumes the in-flight window's output handles
    and therefore runs right after that window retires, off the host's
    critical path.  Inputs are not donated: the vectors are a few hundred
    bytes and the in-flight window may still hold them as operands.
    """

    def lane_install(pending, active, eos, do_sample, temperature, top_k,
                     top_p, rngs, slot, tok, eos_v, do_sample_v,
                     temperature_v, top_k_v, top_p_v, rng):
        return (
            pending.at[slot].set(tok),
            active.at[slot].set(True),
            eos.at[slot].set(eos_v),
            do_sample.at[slot].set(do_sample_v),
            temperature.at[slot].set(temperature_v),
            top_k.at[slot].set(top_k_v),
            top_p.at[slot].set(top_p_v),
            rngs.at[slot].set(rng),
        )

    s = shardings
    return _serve_jit(
        lane_install,
        in_shardings=None if s is None else s.rep(16),
        out_shardings=None if s is None else s.rep(8),
    )


def make_copy_chunk(chunk_len: int,
                    shardings: Optional[ServeShardings] = None):
    """Jitted ``(scratch, slab_k, slab_v) -> scratch``: replay one cached chunk.

    The prefix-cache hit path: a retained KV slab ``[L, 1, chunk_len, H, D]``
    (what :func:`make_prefill_chunk` computed for these tokens under this
    exact prefix) is ``dynamic_update_slice``-d into the batch-1 scratch cache
    at ``scratch.index`` — the same shape family as :func:`make_insert`, so
    the compiled-shape budget grows by exactly one executable per bucket, not
    per request.  The index advances by the full ``chunk_len`` just as a real
    prefill of this chunk would.
    """

    def copy_chunk(scratch: KVCache, slab_k, slab_v):
        k = jax.lax.dynamic_update_slice(
            scratch.k, slab_k.astype(scratch.k.dtype), (0, 0, scratch.index, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            scratch.v, slab_v.astype(scratch.v.dtype), (0, 0, scratch.index, 0, 0)
        )
        return scratch.replace(k=k, v=v, index=scratch.index + chunk_len)

    s = shardings
    return _serve_jit(
        copy_chunk,
        donate_argnums=(0,),
        in_shardings=None if s is None else (s.cache(), s.kv, s.kv),
        out_shardings=None if s is None else s.cache(),
    )


# --------------------------------------------------------------------- paged
# Block-table variants (ServingEngine(paged=True), :mod:`.paging`): KV lives
# in a shared page pool ``[L, num_pages, page, Hkv, Dh]`` and each executable
# gathers a lane's pages into a contiguous view, runs the *same* traced
# decode/verify/prefill body as the slab path, then scatters only the
# newly-written positions back.  The view width equals the slab width
# (``pages_per_lane * page == max_len``), so the attention program — and with
# it every greedy argmax — is bitwise identical to the legacy pool.  The
# transient gathered view costs one slab-sized temporary per call; removing it
# is exactly the ROADMAP's "Pallas paged decode kernel" item, which reads
# pages in place.  Compiled-shape budget: one paged executable per legacy
# shape plus ONE ``copy_page`` (copy-on-write), still bounded by bucket count.


def _gather_view(pages, tables):
    """``pages [L, NP, page, H, D]`` gathered through ``tables [N, P]`` into a
    contiguous per-lane view ``[L, N, P * page, H, D]``."""
    L, _, page, H, D = pages.shape
    N, P = tables.shape
    return pages[:, tables].reshape(L, N, P * page, H, D)


def _live_tables(tables, live):
    """Mask table slots at or past each lane's live page count to the null
    page, so gathers only move pages that can hold a visible key.  ``live``
    is ``[N]`` (or scalar for the single prefill lane).  Bitwise-neutral: a
    masked slot's positions sit past the lane's valid length, and the causal
    mask already replaces their logits before the softmax — this just stops
    the gather from reading whole stale pages to feed positions the mask
    throws away."""
    num_p = tables.shape[-1]
    if jnp.ndim(live) == 0:
        return jnp.where(jnp.arange(num_p) < live, tables, NULL_PAGE)
    return jnp.where(jnp.arange(num_p)[None, :] < live[:, None], tables, NULL_PAGE)


def _scatter_span(pages, view, tables, start, width: int, active):
    """Write ``view[:, n, start[n] : start[n] + width]`` back through lane
    ``n``'s block table, for every ACTIVE lane.  Positions are guaranteed
    in-range by the engine's admission check (``prompt + max_new + span <=
    max_len``).  Inactive lanes' writes are rerouted to the null page: a
    frozen lane's row may be vacant (all-null already), but a lane mid-prefill
    has REAL pages mapped — possibly shared with the prefix cache — and its
    stale write index must never trample them."""
    L, _, page, H, D = pages.shape
    N = tables.shape[0]
    written = jax.vmap(
        lambda kv, i: jax.lax.dynamic_slice(kv, (0, i, 0, 0), (L, width, H, D)),
        in_axes=(1, 0), out_axes=1,
    )(view, start)                                       # [L, N, width, H, D]
    pos = start[:, None] + jnp.arange(width)             # [N, width]
    pid = jnp.take_along_axis(tables, pos // page, axis=1)
    pid = jnp.where(active[:, None], pid, NULL_PAGE)
    off = pos % page
    return pages.at[:, pid.reshape(-1), off.reshape(-1)].set(
        written.reshape(L, N * width, H, D)
    )


def make_paged_prefill_chunk(model: Transformer, chunk_len: int, page_size: int,
                             direct: bool = False,
                             shardings: Optional[ServeShardings] = None):
    """Paged prefill: ``(params, tokens [1, chunk_len], pages_k, pages_v,
    table [P], base) -> (pages_k, pages_v)``.

    Gathers the prefilling lane's full view (shared prefix pages included —
    this is how a partial cache hit feeds context to the chunks after it
    without any copy), runs the slab prefill forward at scalar index ``base``,
    and scatters the chunk's ``chunk_len / page_size`` freshly-written pages
    back.  ``base`` and the chunk span are page-aligned by construction: every
    bucket is a multiple of ``page_size`` and chunk starts are sums of
    buckets, so a chunk never writes into a shared page.

    ``direct=True`` swaps the gather/scatter sandwich for the in-model paged
    cache (:class:`~accelerate_tpu.models.transformer.PagedKVCache`): the
    forward reads pages in place and the write path owns the per-page scales,
    so quantized pools requantize each touched page against fresh content.
    Signature becomes ``(params, tokens, pages_k, pages_v, k_scales, v_scales,
    table [P], base) -> (pages_k, pages_v, k_scales, v_scales, quant_err)``.
    With a ``paged_kernel="flash_prefill"`` model this is the Pallas prefill
    path (``ops/paged_attention.py::paged_flash_prefill``) — no gather, no
    scatter round-trip, the chunk attends over prior pages in place.
    """
    if chunk_len % page_size != 0:
        raise ValueError(
            f"chunk bucket {chunk_len} must be a multiple of page_size {page_size}"
        )
    npg = chunk_len // page_size
    s = shardings

    if direct:
        def direct_prefill_chunk(params, tokens, pages_k, pages_v, k_scales,
                                 v_scales, table, base):
            cache = PagedKVCache(
                pages_k=pages_k, pages_v=pages_v,
                k_scales=k_scales, v_scales=v_scales,
                tables=table[None], index=base.reshape(1),
                active=jnp.ones((1,), bool), quant_err=jnp.float32(0.0),
            )
            _, cache = model.apply({"params": params}, tokens, cache=cache)
            return (cache.pages_k, cache.pages_v, cache.k_scales,
                    cache.v_scales, cache.quant_err)

        return _serve_jit(
            direct_prefill_chunk,
            donate_argnums=(2, 3, 4, 5),
            in_shardings=None if s is None else (
                s.params, s.replicated, s.kv, s.kv, s.scales, s.scales,
                *s.rep(2),
            ),
            out_shardings=None if s is None else (
                s.kv, s.kv, s.scales, s.scales, s.replicated,
            ),
        )

    def paged_prefill_chunk(params, tokens, pages_k, pages_v, table, base):
        L, _, page, H, D = pages_k.shape
        live = (base + chunk_len - 1) // page_size + 1
        gt = _live_tables(table, live)
        cache = KVCache(
            k=_gather_view(pages_k, gt[None]),
            v=_gather_view(pages_v, gt[None]),
            index=base,
        )
        _, cache = model.apply({"params": params}, tokens, cache=cache)
        ids = jax.lax.dynamic_slice(table, (base // page_size,), (npg,))
        wk = jax.lax.dynamic_slice(cache.k, (0, 0, base, 0, 0), (L, 1, chunk_len, H, D))
        wv = jax.lax.dynamic_slice(cache.v, (0, 0, base, 0, 0), (L, 1, chunk_len, H, D))
        pages_k = pages_k.at[:, ids].set(wk.reshape(L, npg, page, H, D))
        pages_v = pages_v.at[:, ids].set(wv.reshape(L, npg, page, H, D))
        return pages_k, pages_v

    return _serve_jit(
        paged_prefill_chunk,
        donate_argnums=(2, 3),
        in_shardings=None if s is None else (
            s.params, s.replicated, s.kv, s.kv, *s.rep(2),
        ),
        out_shardings=None if s is None else (s.kv, s.kv),
    )


def make_paged_decode_window(model: Transformer, window: int,
                             direct: bool = False,
                             shardings: Optional[ServeShardings] = None):
    """Paged decode: ``(params, pages_k, pages_v, tables [N, P], index [N],
    tokens, active, eos, do_sample, temperature, top_k, top_p, pad, rngs)
    -> (pages_k, pages_v, out_tokens [N, window], new_pending, new_rngs)``.

    Gather view -> the shared :func:`_decode_scan` (bitwise the slab program)
    -> scatter the ``window`` written positions per lane.  The engine tracks
    each lane's index on the host (install/advance arithmetic is exact), so
    no index array needs to round-trip.

    ``direct=True`` drops the gather/scatter sandwich: the model runs on a
    :class:`~accelerate_tpu.models.transformer.PagedKVCache`, attention reads
    pages in place (``config.paged_kernel`` picks pallas kernel vs XLA
    reference) and writes go through the scale-aware paged insert — the
    quantized-KV and Pallas fast paths.  Same traced ``_decode_scan`` body, so
    sampling/freeze/EOS semantics cannot drift.  Signature gains the scale
    arrays: ``(params, pages_k, pages_v, k_scales, v_scales, tables, index,
    tokens, ...) -> (pages_k, pages_v, k_scales, v_scales, out_tokens,
    new_pending, new_rngs, quant_err)``.
    """

    s = shardings

    if direct:
        def direct_decode_window(params, pages_k, pages_v, k_scales, v_scales,
                                 tables, index, tokens, active, eos, do_sample,
                                 temperature, top_k, top_p, pad, rngs):
            cache = PagedKVCache(
                pages_k=pages_k, pages_v=pages_v,
                k_scales=k_scales, v_scales=v_scales,
                tables=tables, index=index, active=active,
                quant_err=jnp.float32(0.0),
            )
            cache, toks, tok, rngs = _decode_scan(
                model, window, params, cache, tokens, active, eos, do_sample,
                temperature, top_k, top_p, pad, rngs,
            )
            return (cache.pages_k, cache.pages_v, cache.k_scales,
                    cache.v_scales, toks, tok, rngs, cache.quant_err)

        return _serve_jit(
            direct_decode_window,
            donate_argnums=(1, 2, 3, 4),
            in_shardings=None if s is None else (
                s.params, s.kv, s.kv, s.scales, s.scales, *s.rep(11),
            ),
            out_shardings=None if s is None else (
                s.kv, s.kv, s.scales, s.scales, *s.rep(4),
            ),
        )

    def paged_decode_window(params, pages_k, pages_v, tables, index, tokens,
                            active, eos, do_sample, temperature, top_k, top_p,
                            pad, rngs):
        page = pages_k.shape[2]
        gt = _live_tables(tables, (index + window - 1) // page + 1)
        cache = KVCache(
            k=_gather_view(pages_k, gt),
            v=_gather_view(pages_v, gt),
            index=index,
        )
        cache, toks, tok, rngs = _decode_scan(
            model, window, params, cache, tokens, active, eos, do_sample,
            temperature, top_k, top_p, pad, rngs,
        )
        pages_k = _scatter_span(pages_k, cache.k, tables, index, window, active)
        pages_v = _scatter_span(pages_v, cache.v, tables, index, window, active)
        return pages_k, pages_v, toks, tok, rngs

    return _serve_jit(
        paged_decode_window,
        donate_argnums=(1, 2),
        in_shardings=None if s is None else (s.params, s.kv, s.kv, *s.rep(11)),
        out_shardings=None if s is None else (s.kv, s.kv, *s.rep(3)),
    )


def make_paged_verify_window(model: Transformer, k: int, direct: bool = False,
                             shardings: Optional[ServeShardings] = None):
    """Paged speculative verify: the slab :func:`_verify_body` over a gathered
    view, scattering all ``K+1`` written positions back (rejected positions'
    KV is unreachable past the committed index and gets overwritten later,
    exactly as in the slab path).  ``(params, pages_k, pages_v, tables, index,
    tokens [N, K+1], ...) -> (pages_k, pages_v, out, n_commit, new_pending,
    new_rngs)`` — the engine advances its host index mirror by ``n_commit``.

    ``direct=True``: in-model paged cache (see
    :func:`make_paged_decode_window`); signature gains the scale arrays and a
    trailing ``quant_err``.
    """
    kp1 = k + 1
    s = shardings

    if direct:
        def direct_verify_window(params, pages_k, pages_v, k_scales, v_scales,
                                 tables, index, tokens, active, eos, do_sample,
                                 temperature, top_k, top_p, pad, rngs):
            cache = PagedKVCache(
                pages_k=pages_k, pages_v=pages_v,
                k_scales=k_scales, v_scales=v_scales,
                tables=tables, index=index, active=active,
                quant_err=jnp.float32(0.0),
            )
            cache, out, n_commit, new_pending, new_rngs = _verify_body(
                model, k, params, cache, tokens, active, eos, do_sample,
                temperature, top_k, top_p, pad, rngs,
            )
            return (cache.pages_k, cache.pages_v, cache.k_scales,
                    cache.v_scales, out, n_commit, new_pending, new_rngs,
                    cache.quant_err)

        return _serve_jit(
            direct_verify_window,
            donate_argnums=(1, 2, 3, 4),
            in_shardings=None if s is None else (
                s.params, s.kv, s.kv, s.scales, s.scales, *s.rep(11),
            ),
            out_shardings=None if s is None else (
                s.kv, s.kv, s.scales, s.scales, *s.rep(5),
            ),
        )

    def paged_verify_window(params, pages_k, pages_v, tables, index, tokens,
                            active, eos, do_sample, temperature, top_k, top_p,
                            pad, rngs):
        page = pages_k.shape[2]
        gt = _live_tables(tables, (index + kp1 - 1) // page + 1)
        cache = KVCache(
            k=_gather_view(pages_k, gt),
            v=_gather_view(pages_v, gt),
            index=index,
        )
        cache, out, n_commit, new_pending, new_rngs = _verify_body(
            model, k, params, cache, tokens, active, eos, do_sample,
            temperature, top_k, top_p, pad, rngs,
        )
        pages_k = _scatter_span(pages_k, cache.k, tables, index, kp1, active)
        pages_v = _scatter_span(pages_v, cache.v, tables, index, kp1, active)
        return pages_k, pages_v, out, n_commit, new_pending, new_rngs

    return _serve_jit(
        paged_verify_window,
        donate_argnums=(1, 2),
        in_shardings=None if s is None else (s.params, s.kv, s.kv, *s.rep(11)),
        out_shardings=None if s is None else (s.kv, s.kv, *s.rep(4)),
    )


def _tree_commit_paged(cache: PagedKVCache, prev_index, path):
    """Commit a tree verify's winning path inside the page pool: gather the
    ``D+1`` path nodes' KV rows through each lane's block table and re-insert
    them contiguously at the lane frontier — the paged twin of the slab
    compaction in :func:`_tree_verify_body`.  Quantized pools dequantize the
    gathered rows and requantize at insert (the same scatter-time scale
    discipline as every other paged write; the round-trip error folds into
    ``quant_err``).  Losing branches' rows past ``frontier + D`` are zeroed by
    the next insert touching their page (stale-slot rule of
    :func:`~accelerate_tpu.ops.paged_attention.paged_quantized_insert`) and
    are never visible to attention (masked past each lane's length)."""
    from ..ops.paged_attention import (
        kv_qmax,
        paged_insert,
        paged_quantized_insert,
    )

    page = cache.pages_k.shape[2]
    p_max = cache.tables.shape[1] - 1
    pos = prev_index[:, None] + path                     # [N, D+1]
    pid = jnp.take_along_axis(
        cache.tables, jnp.clip(pos // page, 0, p_max), axis=1
    )
    off = pos % page
    quantized = kv_qmax(cache.pages_k.dtype) is not None

    def _rows(pages, scales):
        rows = pages[:, pid, off]                        # [L, N, D+1, H, Dh]
        if quantized:
            rows = rows.astype(jnp.float32) * scales[:, pid][..., None]
        return rows

    rows_k = _rows(cache.pages_k, cache.k_scales)
    rows_v = _rows(cache.pages_v, cache.v_scales)
    if quantized:
        ins = jax.vmap(
            lambda p, sc, r: paged_quantized_insert(
                p, sc, r, cache.tables, prev_index, cache.active
            )
        )
        pages_k, k_scales, err_k = ins(cache.pages_k, cache.k_scales, rows_k)
        pages_v, v_scales, err_v = ins(cache.pages_v, cache.v_scales, rows_v)
        err = jnp.maximum(jnp.max(err_k), jnp.max(err_v))
        return cache.replace(
            pages_k=pages_k, pages_v=pages_v,
            k_scales=k_scales, v_scales=v_scales,
            quant_err=jnp.maximum(cache.quant_err, err),
        )
    ins = jax.vmap(
        lambda p, r: paged_insert(p, r, cache.tables, prev_index, cache.active)
    )
    return cache.replace(
        pages_k=ins(cache.pages_k, rows_k), pages_v=ins(cache.pages_v, rows_v)
    )


def make_paged_tree_verify_window(model: Transformer, tree,
                                  direct: bool = False,
                                  shardings: Optional[ServeShardings] = None):
    """Paged tree speculative verify — :func:`make_tree_verify_window` over
    the page pool.  ``(params, pages_k, pages_v, tables, index,
    tokens [N, S], ...) -> (pages_k, pages_v, out [N, D+1], n_commit,
    new_pending, new_rngs)``.

    ``direct=False`` runs the slab :func:`_tree_verify_body` (including its
    slab compaction) over a gathered per-lane view and scatters all ``S``
    written positions back — rows past the compacted frontier are unreachable
    garbage, exactly like rejected positions in the linear paged verify.
    ``direct=True`` threads the :class:`PagedKVCache` through the model (the
    quantized / pallas-kernel path); the winning path commits via
    :func:`_tree_commit_paged` and the signature gains the scale arrays and a
    trailing ``quant_err``.
    """
    s_nodes = tree.nodes
    s = shardings

    if direct:
        def direct_tree_verify_window(params, pages_k, pages_v, k_scales,
                                      v_scales, tables, index, tokens, active,
                                      eos, do_sample, temperature, top_k,
                                      top_p, pad, rngs):
            cache = PagedKVCache(
                pages_k=pages_k, pages_v=pages_v,
                k_scales=k_scales, v_scales=v_scales,
                tables=tables, index=index, active=active,
                quant_err=jnp.float32(0.0),
            )
            cache, out, n_commit, new_pending, new_rngs = _tree_verify_body(
                model, tree, params, cache, tokens, active, eos, do_sample,
                temperature, top_k, top_p, pad, rngs,
            )
            return (cache.pages_k, cache.pages_v, cache.k_scales,
                    cache.v_scales, out, n_commit, new_pending, new_rngs,
                    cache.quant_err)

        return _serve_jit(
            direct_tree_verify_window,
            donate_argnums=(1, 2, 3, 4),
            in_shardings=None if s is None else (
                s.params, s.kv, s.kv, s.scales, s.scales, *s.rep(11),
            ),
            out_shardings=None if s is None else (
                s.kv, s.kv, s.scales, s.scales, *s.rep(5),
            ),
        )

    def paged_tree_verify_window(params, pages_k, pages_v, tables, index,
                                 tokens, active, eos, do_sample, temperature,
                                 top_k, top_p, pad, rngs):
        page = pages_k.shape[2]
        gt = _live_tables(tables, (index + s_nodes - 1) // page + 1)
        cache = KVCache(
            k=_gather_view(pages_k, gt),
            v=_gather_view(pages_v, gt),
            index=index,
        )
        cache, out, n_commit, new_pending, new_rngs = _tree_verify_body(
            model, tree, params, cache, tokens, active, eos, do_sample,
            temperature, top_k, top_p, pad, rngs,
        )
        pages_k = _scatter_span(pages_k, cache.k, tables, index, s_nodes, active)
        pages_v = _scatter_span(pages_v, cache.v, tables, index, s_nodes, active)
        return pages_k, pages_v, out, n_commit, new_pending, new_rngs

    return _serve_jit(
        paged_tree_verify_window,
        donate_argnums=(1, 2),
        in_shardings=None if s is None else (s.params, s.kv, s.kv, *s.rep(11)),
        out_shardings=None if s is None else (s.kv, s.kv, *s.rep(4)),
    )


def make_copy_page(shardings: Optional[ServeShardings] = None):
    """Jitted copy-on-write: ``(pages_k, pages_v, k_scales, v_scales, src,
    dst) -> (pages_k, pages_v, k_scales, v_scales)`` duplicates one physical
    page (dequantization scales ride along — a quantized copy is exact, both
    pages decode identically).  Runs only when a lane's first decode write
    lands in a page the prefix cache (or a sibling lane) still references —
    at most once per admitted request, and never on the pure aliasing hit
    path.  One compiled shape per engine, page-size-static.
    """

    def copy_page(pages_k, pages_v, k_scales, v_scales, src, dst):
        pages_k = pages_k.at[:, dst].set(pages_k[:, src])
        pages_v = pages_v.at[:, dst].set(pages_v[:, src])
        k_scales = k_scales.at[:, dst].set(k_scales[:, src])
        v_scales = v_scales.at[:, dst].set(v_scales[:, src])
        return pages_k, pages_v, k_scales, v_scales

    s = shardings
    return _serve_jit(
        copy_page,
        donate_argnums=(0, 1, 2, 3),
        in_shardings=None if s is None else (
            s.kv, s.kv, s.scales, s.scales, *s.rep(2),
        ),
        out_shardings=None if s is None else (s.kv, s.kv, s.scales, s.scales),
    )


def make_spill_extract(npages: int, shardings: Optional[ServeShardings] = None):
    """Jitted D2H-side gather for the hierarchical prefix cache's spill path:
    ``(pages_k, pages_v, k_scales, v_scales, ids [npages]) -> (chunk_k
    [L, npages, page, Hkv, Dh], chunk_v, chunk_k_scales [L, npages, Hkv],
    chunk_v_scales)`` packs one evicted chunk's pages (quant scales ride
    along, so int8/fp8 chunks spill at their quantized density) into dense
    per-chunk arrays the engine fetches at its drain point — the gather is
    enqueued, never synced, and NOTHING is donated: the pool stays live for
    the in-flight decode window.  One compiled shape per prefill bucket
    (``npages = bucket // page_size``), so the compiled budget grows by
    exactly the bucket set.
    """

    def spill_extract(pages_k, pages_v, k_scales, v_scales, ids):
        if ids.shape[0] != npages:
            raise ValueError(
                f"spill_extract compiled for {npages} pages, got {ids.shape[0]}"
            )
        return (jnp.take(pages_k, ids, axis=1),
                jnp.take(pages_v, ids, axis=1),
                jnp.take(k_scales, ids, axis=1),
                jnp.take(v_scales, ids, axis=1))

    s = shardings
    return _serve_jit(
        spill_extract,
        in_shardings=None if s is None else (
            s.kv, s.kv, s.scales, s.scales, s.replicated,
        ),
        out_shardings=None if s is None else (s.kv, s.kv, s.scales, s.scales),
    )


def make_promote_install(npages: int, shardings: Optional[ServeShardings] = None):
    """Jitted H2D-side scatter for the hierarchical prefix cache's promotion
    path: ``(pages_k, pages_v, k_scales, v_scales, chunk_k, chunk_v,
    chunk_k_scales, chunk_v_scales, ids [npages]) -> (pages_k, pages_v,
    k_scales, v_scales)`` installs a spilled chunk's payload into freshly
    allocated pages.  The pool arrays are donated (in-place alias per shard,
    the decode-window discipline), so the engine parks the old handles on the
    in-flight window's ``Readback.consumed`` before rebinding — the install
    enqueues *behind* the window and overlaps the decode it rides with.  One
    compiled shape per prefill bucket, mirroring :func:`make_spill_extract`.
    """

    def promote_install(pages_k, pages_v, k_scales, v_scales,
                        chunk_k, chunk_v, chunk_k_scales, chunk_v_scales, ids):
        if ids.shape[0] != npages:
            raise ValueError(
                f"promote_install compiled for {npages} pages, got {ids.shape[0]}"
            )
        pages_k = pages_k.at[:, ids].set(chunk_k.astype(pages_k.dtype))
        pages_v = pages_v.at[:, ids].set(chunk_v.astype(pages_v.dtype))
        k_scales = k_scales.at[:, ids].set(chunk_k_scales.astype(k_scales.dtype))
        v_scales = v_scales.at[:, ids].set(chunk_v_scales.astype(v_scales.dtype))
        return pages_k, pages_v, k_scales, v_scales

    s = shardings
    return _serve_jit(
        promote_install,
        donate_argnums=(0, 1, 2, 3),
        in_shardings=None if s is None else (
            s.kv, s.kv, s.scales, s.scales,
            s.kv, s.kv, s.scales, s.scales, s.replicated,
        ),
        out_shardings=None if s is None else (s.kv, s.kv, s.scales, s.scales),
    )


def pad_page_ids(ids: Sequence[int], npages: int) -> "np.ndarray":
    """Pad a lane's live page-id list with ``NULL_PAGE`` up to a migration
    executable's fixed ``npages`` width — the sanctioned bucket-padded
    dispatch.  The null page is the pool's garbage sink: the migrate gather
    reads finite (harmless) values from it for the padded rows, and the
    migrate install scatters those padded rows back INTO it, where writes
    are harmless by construction — so one compiled shape serves every
    per-lane page count and nothing ever drifts the jit signature."""
    if len(ids) > npages:
        raise ValueError(
            f"lane holds {len(ids)} pages, exceeding the executable's "
            f"{npages}-page width"
        )
    out = np.full((npages,), NULL_PAGE, np.int32)
    out[:len(ids)] = np.asarray(ids, np.int32)
    return out


def plan_chunks(prompt_len: int, buckets: Sequence[int]) -> Tuple[Tuple[int, int], ...]:
    """Split a prompt into prefill chunks drawn from the fixed bucket sizes.

    Returns ``((bucket_len, valid_len), ...)``: greedy largest-fit, so only
    the final chunk can be padded (``valid_len < bucket_len``).  KV for the
    prompt's last token is still *written* by prefill but re-written by the
    first decode step — see :func:`make_insert`.
    """
    buckets = sorted(set(int(b) for b in buckets))
    if not buckets or buckets[0] <= 0:
        raise ValueError(f"prefill buckets must be positive, got {buckets}")
    chunks = []
    remaining = prompt_len
    while remaining > 0:
        fit = [b for b in buckets if b <= remaining]
        b = max(fit) if fit else buckets[0]
        chunks.append((b, min(b, remaining)))
        remaining -= min(b, remaining)
    return tuple(chunks)


def jit_cache_sizes(*fns) -> int:
    """Total number of compiled executables across jitted fns — the
    no-per-request-retrace assertion counter (0 until first call).  Reads the
    pjit-internal counter through
    :func:`~accelerate_tpu.utils.jax_compat.jit_cache_size`, which degrades to
    0 rather than crashing if a jax minor bump moves the private attribute."""
    return sum(jit_cache_size(f) or 0 for f in fns)

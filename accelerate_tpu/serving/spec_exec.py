"""Speculation dispatch stage: drafters, tree topology, and the draft forward.

The engine's speculative machinery used to live inline in ``engine.py``
(``_propose_drafts`` / ``_verify_cycle``); this module extracts it into a
stage with a small **drafter protocol** so the scheduler carve-up planned on
the ROADMAP never has to thread through drafting code.  Three drafters:

* ``ngram`` — :class:`NgramDrafter`: the host-side prompt-lookup drafter
  (:mod:`.spec`), now backed by the *incremental* per-lane
  :class:`~accelerate_tpu.serving.spec.NgramIndex` so steady-state drafting
  is O(k) per cycle instead of re-walking the whole context.  Feeds the
  linear ``[slots, K+1]`` verify window; token-identical to the brute-force
  matcher.
* ``model`` — :class:`TreeDrafter` with ``width == 1``: an on-device draft
  model (a truncated-layer head of the served model, see
  :func:`build_draft`) drafts ``depth`` tokens per lane in ONE small jitted
  forward (:func:`make_draft_forward`) instead of host numpy.  Verification
  still runs the tree window — a width-1 tree is exactly the linear chain.
* ``tree`` — :class:`TreeDrafter` with ``width > 1``: the draft model's
  top-``width`` candidates at the branch point each extend into a greedy
  chain, giving a ``1 + width * depth``-node token tree
  (:class:`TreeSpec`, chains topology) verified in one forward under the
  ancestor mask (SpecInfer/Medusa-style tree attention).

The draft forward is **stateless**: each cycle it re-prefills a bounded
per-lane context window (:class:`~accelerate_tpu.serving.paging
.DraftContextWindow`, host-side) through the truncated head into a scratch
KV created inside the jit.  A persistent draft KV tier was considered and
rejected: it would need its own page class, rollback of losing branches
every cycle, and a second swap/donation discipline — re-prefilling
``draft_ctx`` tokens through a few layers costs less than one verify forward
and keeps the draft a pure function of the visible context.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import KVCache, Transformer, TransformerConfig
from .spec import NgramIndex


class TreeSpec:
    """Static chains-topology token tree for speculative verification.

    ``width`` sibling branches at the branch point, each a greedy chain of
    ``depth`` draft tokens: ``nodes = 1 + width * depth``.  Node 0 is the
    lane's pending token (the tree root, depth 0); branch ``b``'s node at
    level ``s`` (1-based) is ``1 + b * depth + (s - 1)``.  Siblings exist
    only at level 1 — the draft model drafts greedily below its top-``width``
    branch candidates, so deeper fan-out would verify tokens the drafter
    assigns near-zero probability.  All arrays are host numpy constants baked
    into the verify executable (the tree shape is engine-static, never
    call-varying):

    * ``parent [S]`` — parent node id (root's parent is itself)
    * ``depth_arr [S]`` — node depth = sequence-position offset from the
      lane frontier
    * ``anc [S, S]`` — ancestor-or-self visibility, the ``tree_mask``
      threaded through :func:`~accelerate_tpu.models.transformer
      .cached_attention` and the Pallas paged kernel
    * ``paths [W, D+1]`` — row ``b`` = the root-to-leaf node chain of branch
      ``b`` (``[0, node(b, 1), .., node(b, D)]``)
    """

    def __init__(self, width: int, depth: int) -> None:
        if width < 1 or depth < 1:
            raise ValueError(f"need width >= 1 and depth >= 1, got {width}x{depth}")
        self.width = width
        self.depth = depth
        self.nodes = 1 + width * depth
        s = self.nodes
        parent = np.zeros(s, dtype=np.int32)
        depth_arr = np.zeros(s, dtype=np.int32)
        paths = np.zeros((width, depth + 1), dtype=np.int32)
        for b in range(width):
            for lvl in range(1, depth + 1):
                i = 1 + b * depth + (lvl - 1)
                parent[i] = 0 if lvl == 1 else i - 1
                depth_arr[i] = lvl
                paths[b, lvl] = i
        anc = np.zeros((s, s), dtype=bool)
        for i in range(s):
            j = i
            anc[i, j] = True
            while j != 0:
                j = int(parent[j])
                anc[i, j] = True
        self.parent = parent
        self.depth_arr = depth_arr
        self.anc = anc
        self.paths = paths

    def __repr__(self) -> str:
        return f"TreeSpec(width={self.width}, depth={self.depth}, nodes={self.nodes})"


class NgramDrafter:
    """Host-side prompt-lookup drafting over the incremental suffix index.

    One :class:`~accelerate_tpu.serving.spec.NgramIndex` per occupied slot,
    lazily synced to the lane's emitted tokens at propose time — the index
    consumes only the *delta* since the previous cycle (O(new tokens), i.e.
    O(k) in steady state), replacing ``propose_ngram_draft``'s per-cycle
    O(context) rescan while staying token-identical to it (the equivalence
    argument lives on :class:`NgramIndex`; ``TestNgramDraft`` pins both).
    """

    kind = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1) -> None:
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self._idx: Dict[int, NgramIndex] = {}

    def propose(self, slot: int, context, k: int) -> Optional[np.ndarray]:
        """Draft ``k`` tokens for ``slot`` whose emitted tokens are
        ``context`` (a growing sequence; the index appends the unseen tail)."""
        idx = self._idx.get(slot)
        if idx is None or len(idx) > len(context):
            # new lane, or the slot was reused without retire — rebuild
            idx = self._idx[slot] = NgramIndex(self.max_ngram, self.min_ngram)
        idx.extend(context[len(idx):])
        return idx.propose(k)

    def retire(self, slot: int) -> None:
        self._idx.pop(slot, None)


class TreeDrafter:
    """On-device draft-model drafting (``model`` when ``width == 1``,
    ``tree`` when ``width > 1``): owns the jitted draft forward plus the
    engine-facing lifecycle hooks.  The engine feeds it the host context
    window arrays (:class:`~accelerate_tpu.serving.paging
    .DraftContextWindow`) and receives the ``[slots, tree.nodes]`` draft
    token array as a *device handle* — it flows straight into the tree
    verify window without a host round-trip."""

    def __init__(self, tree: TreeSpec, draft_cfg: TransformerConfig,
                 forward) -> None:
        self.tree = tree
        self.draft_cfg = draft_cfg
        self.forward = forward

    @property
    def kind(self) -> str:
        return "tree" if self.tree.width > 1 else "model"

    def propose_device(self, draft_params, ctx, length):
        """Dispatch the draft forward: ``(ctx [N, C], length [N]) ->
        tokens [N, tree.nodes]`` (async device handle)."""
        return self.forward(draft_params, ctx, length)

    def retire(self, slot: int) -> None:  # stateless — context lives host-side
        pass


# ----------------------------------------------------------------- draft model
def _slice_layer_params(params: Dict[str, Any], num_layers: int) -> Dict[str, Any]:
    """First ``num_layers`` decoder layers of a served param tree, both
    layouts: scan (``layers`` with a leading depth axis — slice axis 0) and
    per-layer (``layers_{i}`` — keep ``i < num_layers``).  Non-layer keys
    (embeddings, final norm, lm head) pass through untouched."""
    out: Dict[str, Any] = {}
    for key, val in params.items():
        if key == "layers":
            out[key] = jax.tree_util.tree_map(lambda a: a[:num_layers], val)
            continue
        m = re.fullmatch(r"layers_(\d+)", key)
        if m is None:
            out[key] = val
        elif int(m.group(1)) < num_layers:
            out[key] = val
    return out


def default_draft_layers(num_layers: int) -> int:
    """Default truncation: a quarter of the served depth, at least one layer.
    Shallow heads keep most of next-token agreement on easy tokens (the
    self-speculation observation behind early-exit drafting) while costing a
    small fraction of the verify forward."""
    return max(1, num_layers // 4)


def build_draft(cfg: TransformerConfig, params, draft_model, *,
                draft_ctx: int, depth: int,
                ) -> Tuple[TransformerConfig, Any]:
    """Resolve the engine's ``draft_model`` knob to ``(draft_cfg, host params)``.

    Three forms:

    * **int n** — *self-speculation*: the first ``n`` layers of the served
      model plus its embeddings / final norm / lm head, sliced host-side from
      the served params.  Re-sliced on every ``swap_params`` so the draft
      tracks the served weights through the front door's hot-swap discipline.
    * **str path** — a HF checkpoint dir streamed through
      :mod:`~accelerate_tpu.models.hf_compat`'s mapping one tensor at a time
      (:func:`native_key_map` built for the truncated config only maps the
      head's tensors, so deep layers are never materialized).  An optional
      ``"#n"`` suffix picks the layer count (``"ckpt/dir#4"``); default
      :func:`default_draft_layers`.
    * **(cfg, params) tuple** — explicit draft (tests, pre-built heads).

    The draft config is the served config with the truncated depth, the
    ``xla`` paged kernel (the draft runs a slab scratch cache — no pages),
    and a ``max_seq_len`` wide enough for the context window plus the chain
    rollout.  Returned params are host arrays; the engine places them
    replicated (the draft is small — sharding it would serialize its many
    tiny dispatches on cross-chip collectives).
    """
    if isinstance(draft_model, tuple):
        draft_cfg, draft_params = draft_model
        # construction / swap time, engine quiesced — not the serving loop
        draft_params = jax.device_get(draft_params)  # noqa: blocking-readback
        return draft_cfg, draft_params
    if isinstance(draft_model, bool) or not isinstance(draft_model, (int, str)):
        raise ValueError(
            f"draft_model must be int (layer count), str (checkpoint dir) or "
            f"(cfg, params), got {type(draft_model).__name__}"
        )
    min_len = draft_ctx + depth + 1
    if isinstance(draft_model, int):
        n = draft_model
        if not 1 <= n <= cfg.num_layers:
            raise ValueError(
                f"draft_model={n} layers out of range 1..{cfg.num_layers}"
            )
        draft_cfg = dataclasses.replace(
            cfg, num_layers=n, paged_kernel="xla",
            max_seq_len=max(cfg.max_seq_len, min_len),
        )
        inner = params["params"] if "params" in params else params
        sliced = _slice_layer_params(inner, n)
        # construction / swap time, engine quiesced — not the serving loop
        return draft_cfg, jax.device_get(sliced)  # noqa: blocking-readback
    path, _, suffix = draft_model.partition("#")
    from ..models.hf_compat import native_key_map
    from ..models.hf_compat import stream_mapped_tensors
    from ..utils.modeling import unflatten_tree

    base_cfg, _ = native_key_map(path)
    n = int(suffix) if suffix else default_draft_layers(base_cfg.num_layers)
    if not 1 <= n <= base_cfg.num_layers:
        raise ValueError(
            f"draft_model {draft_model!r}: {n} layers out of range "
            f"1..{base_cfg.num_layers}"
        )
    draft_cfg = dataclasses.replace(
        base_cfg, num_layers=n, paged_kernel="xla", scan_layers=False,
        max_seq_len=max(base_cfg.max_seq_len, min_len),
    )
    # a key map built for the truncated config only names the head's tensors;
    # streaming it touches one tensor at a time and never loads deep layers
    _, mapping = native_key_map(path, draft_cfg)
    flat = stream_mapped_tensors(path, mapping)
    return draft_cfg, unflatten_tree(flat)


def make_draft_forward(model: Transformer, tree: TreeSpec, ctx_len: int,
                       shardings=None):
    """One jitted draft forward: ``(params, ctx [N, C], length [N]) ->
    tokens [N, tree.nodes]`` int32 — the whole draft tree in a single
    dispatch.

    Two phases inside one executable, all on a scratch :class:`KVCache`
    created in-trace (zero persistent draft state):

    1. **context prefill** — one forward over the right-padded window;
       positions default to ``arange(C)`` and the causal mask keeps padded
       tail rows invisible.  The logits row at ``length - 1`` yields the
       top-``width`` branch candidates.  The cache index then *rewinds* to
       ``length``: the rollout below overwrites pad rows in place, so no
       pad KV is ever attended.
    2. **chain rollout** — the cache is tiled ``width`` times on the lane
       axis (lane-major, matching the candidates' row-major flatten) and
       ``depth - 1`` greedy single-token steps extend every branch in
       parallel — the branch dimension rides the batch dimension, so the
       rollout costs ``depth - 1`` tiny forwards regardless of width.

    Output layout matches :class:`TreeSpec`: column 0 is the lane's pending
    token (= ``ctx[length - 1]``, the tree root), then branch-major chains.
    Absolute rope positions inside the draft differ from the served model's
    (the window is a suffix) — harmless, rope attends to position
    *differences* and the draft's only job is ranking continuations.
    """
    from .pool import _serve_jit

    width, depth = tree.width, tree.depth
    cfg = model.config

    def draft_forward(params, ctx, length):
        n, c = ctx.shape
        length = jnp.maximum(length.astype(jnp.int32), 1)
        cache = KVCache.create(cfg, n, max_len=c + depth, per_lane_index=True)
        logits, cache = model.apply({"params": params}, ctx, cache=cache)
        last = jnp.take_along_axis(
            logits, (length - 1)[:, None, None], axis=1
        )[:, 0]                                           # [N, V]
        cand = jax.lax.top_k(last, width)[1].astype(jnp.int32)       # [N, W]
        # rewind to the valid frontier: branch steps write over pad rows
        cache = cache.replace(
            k=jnp.repeat(cache.k, width, axis=1),
            v=jnp.repeat(cache.v, width, axis=1),
            index=jnp.repeat(length, width),
        )
        toks = cand.reshape(n * width)
        chain = [toks]
        for _ in range(depth - 1):
            step_logits, cache = model.apply(
                {"params": params}, toks[:, None], cache=cache
            )
            toks = jnp.argmax(step_logits[:, 0], axis=-1).astype(jnp.int32)
            chain.append(toks)
        tree_tokens = (
            jnp.stack(chain)                              # [D, N*W]
            .reshape(depth, n, width)
            .transpose(1, 2, 0)                           # [N, W, D] branch-major
            .reshape(n, width * depth)
        )
        root = jnp.take_along_axis(ctx, (length - 1)[:, None], axis=1)
        return jnp.concatenate([root.astype(jnp.int32), tree_tokens], axis=1)

    s = shardings
    return _serve_jit(
        draft_forward,
        in_shardings=None if s is None else s.rep(3),
        out_shardings=None if s is None else s.replicated,
    )

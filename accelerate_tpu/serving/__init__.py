"""Continuous-batching serving: slot-based KV pool, in-flight admission,
chunked prefill — iteration-level scheduling (Orca; vLLM's slot reuse) kept
inside a fixed set of compiled TPU executables.  With ``paged=True`` the KV
pool becomes a refcounted page pool behind per-lane block tables
(:mod:`.paging` — PagedAttention, TPU-native).  See ``docs/usage/serving.md``.
"""

from .engine import ServingEngine
from .errors import AdmissionError, DeadlineExceeded
from .faults import FaultInjected, FaultInjector, FaultPlan
from .paging import NULL_PAGE, PageAllocator, PagedKVPool
from .pool import (
    ServeShardings,
    jit_cache_sizes,
    make_copy_chunk,
    make_copy_page,
    make_decode_window,
    make_insert,
    make_paged_decode_window,
    make_paged_prefill_chunk,
    make_paged_verify_window,
    make_prefill_chunk,
    make_promote_install,
    make_spill_extract,
    make_verify_window,
    plan_chunks,
)
from .prefix_cache import PrefixCache, PrefixNode, rolling_hash
from .router import ReplicaRouter
from .scheduler import Request, RequestState, Scheduler
from .spec import propose_ngram_draft
from .transfer import MigrationError, PageMigrator

__all__ = [
    "ServingEngine",
    "AdmissionError",
    "DeadlineExceeded",
    "FaultInjected",
    "FaultInjector",
    "FaultPlan",
    "MigrationError",
    "PageMigrator",
    "ReplicaRouter",
    "ServeShardings",
    "Request",
    "RequestState",
    "Scheduler",
    "PrefixCache",
    "PrefixNode",
    "rolling_hash",
    "NULL_PAGE",
    "PageAllocator",
    "PagedKVPool",
    "plan_chunks",
    "make_decode_window",
    "make_verify_window",
    "make_prefill_chunk",
    "make_insert",
    "make_copy_chunk",
    "make_paged_decode_window",
    "make_paged_verify_window",
    "make_paged_prefill_chunk",
    "make_copy_page",
    "make_spill_extract",
    "make_promote_install",
    "propose_ngram_draft",
    "jit_cache_sizes",
]

"""Headline benchmark: BERT-base-class training throughput per chip.

Mirrors the reference's primary target workload (BASELINE.json: BERT-base
GLUE/MRPC via ``examples/nlp_example.py`` — seq 128 classification-scale
training).  We train a BERT-base-sized (~110M param) transformer with the
framework's compiled train step (bf16, grad clip, adamw) and report
samples/sec/chip.

``vs_baseline`` compares against an A100 80GB running the same-size model in
fp16 with HF Accelerate+torch (~650 samples/s for BERT-base seq128 — the
"≥ A100 step-time" bar from BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

A100_BASELINE_SAMPLES_PER_SEC = 650.0

BATCH = 64
SEQ = 128
WARMUP = 5
STEPS = 20


def main():
    import optax

    import accelerate_tpu as at
    from accelerate_tpu.models.transformer import Transformer, TransformerConfig, lm_loss_fn

    # BERT-base geometry (110M): hidden 768, 12 layers, 12 heads, vocab 30522.
    cfg = TransformerConfig(
        vocab_size=30522,
        hidden_size=768,
        intermediate_size=3072,
        num_layers=12,
        num_heads=12,
        num_kv_heads=12,
        max_seq_len=SEQ,
    )
    model = Transformer(cfg)

    acc = at.Accelerator(mixed_precision="bf16")
    n_chips = len(jax.devices())

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (BATCH, SEQ)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:1])["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    state = acc.create_train_state(params=params, tx=optax.adamw(5e-5), seed=0)
    step = acc.compile_train_step(lm_loss_fn(model), max_grad_norm=1.0)

    batch = {"input_ids": ids}
    for _ in range(WARMUP):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    samples_per_sec = BATCH * STEPS / dt
    per_chip = samples_per_sec / n_chips
    # 6*N FLOPs per token (fwd+bwd) — standard transformer estimate.
    tflops = 6 * n_params * SEQ * samples_per_sec / 1e12

    print(
        json.dumps(
            {
                "metric": "bert_base_train_samples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(per_chip / A100_BASELINE_SAMPLES_PER_SEC, 3),
                "detail": {
                    "params": n_params,
                    "batch": BATCH,
                    "seq": SEQ,
                    "chips": n_chips,
                    "step_ms": round(1e3 * dt / STEPS, 2),
                    "model_tflops_per_sec": round(tflops, 1),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: BERT-base-class training throughput per chip.

Mirrors the reference's primary target workload (BASELINE.json: BERT-base
GLUE/MRPC via ``examples/nlp_example.py`` — seq 128 classification-scale
training).  We train a BERT-base-sized (~110M param) transformer with the
framework's compiled train step (bf16, grad clip, adamw) and report
samples/sec/chip, plus MFU against the detected chip's peak.

Baseline derivation (the ``vs_baseline`` denominator): the bar from
BASELINE.md is "≥ A100 step-time" on this workload.  A100 80GB peak is
312 TFLOP/s (fp16/bf16, dense).  BERT-base fwd+bwd costs ~6·N·S FLOPs/sample
= 6 · 110e6 · 128 ≈ 8.45e10, so the A100 roofline is ~3700 samples/s at 100%
MFU.  Eager-mode HF Accelerate + torch.cuda.amp on this class of short-seq
model sustains ~15-20% MFU in public fine-tuning benchmarks (small kernels,
no fusion, python step overhead) → 550-750 samples/s; we take 650 (≈17.6%
A100 MFU) as the reference point.  Beating it at higher MFU on a smaller
chip is the honest win condition.

Run ``python bench_inference.py`` for the big-model streaming-inference
benchmark (tokens/s, the reference ``benchmarks/big_model_inference.py``
analog), and ``python bench.py --task mrpc`` to time the actual
examples/nlp_example.py task instead of the synthetic LM proxy.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

A100_BASELINE_SAMPLES_PER_SEC = 650.0  # derivation in module docstring

# Round-5 same-session sweep on the v5e: batch 64 → 1119.9 samples/s
# (69.9% MFU), 128 → 1151.5 (71.9%), 256 → 1071.2 (66.9%).  128 amortizes
# per-step overhead without spilling; 256 loses to HBM pressure.
BATCH = 128
SEQ = 128
WARMUP = 5
STEPS = 20

# bf16 dense peak TFLOP/s by device kind (public spec sheets).  Used for MFU;
# unknown kinds fall back to None and MFU is omitted rather than guessed.
CHIP_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5e": 197.0,
    "TPU v5 lite": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6e": 918.0,
    "TPU v6 lite": 918.0,
}


def detect_peak_tflops() -> float | None:
    kind = getattr(jax.devices()[0], "device_kind", "") or ""
    for name, peak in CHIP_PEAK_TFLOPS.items():
        if kind.lower().startswith(name.lower()) or name.lower() in kind.lower():
            return peak
    return None


def bench_lm_proxy():
    """BERT-base-geometry causal-LM training step (the default headline)."""
    import optax

    import accelerate_tpu as at
    from accelerate_tpu.models.transformer import Transformer, TransformerConfig, lm_loss_fn

    # BERT-base geometry (110M): hidden 768, 12 layers, 12 heads, vocab 30522.
    cfg = TransformerConfig(
        vocab_size=30522,
        hidden_size=768,
        intermediate_size=3072,
        num_layers=12,
        num_heads=12,
        num_kv_heads=12,
        max_seq_len=SEQ,
    )
    model = Transformer(cfg)

    acc = at.Accelerator(mixed_precision="bf16")
    n_chips = len(jax.devices())

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (BATCH, SEQ)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:1])["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    state = acc.create_train_state(params=params, tx=optax.adamw(5e-5), seed=0)
    step = acc.compile_train_step(lm_loss_fn(model), max_grad_norm=1.0)

    batch = {"input_ids": ids}
    for _ in range(WARMUP):
        state, metrics = step(state, batch)
    # block_until_ready is unreliable over tunneled TPU transports; a scalar
    # D2H materialization is the portable completion barrier.
    float(metrics["loss"])

    # Fill the XLA cost table off the clock (re-lowers the captured step
    # signature): the per-step train/step_mfu gauge update inside the timed
    # loop is then a dict lookup + gauge store, nothing more.
    cost_snap = acc.analyze_costs()

    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    # Telemetry overhead A/B: the same timed loop with every instrument
    # reduced to its disabled boolean check.  The acceptance bar is <1% of
    # step time; the ratio lands in detail.telemetry.overhead_frac.
    at.telemetry.set_enabled(False)
    at.get_tracer().enabled = False
    for _ in range(2):  # re-warm: the wrapper now takes its short-circuit path
        state, metrics = step(state, batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt_off = time.perf_counter() - t0
    at.telemetry.set_enabled(True)
    at.get_tracer().enabled = True
    overhead_frac = max(0.0, dt / dt_off - 1.0) if dt_off > 0 else 0.0
    assert overhead_frac < 0.01, (
        f"telemetry overhead {overhead_frac:.2%} exceeds the 1% budget "
        f"(enabled {1e3 * dt / STEPS:.2f} ms/step vs disabled {1e3 * dt_off / STEPS:.2f})"
    )

    samples_per_sec = BATCH * STEPS / dt
    per_chip = samples_per_sec / n_chips
    # 6*N FLOPs per token (fwd+bwd) — standard transformer estimate.
    tflops = 6 * n_params * SEQ * samples_per_sec / 1e12
    peak = detect_peak_tflops()

    detail = {
        "params": n_params,
        "batch": BATCH,
        "seq": SEQ,
        "chips": n_chips,
        "step_ms": round(1e3 * dt / STEPS, 2),
        "model_tflops_per_sec": round(tflops, 1),
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "baseline": "A100-80GB fp16 eager HF Accelerate ~650 samples/s (see docstring)",
    }
    if peak is not None:
        detail["chip_peak_tflops"] = peak

    # MFU: prefer XLA's own cost model for the numerator (the compiled step's
    # actual FLOPs — fusion, remat recompute and all); the 6*N*S analytic
    # estimate is the fallback when the backend has no cost_analysis.  The
    # denominator always resolves (detect_device_peaks has a generic-CPU
    # fallback), so detail.mfu is present — finite and in (0, 1] — on every
    # platform, with mfu_source labeling how honest the number is.
    cost_entry = next(
        (v for k, v in cost_snap.items() if k.startswith("train_step/")), None
    )
    xla_flops = cost_entry.get("flops") if cost_entry else None
    peak_flops_per_s = acc.device_peaks.flops_per_s * n_chips
    if xla_flops:
        detail["mfu"] = round(min(1.0, xla_flops * STEPS / dt / peak_flops_per_s), 6)
        detail["mfu_source"] = "xla_cost_analysis"
    else:
        detail["mfu"] = round(min(1.0, tflops * 1e12 / peak_flops_per_s), 6)
        detail["mfu_source"] = "analytic_6NS"
    if cost_entry and cost_entry.get("hbm_peak_bytes"):
        detail["hbm_peak_bytes"] = cost_entry["hbm_peak_bytes"]

    # Per-phase breakdown from the unified telemetry layer (ISSUE: the bench
    # JSON carries the span rollup + step-time percentiles + compile counts).
    step_snap = acc.telemetry.get("train/step_time_s").snapshot()
    detail["telemetry"] = {
        "overhead_frac": round(overhead_frac, 5),
        "step_time_ms": {
            "p50": round(1e3 * step_snap["p50"], 3),
            "p90": round(1e3 * step_snap["p90"], 3),
            "p99": round(1e3 * step_snap["p99"], 3),
        },
        "spans": {
            name: {"count": agg["count"], "mean_ms": round(1e3 * agg["mean_s"], 3),
                   "max_ms": round(1e3 * agg["max_s"], 3)}
            for name, agg in acc.tracer.aggregate().items()
        },
        "compiles": {
            name: int(acc.telemetry.get(name).value)
            for name in (m.name for m in acc.telemetry)
            if name.startswith("compile/") and name.endswith("/count")
        },
        "tokens_per_s": round(acc.telemetry.get("train/tokens_per_s").value, 1),
    }

    print(
        json.dumps(
            {
                "metric": "bert_base_train_samples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(per_chip / A100_BASELINE_SAMPLES_PER_SEC, 3),
                "detail": detail,
            }
        )
    )


def _bench_train_config(
    metric: str,
    cfg_kwargs: dict,
    *,
    batch: int,
    accelerator_kwargs: dict,
    baseline_note: str,
    steps: int = STEPS,
    warmup: int = WARMUP,
    smoke: bool = False,
):
    """Shared runner for the big-geometry training benches (zero3 / fsdp).

    Measures samples/s(/chip) and MFU for a Transformer of the given geometry
    under the given Accelerator config.  ``smoke=True`` shrinks the geometry
    so the path is CI-testable on CPU (same code, tiny shapes).
    """
    import optax

    import accelerate_tpu as at
    from accelerate_tpu.models.transformer import Transformer, TransformerConfig, lm_loss_fn

    if smoke:
        cfg_kwargs = {
            **cfg_kwargs,
            # big enough that fp32 state spans several 1 MB chunks (the nvme
            # smoke needs a real multi-chunk stream), small enough for CI
            "vocab_size": 2048,
            "hidden_size": 128,
            "intermediate_size": 256,
            "num_layers": 2,
            "num_heads": 4,
            "num_kv_heads": 2,
            "max_seq_len": 64,
            # the pallas kernel interprets on CPU — too slow for even a smoke
            # run at seq 64; the smoke tier checks the config plumbing only
            "attention_impl": "xla",
        }
        batch, steps, warmup = 2, 2, 1
    seq = cfg_kwargs["max_seq_len"]
    cfg = TransformerConfig(scan_layers=True, remat=True, **cfg_kwargs)
    model = Transformer(cfg)

    acc = at.Accelerator(mixed_precision="bf16", **accelerator_kwargs)
    n_chips = len(jax.devices())

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    params = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), ids[:1])["params"])
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    # init straight into host memory: a device-resident fp32 copy would occupy
    # HBM through creation (the bigger-than-HBM case the zero3 config targets)
    params = at.init_params_on_host(model, ids[:1])
    state = acc.create_train_state(params=params, tx=optax.adamw(1e-4), seed=0)
    del params
    step = acc.compile_train_step(lm_loss_fn(model), max_grad_norm=1.0)

    batch_pytree = {"input_ids": ids}
    for _ in range(warmup):
        state, metrics = step(state, batch_pytree)
    float(metrics["loss"])  # D2H barrier (block_until_ready unreliable on tunnels)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_pytree)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    samples_per_sec = batch * steps / dt
    per_chip = samples_per_sec / n_chips
    tflops = 6 * n_params * seq * samples_per_sec / 1e12
    peak = detect_peak_tflops()
    detail = {
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "chips": n_chips,
        "step_ms": round(1e3 * dt / steps, 2),
        "model_tflops_per_sec": round(tflops, 2),
        "tokens_per_sec": round(samples_per_sec * seq, 1),
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "baseline": baseline_note,
        "final_loss": float(metrics["loss"]),
        "smoke": smoke,
        "remat_policy": cfg.remat_policy,
        "attention_impl": cfg.attention_impl,
    }
    if peak is not None:
        detail["chip_peak_tflops"] = peak
        detail["mfu"] = round(tflops / n_chips / peak, 4)
    # XLA cost/HBM accounting (best-effort: the zero3/accumulation paths
    # dispatch through python wrappers XLA cannot analyze — graceful absence)
    cost_entry = next(
        (v for k, v in acc.analyze_costs().items() if k.startswith("train_step/")),
        None,
    )
    if cost_entry:
        if cost_entry.get("hbm_peak_bytes"):
            detail["hbm_peak_bytes"] = cost_entry["hbm_peak_bytes"]
        if cost_entry.get("flops"):
            detail["mfu"] = round(
                min(1.0, cost_entry["flops"] * steps / dt
                    / (acc.device_peaks.flops_per_s * n_chips)),
                6,
            )
            detail["mfu_source"] = "xla_cost_analysis"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(per_chip, 3),
                "unit": "samples/s/chip",
                # no published reference throughput exists for these configs
                # (BASELINE.md: "functional parity" / convergence targets);
                # report MFU as the defensible number and leave vs_baseline
                # as achieved-MFU so the field stays meaningful, labeled.
                "vs_baseline": detail.get("mfu"),
                "detail": detail,
            }
        )
    )


def bench_zero3(smoke: bool = False, batch: int = 4, chunk_mb: int = -1, overlap: int = 1,
                offload_device: str = "cpu", **cfg_overrides):
    """GPT-2-XL geometry (1.5B), ZeRO-3 + host optimizer offload — the
    BASELINE.md 'DeepSpeed ZeRO-3 plugin equivalent' config.  The fp32 adam
    moments (~12 GB) live in host memory and stream to HBM only on update
    steps; params stay sharded in HBM.  ``offload_device="nvme"`` runs the
    ZeRO-Infinity-style disk tier instead (mmap'd chunk files under
    ./bench_nvme_tier/, page cache doing the short-term caching)."""
    import accelerate_tpu as at

    nvme_kwargs = {}
    if offload_device == "nvme":
        import os as _os
        import shutil as _shutil

        path = _os.path.abspath("./bench_nvme_tier")
        _shutil.rmtree(path, ignore_errors=True)  # stale chunks from other geometries
        nvme_kwargs["nvme_path"] = path
        if smoke:
            chunk_mb = 1  # tiny smoke state must still span several chunks

    _bench_train_config(
        f"gpt2xl_zero3_offload{'_nvme' if offload_device == 'nvme' else ''}_samples_per_sec_per_chip",
        {
            # overrides may replace any default (e.g. a smaller geometry for
            # the tunnel-bound nvme-tier proof run) — dict-merge, not
            # keyword-collide.  Full remat stays the default: activation
            # savings matter more than recompute FLOPs when the whole budget
            # is params+grads+chunk streams, and step time is dominated by
            # the optimizer-state stream anyway.
            **dict(
                vocab_size=50257,
                hidden_size=1600,
                intermediate_size=6400,
                num_layers=48,
                num_heads=25,
                num_kv_heads=25,
                max_seq_len=1024,
            ),
            **cfg_overrides,
        },
        batch=batch,
        accelerator_kwargs=dict(
            deepspeed_plugin=at.ZeroPlugin(
                zero_stage=3,
                offload_optimizer_device=offload_device,
                **nvme_kwargs,
                # adaptive chunk sizing from free HBM (utils/chunked_update.
                # auto_chunk_bytes): resident working set + a 10% margin leave
                # ~6 GB on a 16 GB chip for the in-flight window at ~4x
                # transients per chunk.  The round-5 A/B measured overlap=2
                # 11% FASTER than serialized at an explicit 1 GB chunk size
                # (post-donation-fix; BENCH_NOTES.md round-5) — pass
                # --overlap 2 --chunk-mb 1024 to take it; the default stays
                # serialized+adaptive for rigs without the headroom.
                offload_update_chunk_mb=chunk_mb,
                offload_update_overlap=overlap,
            ),
            mesh={"fsdp": -1},
            # NB: accumulation would amortize the per-step optimizer stream,
            # but a separate accumulation buffer adds a third params-sized
            # bf16 tensor (params + buffer + backward grads) — at 2.1B params
            # that exceeds a single 16 GB chip.  accum=1 reuses the grads as
            # the buffer; multi-chip fsdp shards all three.
        ),
        baseline_note="BASELINE.md: GPT-2-XL ZeRO-3 + host offload — functional parity target; vs_baseline reports MFU",
        smoke=smoke,
    )


def bench_fsdp(smoke: bool = False, batch: int = 3, grad_wire: str = "bf16", **cfg_overrides):
    """Llama geometry full-shard FSDP at the largest single-chip-feasible
    scale (TinyLlama-1.1B-class: hidden 2048, GQA 32/4, SwiGLU 5632, seq 2048,
    16 layers ≈ 0.84B so fp32 params+grads+adam ≈ 13.5 GB fit v5e HBM) — the
    BASELINE.md 'Llama-2-7B full-shard FSDP' config scaled to the bench rig;
    on a pod mesh the same code spans chips.

    Defaults are the measured-best from the round-4 sweep (BENCH_NOTES.md):
    batch 3, full remat, XLA attention, bf16 gradient carry.  The step is
    attention-bandwidth-bound at this seq-2048 geometry: every alternative
    measured — dots_saveable and proj_saveable remat (less recompute, more
    HBM traffic), the in-tree pallas flash, splash attention, stock pallas
    flash, and causal-blocked XLA attention — came out equal or slower on
    v5e, so the remaining MFU headroom is an attention kernel faster than
    XLA's fused path, which none of the five candidates is at GQA 32:4 /
    head-dim 64.  Use --remat-policy/--attention-impl/--grad-wire to
    reproduce the sweep."""
    import accelerate_tpu as at

    _bench_train_config(
        "llama_fsdp_full_shard_samples_per_sec_per_chip",
        dict(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_layers=16,
            num_heads=32,
            num_kv_heads=4,
            max_seq_len=2048,
            # full remat measured FASTER than proj_saveable/dots_saveable here
            # (saving activations costs more HBM bandwidth than the recompute
            # costs FLOPs on this attention-bound step) — see BENCH_NOTES.md
            **{"remat_policy": "full", **cfg_overrides},
        ),
        batch=batch,
        accelerator_kwargs=dict(
            fsdp_plugin=at.FullyShardedDataParallelPlugin(sharding_strategy="FULL_SHARD"),
            mesh={"fsdp": -1},
            # bf16 gradient carry (the DDP bf16 comm-hook analog, reference
            # utils/dataclasses.py:105-199): halves the live gradient tree
            # between backward and apply — ~1.7 GB at this geometry, the
            # margin that lets proj_saveable fit next to the fp32 adam state.
            # Clip/norm math stays fp32; moments stay fp32.
            kwargs_handlers=(
                [at.CollectiveKwargs(grad_reduce_dtype="bf16")] if grad_wire == "bf16" else []
            ),
        ),
        baseline_note="BASELINE.md: Llama full-shard FSDP MFU target; vs_baseline reports MFU",
        smoke=smoke,
    )


def bench_longseq(
    smoke: bool = False, batch: int = 1, seq: int = 16384,
    attention_impl: str = "pallas", **cfg_overrides,
):
    """Long-context single-chip training (SURVEY §5.7's workload class): the
    llama-geometry model at S=16k+, batch 1, where attention cost is O(S^2)
    and kernels with O(S) memory (in-tree pallas flash / blocked-causal XLA)
    are mandatory — the regime the short-seq fsdp bench showed them losing in
    is inverted here.  ``--attention-impl`` sweeps the kernels; MFU accounts
    the quadratic attention FLOPs explicitly (6*N*S undercounts them badly at
    this length).
    """
    import optax

    import accelerate_tpu as at
    from accelerate_tpu.models.transformer import Transformer, TransformerConfig, lm_loss_fn

    geometry = dict(
        vocab_size=32000,
        hidden_size=2048,
        intermediate_size=5632,
        num_layers=16,
        num_heads=32,
        num_kv_heads=4,
    )
    if smoke:
        seq, batch = 512, 1
        geometry = dict(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2,
        )
    cfg = TransformerConfig(
        max_seq_len=seq,
        scan_layers=True,
        remat=True,
        # the pallas kernel interprets on CPU — smoke checks plumbing only
        attention_impl=attention_impl if not smoke else "xla",
        **{
            **geometry,
            "remat_policy": "full",  # overridable via --remat-policy
            **cfg_overrides,
        },
    )
    model = Transformer(cfg)
    at.AcceleratorState._reset_state(reset_partial_state=True)
    at.GradientState._reset_state()
    acc = at.Accelerator(mixed_precision="bf16")

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    abstract = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), ids[:1])["params"])
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(abstract))
    params = at.init_params_on_host(model, ids[:1])
    state = acc.create_train_state(params=params, tx=optax.adamw(1e-4), seed=0)
    del params
    step = acc.compile_train_step(lm_loss_fn(model), max_grad_norm=1.0)

    batch_pytree = {"input_ids": ids}
    warmup, steps = (1, 2) if smoke else (2, 5)
    for _ in range(warmup):
        state, metrics = step(state, batch_pytree)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_pytree)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    # fwd+bwd FLOPs/sample: 6*N*S for the matmul stack + the causal attention
    # quadratic term (score+PV, fwd ~2*S^2*d*Hq causal-halved, train 3x)
    attn_flops = cfg.num_layers * 6 * seq * seq * cfg.resolved_head_dim * cfg.num_heads
    flops_per_sample = 6 * n_params * seq + attn_flops
    tflops = flops_per_sample * batch * steps / dt / 1e12
    n_chips = len(jax.devices())
    peak = detect_peak_tflops()
    detail = {
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "attention_impl": cfg.attention_impl,
        "step_ms": round(1e3 * dt / steps, 2),
        "attn_flops_frac": round(attn_flops / flops_per_sample, 3),
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "final_loss": float(metrics["loss"]),
        "smoke": smoke,
    }
    if peak:
        detail["chip_peak_tflops"] = peak
        detail["mfu"] = round(tflops / n_chips / peak, 4)
    cost_entry = next(
        (v for k, v in acc.analyze_costs().items() if k.startswith("train_step/")),
        None,
    )
    if cost_entry:
        if cost_entry.get("hbm_peak_bytes"):
            detail["hbm_peak_bytes"] = cost_entry["hbm_peak_bytes"]
        if cost_entry.get("flops"):
            detail["mfu"] = round(
                min(1.0, cost_entry["flops"] * steps / dt
                    / (acc.device_peaks.flops_per_s * n_chips)),
                6,
            )
            detail["mfu_source"] = "xla_cost_analysis"
    print(
        json.dumps(
            {
                "metric": "longseq_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec / n_chips, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": detail.get("mfu"),
                "detail": detail,
            }
        )
    )


def bench_cv(smoke: bool = False, batch: int = 128):
    """ResNet-50 bf16 training throughput — the BASELINE.md
    ``examples/cv_example.py`` row at the reference geometry (224x224,
    1000 classes; the reference fine-tunes a timm ResNet-50 on pets).

    Synthetic NHWC data (zero egress), real model, full compiled train step
    (bf16 policy, adamw, clip).  MFU accounts conv+GEMM FLOPs analytically
    (``resnet_flops_per_image``) x3 for fwd+bwd, matching the LM bench's
    6*N*S convention.
    """
    import optax

    import accelerate_tpu as at
    from accelerate_tpu.models.resnet import resnet50, resnet_flops_per_image

    image_size = 64 if smoke else 224
    if smoke:
        batch = 8
    model = resnet50(num_classes=1000)
    flops_per_image = 3 * resnet_flops_per_image(model, image_size)

    at.AcceleratorState._reset_state(reset_partial_state=True)
    at.GradientState._reset_state()
    acc = at.Accelerator(mixed_precision="bf16")
    rng = np.random.default_rng(0)
    images = rng.normal(size=(batch, image_size, image_size, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, (batch,)).astype(np.int32)
    batch_data = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}

    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, image_size, image_size, 3)))["params"]
    state = acc.create_train_state(params=params, tx=optax.adamw(1e-3), seed=0)

    def loss_fn(p, b, rng=None):
        import optax as _optax

        logits = model.apply({"params": p}, b["image"])
        return _optax.softmax_cross_entropy_with_integer_labels(logits, b["label"]).mean()

    step = acc.compile_train_step(loss_fn, max_grad_norm=1.0)
    warmup, steps = (1, 3) if smoke else (WARMUP, STEPS)
    for _ in range(warmup):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])  # D2H completion barrier (tunnel-safe)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    per_chip = batch * steps / dt / n_chips
    detail = {
        "model": "resnet50-groupnorm",
        "image_size": image_size,
        "batch": batch,
        "chips": n_chips,
        "step_ms": round(1e3 * dt / steps, 2),
        "final_loss": float(metrics["loss"]),
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "train_flops_per_image_g": round(flops_per_image / 1e9, 2),
    }
    peak = detect_peak_tflops()
    if peak:
        detail["chip_peak_tflops"] = peak
    # MFU with the honest-FLOPs convention (models/resnet.py): the analytic
    # conv+GEMM count is the *fallback* numerator; XLA's cost model — which
    # sees the fused program the chip actually runs — takes precedence.
    cost_entry = next(
        (v for k, v in acc.analyze_costs().items() if k.startswith("train_step/")),
        None,
    )
    peak_flops_per_s = acc.device_peaks.flops_per_s * n_chips
    xla_flops = cost_entry.get("flops") if cost_entry else None
    if xla_flops:
        detail["mfu"] = round(min(1.0, xla_flops * steps / dt / peak_flops_per_s), 6)
        detail["mfu_source"] = "xla_cost_analysis"
    else:
        detail["mfu"] = round(
            min(1.0, per_chip * n_chips * flops_per_image / peak_flops_per_s), 6
        )
        detail["mfu_source"] = "analytic_resnet_flops"
    if cost_entry and cost_entry.get("hbm_peak_bytes"):
        detail["hbm_peak_bytes"] = cost_entry["hbm_peak_bytes"]
    print(
        json.dumps(
            {
                "metric": "resnet50_train_samples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                # public reference point: A100-80GB ResNet-50 fp16/AMP training
                # sustains ~1200-1500 img/s in eager torch (MLPerf-tuned rigs
                # reach ~2900); we take 1350 as the eager-HF-stack analog of
                # the LM bench's 650 samples/s convention.
                "vs_baseline": round(per_chip / 1350.0, 3),
                "detail": detail,
            }
        )
    )


def bench_mrpc(epochs: int = 3):
    """Time the real examples/nlp_example.py task (text-pair classification on
    the checked-in dataset) — the literal BASELINE.md workload."""
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples"))
    import optax

    import accelerate_tpu as at
    from nlp_example import MAX_LEN, EncoderClassifier, get_dataloaders

    acc = at.Accelerator(mixed_precision="bf16")
    train_dl, eval_dl = get_dataloaders(acc, batch_size=32)
    model = EncoderClassifier()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, MAX_LEN), jnp.int32))["params"]
    state = acc.create_train_state(params=params, tx=optax.adamw(2e-4), seed=0)

    def loss_fn(p, batch, rng=None):
        logits = model.apply({"params": p}, batch["input_ids"])
        import optax as _optax

        return _optax.softmax_cross_entropy(logits, jax.nn.one_hot(batch["labels"], 2)).mean()

    step = acc.compile_train_step(loss_fn, max_grad_norm=1.0)
    # warmup epoch compiles
    for batch in train_dl:
        state, metrics = step(state, batch)
    float(metrics["loss"])  # D2H barrier (block_until_ready unreliable on tunnels)

    n_samples = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        for batch in train_dl:
            state, metrics = step(state, batch)
            n_samples += batch["input_ids"].shape[0]
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    per_chip = n_samples / dt / len(jax.devices())
    print(
        json.dumps(
            {
                "metric": "mrpc_train_samples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(per_chip / A100_BASELINE_SAMPLES_PER_SEC, 3),
                "detail": {"epochs": epochs, "samples": n_samples, "final_loss": float(metrics["loss"])},
            }
        )
    )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--task", choices=["lm", "mrpc", "zero3", "fsdp", "cv", "longseq"], default="lm")
    parser.add_argument("--seq", type=int, default=None,
                        help="longseq task: sequence length (default 16384)")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny-geometry run of the same code path (CI)")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--remat-policy", default=None,
                        choices=["full", "nothing_saveable", "dots_saveable",
                                 "dots_with_no_batch_dims_saveable", "proj_saveable"],
                        help="override the task's remat policy (fsdp default: full)")
    parser.add_argument("--attention-impl", default=None,
                        choices=["xla", "blocked", "pallas"],
                        help="override the task's attention kernel (default: xla)")
    parser.add_argument("--grad-wire", default=None, choices=["bf16", "fp32"],
                        help="fsdp task: gradient carry dtype (default bf16)")
    parser.add_argument("--chunk-mb", type=int, default=None,
                        help="zero3 task: offload chunk size in MB (-1 = adaptive)")
    parser.add_argument("--overlap", type=int, default=None,
                        help="zero3 task: in-flight chunk window (1 = serialized)")
    parser.add_argument("--offload-device", default=None, choices=["cpu", "nvme"],
                        help="zero3 task: optimizer-state tier (nvme = disk mmap)")
    args = parser.parse_args()
    overrides = {}
    if args.batch:
        overrides["batch"] = args.batch
    if args.remat_policy:
        overrides["remat_policy"] = args.remat_policy
    if args.attention_impl:
        overrides["attention_impl"] = args.attention_impl
    if args.grad_wire and args.task != "fsdp":
        parser.error("--grad-wire only applies to --task fsdp")
    if (args.chunk_mb is not None or args.overlap is not None) and args.task != "zero3":
        parser.error("--chunk-mb/--overlap only apply to --task zero3")
    if args.seq is not None and args.task != "longseq":
        parser.error("--seq only applies to --task longseq")
    if args.offload_device is not None and args.task != "zero3":
        parser.error("--offload-device only applies to --task zero3")
    if overrides and args.task in ("lm", "mrpc"):
        parser.error(
            "--batch/--remat-policy/--attention-impl only apply to the "
            "zero3/fsdp/longseq tasks (cv: --batch only), not "
            f"--task {args.task}"
        )
    if args.task == "mrpc":
        bench_mrpc()
    elif args.task == "cv":
        if set(overrides) - {"batch"}:
            parser.error("--task cv accepts only --batch of the overrides")
        bench_cv(smoke=args.smoke, **overrides)
    elif args.task == "longseq":
        if args.seq is not None:
            overrides["seq"] = args.seq
        bench_longseq(smoke=args.smoke, **overrides)
    elif args.task == "zero3":
        if args.chunk_mb is not None:
            overrides["chunk_mb"] = args.chunk_mb
        if args.overlap is not None:
            overrides["overlap"] = args.overlap
        if args.offload_device is not None:
            overrides["offload_device"] = args.offload_device
        bench_zero3(smoke=args.smoke, **overrides)
    elif args.task == "fsdp":
        if args.grad_wire:
            overrides["grad_wire"] = args.grad_wire
        bench_fsdp(smoke=args.smoke, **overrides)
    else:
        bench_lm_proxy()


if __name__ == "__main__":
    main()

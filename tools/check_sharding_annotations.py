#!/usr/bin/env python
"""Lint: every jit in the serving package threads explicit shardings.

Serving executables are compiled once and reused across thousands of steps;
a ``jax.jit``/``pjit`` without ``in_shardings``/``out_shardings`` leaves
placement to GSPMD's propagation pass, which is free to pick a layout that
silently diverges from the head-sharded KV pool (a resharding collective in
the decode loop, or worse, a replicated pool that quietly undoes the tp
memory win).  So inside ``accelerate_tpu/serving/`` every ``jax.jit`` /
``jax.pjit`` / bare ``jit(...)`` call must pass at least one of the
``in_shardings`` / ``out_shardings`` keywords — in practice by going through
``pool._serve_jit``, which threads both or documents why not.

A call that is intentionally unconstrained carries a ``# noqa: sharding``
pragma on its line (with a reason, by convention).  Decorator usage
(``@jax.jit``) is a call node too and is checked the same way.

Exit status 1 with one ``path:line`` diagnostic per violation; 0 when clean.
Wired into ``make quality``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "accelerate_tpu" / "serving"
JIT_NAMES = ("jit", "pjit")
SHARDING_KWARGS = ("in_shardings", "out_shardings")
PRAGMA = "noqa: sharding"


def _is_jit_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):  # jax.jit / jax.experimental.pjit.pjit
        return func.attr in JIT_NAMES
    if isinstance(func, ast.Name):  # from jax import jit
        return func.id in JIT_NAMES
    return False


def unannotated_jits(path: Path) -> list:
    """``lineno`` for every jit call missing explicit sharding keywords."""
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:  # quality target also runs compileall; be loud
        print(f"{path}:{exc.lineno}: syntax error: {exc.msg}")
        sys.exit(1)
    src_lines = source.splitlines()
    found = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and _is_jit_call(node)
            and not any(kw.arg in SHARDING_KWARGS for kw in node.keywords)
            and PRAGMA not in src_lines[node.lineno - 1]
        ):
            found.append(node.lineno)
    return found


def main() -> int:
    violations = []
    for path in sorted(PACKAGE.rglob("*.py")):
        for lineno in unannotated_jits(path):
            rel = path.relative_to(REPO_ROOT)
            violations.append(
                f"{rel}:{lineno}: jit without in_shardings/out_shardings — "
                f"route it through pool._serve_jit or add '# {PRAGMA}' with "
                "a reason"
            )
    for v in violations:
        print(v)
    if violations:
        print(f"check_sharding_annotations: {len(violations)} violation(s)")
        return 1
    print("check_sharding_annotations: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Lint: no ``functools.lru_cache`` / ``functools.cache`` on instance methods.

An lru_cache on a method keys its cache on ``self``: every instance gets its
own entry, the cache keeps each instance alive for the lifetime of the class
(a memory leak), and per-instance state silently defeats the dedupe the cache
was meant to provide — exactly the bug class fixed in
``MultiProcessAdapter.warning_once`` (the re-warning-per-adapter-instance
leak; see ``accelerate_tpu/logging.py``).  Module-level functions are fine;
methods must use an explicit container keyed on what they actually mean to
dedupe (a module-level set/dict, or ``functools.cached_property`` for a
compute-once attribute).

Exempt:

* ``accelerate_tpu/test_utils/`` and ``accelerate_tpu/commands/`` (matching
  ``check_no_bare_print.py`` — short-lived CLI/test objects can't leak long);
* ``@staticmethod`` methods (no ``self``/``cls`` in the key — an ordinary
  cached function that happens to live in a class namespace);
* lines carrying a ``# noqa: method-lru-cache`` pragma.

Exit status 1 with one ``path:line`` diagnostic per violation; 0 when clean.
Wired into ``make quality``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "accelerate_tpu"
EXEMPT_DIRS = ("test_utils", "commands")
BANNED = ("lru_cache", "cache")
PRAGMA = "noqa: method-lru-cache"


def _deco_name(deco: ast.expr) -> str:
    """Dotted name of a decorator, unwrapping a call: ``functools.lru_cache``,
    ``lru_cache``, ``staticmethod`` ..."""
    target = deco.func if isinstance(deco, ast.Call) else deco
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
        return f"{target.value.id}.{target.attr}"
    return ""


def _is_banned(deco: ast.expr) -> bool:
    name = _deco_name(deco)
    return name in BANNED or name in tuple(f"functools.{b}" for b in BANNED)


def check_file(path: Path) -> list:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:  # quality target also runs compileall; be loud
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]
    src_lines = source.splitlines()
    violations = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            deco_names = [_deco_name(d) for d in fn.decorator_list]
            if "staticmethod" in deco_names:
                continue
            args = fn.args.posonlyargs + fn.args.args
            if not args or args[0].arg not in ("self", "cls"):
                continue
            for deco in fn.decorator_list:
                if not _is_banned(deco):
                    continue
                if PRAGMA in src_lines[deco.lineno - 1]:
                    continue
                rel = path.relative_to(REPO_ROOT)
                violations.append(
                    f"{rel}:{deco.lineno}: functools.{_deco_name(deco).split('.')[-1]} "
                    f"on method {cls.name}.{fn.name} — the cache keys on "
                    f"{args[0].arg!r}, leaking every instance and deduping "
                    "per-instance; use a module-level container or cached_property"
                )
    return violations


def main() -> int:
    violations = []
    for path in sorted(PACKAGE.rglob("*.py")):
        rel_parts = path.relative_to(PACKAGE).parts
        if rel_parts[0] in EXEMPT_DIRS or path.name == "__main__.py":
            continue
        violations.extend(check_file(path))
    for v in violations:
        print(v)
    if violations:
        print(f"check_no_method_lru_cache: {len(violations)} violation(s)")
        return 1
    print("check_no_method_lru_cache: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

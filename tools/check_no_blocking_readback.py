#!/usr/bin/env python
"""Lint: no blocking device->host readback in the serving hot path.

The pipelined serve loop (``ServingEngine(async_depth=1)``) works because
dispatching window N+1 never waits on window N — every device->host
materialization is funneled through ``serving/readback.py``'s ``fetch``,
drained at the one point the engine has decided to block.  A stray
``jax.device_get`` (or ``.block_until_ready()``) anywhere else in
``accelerate_tpu/serving/`` silently re-serializes the pipeline: the loop
still produces identical tokens, just without the overlap, which is exactly
the kind of regression that survives every correctness test.

Flags, in any ``accelerate_tpu/serving/*.py``:

* calls to ``device_get`` (``jax.device_get``, bare ``device_get``, or any
  dotted path ending in it);
* calls to / references of ``block_until_ready``.

Exempt:

* ``serving/readback.py`` — the one sanctioned blocking transfer lives
  there;
* lines carrying a ``# noqa: readback`` pragma (for a deliberate sync a
  comment must justify).

Exit status 1 with one ``path:line`` diagnostic per violation; 0 when clean.
Wired into ``make quality``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
SERVING = REPO_ROOT / "accelerate_tpu" / "serving"
EXEMPT_FILES = ("readback.py",)
PRAGMA = "noqa: readback"
BLOCKING_NAMES = ("device_get", "block_until_ready")


def _name_of(node: ast.AST) -> str:
    """Trailing identifier of a Name / dotted Attribute, '' otherwise."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def check_file(path: Path) -> list:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:  # quality target also runs compileall; be loud
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]
    src_lines = source.splitlines()
    violations = []
    for node in ast.walk(tree):
        # flag the attribute access itself, not just calls: passing
        # ``arr.block_until_ready`` around blocks just as hard when invoked
        if isinstance(node, ast.Call):
            name = _name_of(node.func)
        elif isinstance(node, ast.Attribute):
            name = node.attr
        else:
            continue
        if name not in BLOCKING_NAMES:
            continue
        if PRAGMA in src_lines[node.lineno - 1]:
            continue
        rel = path.relative_to(REPO_ROOT)
        violations.append(
            f"{rel}:{node.lineno}: blocking readback ({name}) in the serving "
            "hot path — route it through serving/readback.fetch (or justify "
            "with '# noqa: readback')"
        )
    # one diagnostic per line: a Call and its Attribute func both match
    return sorted(set(violations))


def main() -> int:
    violations = []
    for path in sorted(SERVING.rglob("*.py")):
        if path.name in EXEMPT_FILES:
            continue
        violations.extend(check_file(path))
    for v in violations:
        print(v)
    if violations:
        print(f"check_no_blocking_readback: {len(violations)} violation(s)")
        return 1
    print("check_no_blocking_readback: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

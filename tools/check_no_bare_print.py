#!/usr/bin/env python
"""Lint: no bare ``print(`` in library code.

Library output must go through ``accelerate_tpu.logging.get_logger`` (rank-
aware, level-filtered, dedupe-capable) or ``PartialState.print`` (the
deliberate main-process print channel) — a stray ``print`` in the train or
serve path emits once per host process and cannot be silenced.

Exempt:

* ``accelerate_tpu/test_utils/`` and ``accelerate_tpu/commands/`` (CLI +
  test harness surfaces print by design);
* any ``__main__.py``;
* code inside a ``main`` / ``_main`` function or an
  ``if __name__ == "__main__":`` block (script entry points);
* lines carrying a ``# noqa: bare-print`` pragma (e.g. ``PartialState.print``
  itself).

Exit status 1 with one ``path:line`` diagnostic per violation; 0 when clean.
Wired into ``make quality``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "accelerate_tpu"
EXEMPT_DIRS = ("test_utils", "commands")
ENTRY_FUNCS = ("main", "_main")
PRAGMA = "noqa: bare-print"


def _exempt_lines(tree: ast.Module) -> set:
    """Line ranges inside entry-point functions / __main__ guards."""
    lines: set = set()

    def mark(node: ast.AST) -> None:
        end = getattr(node, "end_lineno", node.lineno)
        lines.update(range(node.lineno, end + 1))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in ENTRY_FUNCS:
                mark(node)
        elif isinstance(node, ast.If):
            # if __name__ == "__main__":  (either comparison order)
            test = node.test
            if (
                isinstance(test, ast.Compare)
                and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Eq)
            ):
                parts = [test.left] + list(test.comparators)
                names = [p.id for p in parts if isinstance(p, ast.Name)]
                consts = [p.value for p in parts if isinstance(p, ast.Constant)]
                if "__name__" in names and "__main__" in consts:
                    mark(node)
    return lines


def check_file(path: Path) -> list:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:  # quality target also runs compileall; be loud
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]
    exempt = _exempt_lines(tree)
    src_lines = source.splitlines()
    violations = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and node.lineno not in exempt
            and PRAGMA not in src_lines[node.lineno - 1]
        ):
            rel = path.relative_to(REPO_ROOT)
            violations.append(
                f"{rel}:{node.lineno}: bare print() in library code — use "
                "get_logger(__name__) or PartialState.print"
            )
    return violations


def main() -> int:
    violations = []
    for path in sorted(PACKAGE.rglob("*.py")):
        rel_parts = path.relative_to(PACKAGE).parts
        if rel_parts[0] in EXEMPT_DIRS or path.name == "__main__.py":
            continue
        violations.extend(check_file(path))
    for v in violations:
        print(v)
    if violations:
        print(f"check_no_bare_print: {len(violations)} violation(s)")
        return 1
    print("check_no_bare_print: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

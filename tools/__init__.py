"""Developer tooling for the accelerate_tpu repo (lint framework lives in
``tools/atpu_lint``; run it with ``python -m tools.atpu_lint``)."""

#!/usr/bin/env python
"""Lint: every metric name registered in library code is documented.

Any literal metric name passed to ``registry.counter(...)``,
``registry.gauge(...)`` or ``registry.histogram(...)`` inside
``accelerate_tpu/`` must appear verbatim in ``docs/usage/observability.md``
— the doc is the operator-facing contract for what a ``/metrics`` scrape or
a JSONL metrics file can contain, and an undocumented gauge is invisible to
whoever has to build the dashboard.

Only string-literal first arguments are checked; names built with f-strings
or variables (e.g. the per-executable ``cost/<name>/...`` gauges) are
dynamic families, documented as patterns, and skipped here.  Calls carrying
a ``# noqa: metric-docs`` pragma on their line are exempt.

Exit status 1 with one ``path:line: name`` diagnostic per violation; 0 when
clean.  Wired into ``make quality``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "accelerate_tpu"
DOC = REPO_ROOT / "docs" / "usage" / "observability.md"
FACTORIES = ("counter", "gauge", "histogram")
PRAGMA = "noqa: metric-docs"


def metric_literals(path: Path) -> list:
    """``(lineno, kind, name)`` for every literal-name metric registration."""
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:  # quality target also runs compileall; be loud
        print(f"{path}:{exc.lineno}: syntax error: {exc.msg}")
        sys.exit(1)
    src_lines = source.splitlines()
    found = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in FACTORIES
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and PRAGMA not in src_lines[node.lineno - 1]
        ):
            found.append((node.lineno, node.func.attr, node.args[0].value))
    return found


def main() -> int:
    if not DOC.exists():
        print(f"check_metric_docs: missing {DOC.relative_to(REPO_ROOT)}")
        return 1
    doc_text = DOC.read_text()
    violations = []
    for path in sorted(PACKAGE.rglob("*.py")):
        for lineno, kind, name in metric_literals(path):
            if name not in doc_text:
                rel = path.relative_to(REPO_ROOT)
                violations.append(
                    f"{rel}:{lineno}: {kind} '{name}' is not documented in "
                    f"{DOC.relative_to(REPO_ROOT)}"
                )
    for v in violations:
        print(v)
    if violations:
        print(f"check_metric_docs: {len(violations)} violation(s)")
        return 1
    print("check_metric_docs: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Unified ``# noqa: <rule-id>[,<rule-id>]`` handling.

One dialect for every rule: a diagnostic on line N is suppressed when line N
carries a ``# noqa:`` pragma naming the diagnostic's rule id.  Multiple ids
are comma-separated; anything after the first whitespace inside an id token
is commentary (``# noqa: sharding-annotations (single-chip)``).  Foreign
codes (flake8's ``E402``, ``N802``, ...) are ignored — they neither suppress
atpu-lint rules nor warn.  A bare ``# noqa`` with no code list is likewise
ignored: blanket suppression hides too much for rules that guard perf
invariants, so atpu-lint requires the rule id to be spelled out.

Migration shim: before the framework existed, the single-rule scripts in
``tools/`` each grew their own pragma dialect — ``# noqa: readback`` and
``# noqa: sharding``.  Those legacy bare forms still suppress their rule for
one release, but the runner emits a warning (not a failure) steering the
author to the canonical rule id.
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

__all__ = ["LEGACY_ALIASES", "parse_noqa", "file_noqa_map"]

# legacy bare form -> canonical rule id (warn-but-honor for one release)
LEGACY_ALIASES = {
    "readback": "blocking-readback",
    "sharding": "sharding-annotations",
}

_NOQA_RE = re.compile(r"#\s*noqa\s*:\s*(?P<codes>[^#]*)", re.IGNORECASE)
_ID_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]*$")


def parse_noqa(line: str) -> Tuple[Set[str], List[str]]:
    """Rule ids suppressed by ``line``'s pragma (canonical form) plus any
    legacy-form ids that were honored via :data:`LEGACY_ALIASES`."""
    ids: Set[str] = set()
    legacy: List[str] = []
    for m in _NOQA_RE.finditer(line):
        for token in m.group("codes").split(","):
            word = token.strip().split(" ")[0].split("\t")[0]
            if not word or not _ID_RE.match(word):
                continue
            if word in LEGACY_ALIASES:
                ids.add(LEGACY_ALIASES[word])
                legacy.append(word)
            else:
                ids.add(word)
    return ids, legacy


def file_noqa_map(src: str) -> Tuple[Dict[int, Set[str]], Dict[int, List[str]]]:
    """Per-line suppression map for a whole file.

    Returns ``(suppressions, legacy_uses)``: line number (1-based) -> set of
    suppressed rule ids, and line number -> legacy bare forms found there.
    """
    suppress: Dict[int, Set[str]] = {}
    legacy_uses: Dict[int, List[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        if "noqa" not in line:
            continue
        ids, legacy = parse_noqa(line)
        if ids:
            suppress[i] = ids
        if legacy:
            legacy_uses[i] = legacy
    return suppress, legacy_uses

"""Committed baseline for grandfathered findings.

A baseline entry suppresses one diagnostic by fingerprint (rule id + path +
stripped source line, so line-number churn doesn't invalidate it).  The
intended lifecycle: a new rule lands with real pre-existing findings, they
are written to the baseline with ``--write-baseline`` (every entry carries a
``note`` — seeded entries must say what tracks the cleanup), and the count
only ever goes down.  The default run loads ``tools/atpu_lint/baseline.json``
when it exists; the repo's checked-in baseline is empty.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List

from .core import Diagnostic

__all__ = ["DEFAULT_BASELINE", "load_baseline", "write_baseline"]

DEFAULT_BASELINE = "tools/atpu_lint/baseline.json"
_VERSION = 1


def load_baseline(path: Path) -> Dict[str, dict]:
    """fingerprint -> entry dict.  Raises ``ValueError`` on a malformed or
    future-versioned file (a silently ignored baseline would unsuppress or
    oversuppress everything)."""
    data = json.loads(path.read_text())
    if not isinstance(data, dict) or data.get("version") != _VERSION:
        raise ValueError(f"unsupported baseline format in {path}")
    entries = data.get("entries", {})
    if not isinstance(entries, dict):
        raise ValueError(f"malformed baseline entries in {path}")
    return entries


def write_baseline(path: Path, diagnostics: Iterable[Diagnostic],
                   note: str = "TODO: triage (seeded by --write-baseline)") -> int:
    """Serialize ``diagnostics`` as the new baseline; returns the entry count."""
    entries = {}
    for diag in diagnostics:
        entries[diag.fingerprint] = {
            "rule": diag.rule,
            "path": diag.path,
            "line": diag.line,
            "note": note,
        }
    payload = {"version": _VERSION, "entries": entries}
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return len(entries)


def empty_baseline() -> dict:
    return {"version": _VERSION, "entries": {}}


def baseline_notes_missing(entries: Dict[str, dict]) -> List[str]:
    """Fingerprints whose entry lacks a tracking note (policy: every seeded
    baseline entry must say what tracks its cleanup)."""
    return [fp for fp, e in sorted(entries.items()) if not str(e.get("note", "")).strip()]

"""atpu-lint command line: ``python -m tools.atpu_lint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/config error.  ``--format json``
emits a machine-readable report (consumed by ``make lint-json`` and CI
artifacts); warnings (legacy-pragma migration notices, skipped cross-tree
checks) go to stderr in both formats and never affect the exit code.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .baseline import (
    DEFAULT_BASELINE,
    baseline_notes_missing,
    load_baseline,
    write_baseline,
)
from .core import Project, Report, Runner
from .rules import ALL_RULES, get_rules

#: default lint surface — everything `make quality` covers
DEFAULT_PATHS = ["accelerate_tpu", "tests", "tools", "bench.py", "bench_inference.py"]


def repo_root() -> Path:
    # tools/atpu_lint/cli.py -> repo root is two parents above the package
    return Path(__file__).resolve().parent.parent.parent


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m tools.atpu_lint",
        description="unified AST/dataflow lint for the accelerate_tpu tree",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select", action="append", default=None, metavar="RULE-ID",
        help="run only these rule ids (repeatable or comma-separated)",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help=f"baseline file (default: {DEFAULT_BASELINE} when present)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the default baseline even if it exists",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rule ids and exit",
    )
    return parser


def _resolve_select(values: Optional[List[str]]) -> Optional[List[str]]:
    if values is None:
        return None
    out: List[str] = []
    for v in values:
        out.extend(tok.strip() for tok in v.split(",") if tok.strip())
    return out


def _render_text(report: Report, stream) -> None:
    for diag in report.diagnostics:
        stream.write(diag.render() + "\n")
    tail = f"{len(report.diagnostics)} finding(s) in {report.files_checked} file(s)"
    if report.suppressed:
        tail += f", {report.suppressed} noqa-suppressed"
    if report.baselined:
        tail += f", {len(report.baselined)} baselined"
    stream.write(tail + "\n")


def _render_json(report: Report, stream) -> None:
    payload = {
        "findings": [d.to_json() for d in report.diagnostics],
        "suppressed": report.suppressed,
        "baselined": [d.to_json() for d in report.baselined],
        "files_checked": report.files_checked,
        "warnings": report.warnings,
    }
    stream.write(json.dumps(payload, indent=2, sort_keys=True) + "\n")


def main(argv: Optional[List[str]] = None, root: Optional[Path] = None,
         stdout=None, stderr=None) -> int:
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    args = build_parser().parse_args(argv)
    root = root or repo_root()

    if args.list_rules:
        for cls in ALL_RULES:
            stdout.write(f"{cls.id:24} {cls.summary}\n")
        return 0

    try:
        rules = get_rules(_resolve_select(args.select))
    except KeyError as exc:
        stderr.write(f"atpu-lint: {exc.args[0]}\n")
        return 2

    baseline_path = root / (args.baseline or DEFAULT_BASELINE)
    baseline = {}
    if not args.no_baseline and not args.write_baseline:
        if baseline_path.exists():
            try:
                baseline = load_baseline(baseline_path)
            except ValueError as exc:
                stderr.write(f"atpu-lint: {exc}\n")
                return 2
            for fp in baseline_notes_missing(baseline):
                stderr.write(
                    f"atpu-lint: warning: baseline entry {fp} has no tracking "
                    "note (policy: every seeded entry says what tracks its "
                    "cleanup)\n"
                )
        elif args.baseline:
            stderr.write(f"atpu-lint: no such baseline: {baseline_path}\n")
            return 2

    project = Project(root=root)
    runner = Runner(rules, project, baseline)
    paths = [Path(p) for p in (args.paths or DEFAULT_PATHS)]
    try:
        report = runner.run(paths)
    except (FileNotFoundError, ValueError) as exc:
        stderr.write(f"{exc}\n")
        return 2

    for warning in report.warnings:
        stderr.write(f"atpu-lint: warning: {warning}\n")

    if args.write_baseline:
        count = write_baseline(baseline_path, report.diagnostics)
        stderr.write(f"atpu-lint: wrote {count} entries to {baseline_path}\n")
        return 0

    if args.format == "json":
        _render_json(report, stdout)
    else:
        _render_text(report, stdout)
    return report.exit_code

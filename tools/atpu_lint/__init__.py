"""atpu-lint: unified AST/dataflow lint framework for the accelerate_tpu tree.

One shared AST load per file, a ``Rule`` plugin protocol, unified ``# noqa``
handling, text/JSON output, and an optional committed baseline.  Run it with
``python -m tools.atpu_lint`` (see ``docs/development/static-analysis.md``).
"""

from .core import Diagnostic, FileContext, Project, Report, Rule, Runner
from .rules import ALL_RULES, RULES_BY_ID, get_rules

__all__ = [
    "ALL_RULES",
    "Diagnostic",
    "FileContext",
    "Project",
    "Report",
    "Rule",
    "Runner",
    "RULES_BY_ID",
    "get_rules",
]

"""atpu-lint core: one shared AST load per file, a ``Rule`` plugin protocol,
and the runner that fans every parsed tree out to all applicable rules.

The previous generation of this tooling was seven single-rule scripts, each
re-reading and re-parsing the whole package with its own walker and its own
``# noqa`` dialect — seven interpreter startups per ``make quality``.  Here a
file is read once, parsed once, its noqa pragmas extracted once, and every
registered rule visits the same tree.  Rules are plain objects:

* ``id`` — kebab-case rule id, the ``# noqa:`` escape token;
* ``applies_to(rel)`` — path scoping (repo-root-relative posix path);
* ``visit(tree, src, ctx)`` — per-file pass returning ``Diagnostic``s;
* ``finalize(project)`` — optional cross-file pass after every visit (used
  by rules that aggregate project-wide state, e.g. metric-docs' orphan-row
  check).

Diagnostics are suppressed by line-level ``# noqa: <rule-id>`` pragmas
(:mod:`tools.atpu_lint.noqa`) and by a committed baseline of fingerprints
(:mod:`tools.atpu_lint.baseline`) for grandfathered findings.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

from .noqa import file_noqa_map

__all__ = ["Diagnostic", "FileContext", "Project", "Report", "Rule", "Runner"]

#: directories never linted, wherever they appear
_SKIP_DIR_NAMES = {"__pycache__", ".git", ".venv", "node_modules"}
#: repo-relative prefixes never linted (fixture files are violations on purpose)
_SKIP_REL_PREFIXES = ("tests/fixtures/lint/",)


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: ``path:line: [rule] message``.  ``src_line`` is the
    stripped source text of the flagged line — the fingerprint keys on it so
    baselines survive unrelated line-number churn."""

    path: str
    line: int
    rule: str
    message: str
    src_line: str = ""

    @property
    def fingerprint(self) -> str:
        key = self.src_line.strip() or str(self.line)
        digest = hashlib.sha1(
            f"{self.rule}\x00{self.path}\x00{key}".encode()
        ).hexdigest()
        return digest[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


class Rule:
    """Plugin protocol.  Subclasses set ``id``/``summary``/``invariant`` and
    override ``applies_to``/``visit`` (and ``finalize`` for cross-file
    rules)."""

    id: str = ""
    #: one-line description for ``--list-rules``
    summary: str = ""

    def applies_to(self, rel: str) -> bool:
        return True

    def visit(self, tree: Optional[ast.Module], src: str, ctx: "FileContext") -> List[Diagnostic]:
        return []

    def finalize(self, project: "Project") -> List[Diagnostic]:
        return []


@dataclasses.dataclass
class Project:
    """Run-wide context: the repo root every ``rel`` path hangs off, plus the
    handful of cross-tree locations rules need (the observability doc, the
    upstream reference checkout).  Tests point ``root`` at fixture trees."""

    root: Path
    reference_root: Path = Path("/root/reference")
    observability_doc: str = "docs/usage/observability.md"
    files: List["FileContext"] = dataclasses.field(default_factory=list)
    warnings: List[str] = dataclasses.field(default_factory=list)

    def warn(self, message: str) -> None:
        if message not in self.warnings:
            self.warnings.append(message)

    def rel(self, path: Path) -> str:
        return path.resolve().relative_to(self.root.resolve()).as_posix()


@dataclasses.dataclass
class FileContext:
    """Everything a rule may need about one file, computed exactly once."""

    path: Path
    rel: str
    src: str
    lines: List[str]
    tree: Optional[ast.Module]
    noqa: Dict[int, Set[str]]
    legacy_noqa: Dict[int, List[str]]
    project: Project

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


@dataclasses.dataclass
class Report:
    diagnostics: List[Diagnostic]
    suppressed: int
    baselined: List[Diagnostic]
    warnings: List[str]
    files_checked: int

    @property
    def exit_code(self) -> int:
        return 1 if self.diagnostics else 0


def discover_files(paths: Sequence[Path], project: Project) -> List[Path]:
    """Expand files/directories into the sorted set of lintable ``.py`` files
    under the project root (fixture trees and cache dirs excluded)."""
    out: Set[Path] = set()
    for p in paths:
        p = p if p.is_absolute() else project.root / p
        if p.is_dir():
            for f in p.rglob("*.py"):
                out.add(f)
        elif p.suffix == ".py" and p.exists():
            out.add(p)
        elif not p.exists():
            raise FileNotFoundError(f"atpu-lint: no such path: {p}")
    kept = []
    for f in sorted(out):
        try:
            rel = project.rel(f)
        except ValueError:
            raise ValueError(f"atpu-lint: {f} is outside the project root {project.root}")
        if any(part in _SKIP_DIR_NAMES for part in Path(rel).parts):
            continue
        if any(rel.startswith(pre) for pre in _SKIP_REL_PREFIXES):
            continue
        kept.append(f)
    return kept


class Runner:
    """Load each file once, run every applicable rule over the shared tree,
    then apply noqa suppression and the baseline."""

    def __init__(self, rules: Sequence[Rule], project: Project,
                 baseline: Optional[Dict[str, dict]] = None):
        self.rules = list(rules)
        self.project = project
        self.baseline = baseline or {}
        self.rule_ids = {r.id for r in self.rules}

    def run(self, paths: Sequence[Path], force: bool = False) -> Report:
        files = discover_files(paths, self.project)
        raw: List[Diagnostic] = []
        ctx_by_rel: Dict[str, FileContext] = {}
        for path in files:
            ctx = self._load(path)
            ctx_by_rel[ctx.rel] = ctx
            self.project.files.append(ctx)
            if ctx.tree is None:
                continue  # the parse diagnostic was already recorded
            for rule in self.rules:
                if force or rule.applies_to(ctx.rel):
                    raw.extend(rule.visit(ctx.tree, ctx.src, ctx))
        for rule in self.rules:
            raw.extend(rule.finalize(self.project))
        return self._filter(raw, ctx_by_rel, len(files))

    def _load(self, path: Path) -> FileContext:
        src = path.read_text()
        rel = self.project.rel(path)
        noqa, legacy = file_noqa_map(src)
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError as exc:
            tree = None
            # surfaced as an unsuppressable diagnostic: make quality also
            # runs compileall, be equally loud here
            self._parse_errors = getattr(self, "_parse_errors", [])
            self._parse_errors.append(
                Diagnostic(rel, exc.lineno or 1, "parse",
                           f"syntax error: {exc.msg}")
            )
        ctx = FileContext(path, rel, src, src.splitlines(), tree, noqa, legacy, self.project)
        return ctx

    def _filter(self, raw: Iterable[Diagnostic], ctx_by_rel: Dict[str, FileContext],
                files_checked: int) -> Report:
        kept: List[Diagnostic] = []
        baselined: List[Diagnostic] = []
        suppressed = 0
        for diag in raw:
            ctx = ctx_by_rel.get(diag.path)
            if not diag.src_line and ctx is not None:
                diag = dataclasses.replace(diag, src_line=ctx.line_text(diag.line))
            if ctx is not None and diag.rule in ctx.noqa.get(diag.line, ()):
                suppressed += 1
                continue
            if diag.fingerprint in self.baseline:
                baselined.append(diag)
                continue
            kept.append(diag)
        kept.extend(getattr(self, "_parse_errors", []))
        # legacy-pragma migration warnings (honored this release, then gone)
        for ctx in ctx_by_rel.values():
            for lineno, forms in sorted(ctx.legacy_noqa.items()):
                for form in forms:
                    from .noqa import LEGACY_ALIASES

                    self.project.warn(
                        f"{ctx.rel}:{lineno}: legacy '# noqa: {form}' form — "
                        f"use '# noqa: {LEGACY_ALIASES[form]}' (bare form is "
                        "honored this release only)"
                    )
        kept.sort(key=lambda d: (d.path, d.line, d.rule))
        return Report(kept, suppressed, baselined, list(self.project.warnings),
                      files_checked)

"""Rule ``bare-print``: no bare ``print(`` in library code.

Library output must go through ``accelerate_tpu.logging.get_logger`` (rank-
aware, level-filtered, dedupe-capable) or ``PartialState.print`` (the
deliberate main-process print channel) — a stray ``print`` in the train or
serve path emits once per host process and cannot be silenced.

Exempt: ``accelerate_tpu/test_utils/`` and ``accelerate_tpu/commands/``
(CLI + test harness surfaces print by design); any ``__main__.py``; code
inside ``main`` / ``_main`` functions or ``if __name__ == "__main__":``
blocks (script entry points); lines carrying ``# noqa: bare-print``.

Ported from ``tools/check_no_bare_print.py``; the rule now also covers the
lint framework's own package (self-hosting — the CLI reporter prints from
``main``, which stays exempt).
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import List

from ..core import Diagnostic, Rule
from ._ast_utils import entry_exempt_lines

EXEMPT_DIRS = ("test_utils", "commands")


class BarePrintRule(Rule):
    id = "bare-print"
    summary = "no bare print() in library code — use get_logger or PartialState.print"

    def applies_to(self, rel: str) -> bool:
        parts = PurePosixPath(rel).parts
        if parts[-1] == "__main__.py":
            return False
        if parts[0] == "accelerate_tpu":
            return len(parts) < 2 or parts[1] not in EXEMPT_DIRS
        return parts[:2] == ("tools", "atpu_lint")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        exempt = entry_exempt_lines(tree)
        out = []
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and node.lineno not in exempt
            ):
                out.append(Diagnostic(
                    ctx.rel, node.lineno, self.id,
                    "bare print() in library code — use get_logger(__name__) "
                    "or PartialState.print",
                ))
        return out

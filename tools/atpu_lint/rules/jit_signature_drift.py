"""Rule ``jit-signature-drift``: no call-varying shape scalar may flow into a
jitted callee as a traced-shape-affecting positional.

The recompile watchdog catches signature drift at runtime — after the fleet
has already burned minutes of compile time.  This is its static counterpart:
a Python scalar derived from ``len(...)`` / ``.shape`` / ``range(...)`` (a
value that varies call to call) must not reach a jitted executable in a
position that changes traced shapes, because every new value then traces and
compiles a fresh program:

* a slice bound on an argument — ``jitted(x[:n])`` ships a different shape
  every call (the repo's answer is bucketed executables:
  ``self._prefill[bucket]`` keys a *dict of executables* on the padded size,
  which this rule deliberately does not flag);
* a shape constructor in an argument — ``jitted(jnp.zeros(n))`` /
  ``np.full(n, ...)``;
* a ``static_argnums`` / ``static_argnames`` position of a callee whose jit
  declaration is visible in this module — static args are hashed into the
  executable key, so a drifting value IS a recompile;
* a bare drifting scalar passed positionally — harmless only if the callee
  never lets it touch a shape; flagged so the author either wraps it
  (``jnp.int32(n)`` arrives as a traced 0-d array) or buckets it.

Linear per-function taint, no branch sensitivity; executables recognized
from visible module bindings exactly as in ``use-after-donate``.  Scope:
``accelerate_tpu/serving/``.  Escape: ``# noqa: jit-signature-drift`` with a
justifying comment.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Diagnostic, Rule
from ._ast_utils import (
    build_executable_index,
    build_jit_index,
    callee_executable_name,
    dotted,
    iter_functions,
    linearize,
    tail_name,
)

SHAPE_ATTRS = {"shape", "ndim", "size"}
SHAPE_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange", "reshape",
                      "broadcast_to", "tile", "repeat"}


class _Drift:
    """Tracks names holding call-varying shape scalars."""

    def __init__(self):
        self.names: Set[str] = set()

    def expr_drifts(self, expr: Optional[ast.expr]) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.names
        if isinstance(expr, ast.Attribute):
            if expr.attr in SHAPE_ATTRS:
                return True
            name = dotted(expr)
            return bool(name and name in self.names)
        if isinstance(expr, ast.Subscript):
            return self.expr_drifts(expr.value)
        if isinstance(expr, ast.Call):
            if tail_name(expr.func) == "len":
                return True
            if tail_name(expr.func) == "int":
                return any(self.expr_drifts(a) for a in expr.args)
            return False
        if isinstance(expr, ast.BinOp):
            return self.expr_drifts(expr.left) or self.expr_drifts(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_drifts(expr.operand)
        return False

    def assign(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            drifts = self.expr_drifts(stmt.value)
            for target in stmt.targets:
                self._bind(target, drifts)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self.expr_drifts(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            name = dotted(stmt.target)
            if name and self.expr_drifts(stmt.value):
                self.names.add(name)
        elif isinstance(stmt, ast.For):
            # a loop variable over range(...) varies per iteration
            if (
                isinstance(stmt.iter, ast.Call)
                and tail_name(stmt.iter.func) == "range"
            ):
                self._bind(stmt.target, True)

    def _bind(self, target: ast.expr, drifts: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, drifts)
            return
        name = dotted(target)
        if not name:
            return
        if drifts:
            self.names.add(name)
        else:
            self.names.discard(name)


class JitSignatureDriftRule(Rule):
    id = "jit-signature-drift"
    summary = "no call-varying len()/.shape scalar in a traced-shape-affecting jit positional"

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/serving/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        jit_index = build_jit_index(tree)
        executables = build_executable_index(tree) | set(jit_index)
        out: List[Diagnostic] = []
        for fn in iter_functions(tree):
            out.extend(self._check_function(fn, jit_index, executables, ctx))
        return out

    def _check_function(self, fn, jit_index, executables: Set[str], ctx) -> List[Diagnostic]:
        drift = _Drift()
        out: List[Diagnostic] = []
        seen: Set[tuple] = set()

        def flag(node: ast.AST, what: str) -> None:
            key = (node.lineno, what)
            if key in seen:
                return
            seen.add(key)
            out.append(Diagnostic(
                ctx.rel, node.lineno, self.id,
                f"jit signature drift: {what} — every new value traces and "
                "compiles a fresh executable; bucket the size (dict of "
                "executables keyed on the padded shape) or wrap the scalar "
                "as a device array (jnp.int32(n)) so it arrives traced",
            ))

        for ls in linearize(fn):
            for call in ls.calls:
                callee = callee_executable_name(call)
                if callee not in executables:
                    continue
                target = jit_index.get(dotted(call.func) or "")
                for pos, arg in enumerate(call.args):
                    self._check_arg(arg, pos, target, drift, flag)
                for kw in call.keywords:
                    if (
                        target is not None
                        and kw.arg in target.static_names
                        and drift.expr_drifts(kw.value)
                    ):
                        flag(kw.value, f"drifting scalar bound to static_argname "
                                       f"'{kw.arg}' of {target.name}()")
            drift.assign(ls.node)
        return out

    def _check_arg(self, arg: ast.expr, pos: int, target, drift: _Drift, flag) -> None:
        # slice with a drifting bound: the argument's shape varies per call
        if isinstance(arg, ast.Subscript):
            slices = arg.slice.elts if isinstance(arg.slice, ast.Tuple) else [arg.slice]
            for s in slices:
                if isinstance(s, ast.Slice) and any(
                    drift.expr_drifts(b) for b in (s.lower, s.upper, s.step)
                ):
                    flag(arg, "argument sliced by a call-varying bound "
                              "(varying traced shape)")
                    return
        # shape constructor sized by a drifting scalar
        if isinstance(arg, ast.Call) and tail_name(arg.func) in SHAPE_CONSTRUCTORS:
            if any(drift.expr_drifts(a) for a in arg.args):
                flag(arg, f"{tail_name(arg.func)}(...) sized by a call-varying "
                          "scalar (varying traced shape)")
                return
        # bare drifting scalar in a positional slot (x.shape[0], len(x), n)
        if drift.expr_drifts(arg):
            if target is not None and pos in target.static_positions:
                flag(arg, f"drifting scalar at static_argnums position {pos} "
                          f"of {target.name}()")
            else:
                flag(arg, "call-varying shape scalar passed positionally to a "
                          "jitted callee")

"""Rule ``blocking-readback``: no blocking device->host readback in the
serving hot path.

The pipelined serve loop (``ServingEngine(async_depth=1)``) works because
dispatching window N+1 never waits on window N — every device->host
materialization is funneled through ``serving/readback.py``'s ``fetch``,
drained at the one point the engine has decided to block.  A stray
``jax.device_get`` (or ``.block_until_ready()``) anywhere else in
``accelerate_tpu/serving/`` silently re-serializes the pipeline: the loop
still produces identical tokens, just without the overlap, which is exactly
the kind of regression that survives every correctness test.

Exempt: ``serving/readback.py`` (the one sanctioned blocking transfer lives
there) and lines carrying ``# noqa: blocking-readback`` (legacy bare
``# noqa: readback`` is honored with a migration warning).

Ported from ``tools/check_no_blocking_readback.py``.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Diagnostic, Rule
from ._ast_utils import tail_name

BLOCKING_NAMES = ("device_get", "block_until_ready")


class BlockingReadbackRule(Rule):
    id = "blocking-readback"
    summary = "no jax.device_get / block_until_ready outside serving/readback.py"

    def applies_to(self, rel: str) -> bool:
        return (
            rel.startswith("accelerate_tpu/serving/")
            and not rel.endswith("/readback.py")
        )

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        out = {}
        for node in ast.walk(tree):
            # flag the attribute access itself, not just calls: passing
            # ``arr.block_until_ready`` around blocks just as hard when invoked
            if isinstance(node, ast.Call):
                name = tail_name(node.func)
            elif isinstance(node, ast.Attribute):
                name = node.attr
            else:
                continue
            if name not in BLOCKING_NAMES:
                continue
            # one diagnostic per line: a Call and its Attribute func both match
            out[node.lineno] = Diagnostic(
                ctx.rel, node.lineno, self.id,
                f"blocking readback ({name}) in the serving hot path — route "
                "it through serving/readback.fetch (or justify with "
                "'# noqa: blocking-readback')",
            )
        return [out[k] for k in sorted(out)]

"""Rule ``implicit-host-sync``: device values from jitted pool executables
must reach the host through ``serving/readback.fetch`` — never through an
implicit conversion.

``blocking-readback`` catches the *explicit* syncs (``jax.device_get``,
``block_until_ready``).  This rule catches the quiet ones: ``int(toks[0])``,
``float(x)``, ``bool(x)``, ``x.item()`` / ``x.tolist()``, ``np.asarray(x)``,
iterating a device array, or truth-testing one (``if pending:``) all force a
blocking device->host materialization.  Inside the pipelined serve loop any
such conversion on a window's outputs stalls the host mid-overlap: tokens
stay identical, the speedup silently disappears — the regression class no
correctness test can see.

Dataflow is a linear per-function taint pass: values returned by calls
through the module's visible executable bindings (``jax.jit`` / ``pjit`` /
``_serve_jit`` results, ``RecompileWatchdog``-wrapped ``make_*`` factories,
and ``self._put`` / ``device_put`` uploads) are device-tainted; taint flows
through assignment, subscripts, arithmetic, and method calls, and is cleared
by ``fetch(...)`` (the one sanctioned sync) or by rebinding from a host
expression.  Scope: ``accelerate_tpu/serving/`` except ``readback.py``.
Escape: ``# noqa: implicit-host-sync`` with a justifying comment.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Diagnostic, Rule
from ._ast_utils import (
    build_executable_index,
    build_jit_index,
    callee_executable_name,
    dotted,
    iter_functions,
    linearize,
    tail_name,
)

UPLOAD_TAILS = {"_put", "device_put"}
SCALAR_BUILTINS = {"int", "float", "bool"}
ITEM_METHODS = {"item", "tolist"}
NUMPY_BASES = {"np", "numpy", "onp"}
NUMPY_SINKS = {"asarray", "array"}


class _Taint:
    """Per-function device-taint state over dotted names."""

    def __init__(self, executables: Set[str]):
        self.names: Set[str] = set()
        self.executables = executables

    def expr_tainted(self, expr: Optional[ast.expr]) -> bool:
        if expr is None:
            return False
        if isinstance(expr, (ast.Name, ast.Attribute)):
            name = dotted(expr)
            if name and name in self.names:
                return True
            if isinstance(expr, ast.Attribute):
                return self.expr_tainted(expr.value)
            return False
        if isinstance(expr, ast.Subscript):
            return self.expr_tainted(expr.value)
        if isinstance(expr, ast.Call):
            if tail_name(expr.func) == "fetch":
                return False  # the sanctioned sync: result is host-side
            if callee_executable_name(expr) in self.executables:
                return True
            if tail_name(expr.func) in UPLOAD_TAILS:
                return True
            if isinstance(expr.func, ast.Attribute) and self.expr_tainted(expr.func.value):
                return True  # method on a device value stays on device
            return any(self.expr_tainted(a) for a in expr.args) or any(
                self.expr_tainted(k.value) for k in expr.keywords
            )
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.BinOp):
            return self.expr_tainted(expr.left) or self.expr_tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v) for v in expr.values)
        if isinstance(expr, ast.Compare):
            return self.expr_tainted(expr.left) or any(
                self.expr_tainted(c) for c in expr.comparators
            )
        if isinstance(expr, ast.IfExp):
            return self.expr_tainted(expr.body) or self.expr_tainted(expr.orelse)
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value)
        return False

    def assign(self, stmt: ast.stmt) -> None:
        """Propagate through an assignment: targets become tainted iff the
        value side is, elementwise when both sides are same-length tuples."""
        if isinstance(stmt, ast.Assign):
            value, targets_list = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value, targets_list = stmt.value, [stmt.target]
        elif isinstance(stmt, ast.AugAssign):
            name = dotted(stmt.target)
            if name and self.expr_tainted(stmt.value):
                self.names.add(name)
            return
        else:
            return
        for target in targets_list:
            if (
                isinstance(target, (ast.Tuple, ast.List))
                and isinstance(value, (ast.Tuple, ast.List))
                and len(target.elts) == len(value.elts)
            ):
                for t, v in zip(target.elts, value.elts):
                    self._bind(t, self.expr_tainted(v))
            else:
                self._bind(target, self.expr_tainted(value))

    def _bind(self, target: ast.expr, tainted: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted)
            return
        if isinstance(target, ast.Starred):
            target = target.value
        name = dotted(target)
        if not name:
            return
        if tainted:
            self.names.add(name)
        else:
            self.names.discard(name)


class ImplicitHostSyncRule(Rule):
    id = "implicit-host-sync"
    summary = "no int()/float()/bool()/.item()/np.asarray/iteration/truth-test on device values"

    def applies_to(self, rel: str) -> bool:
        return (
            rel.startswith("accelerate_tpu/serving/")
            and not rel.endswith("/readback.py")
        )

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        jit_index = build_jit_index(tree)
        executables = build_executable_index(tree) | set(jit_index)
        out: List[Diagnostic] = []
        for fn in iter_functions(tree):
            out.extend(self._check_function(fn, executables, ctx))
        return out

    def _check_function(self, fn, executables: Set[str], ctx) -> List[Diagnostic]:
        taint = _Taint(executables)
        out: List[Diagnostic] = []
        seen: Set[tuple] = set()

        def flag(node: ast.AST, what: str) -> None:
            key = (node.lineno, what)
            if key in seen:
                return
            seen.add(key)
            out.append(Diagnostic(
                ctx.rel, node.lineno, self.id,
                f"implicit host sync: {what} blocks until the device value "
                "materializes, stalling the pipelined serve loop — drain it "
                "through serving/readback.fetch at the engine's chosen sync "
                "point (or justify with '# noqa: implicit-host-sync')",
            ))

        for ls in linearize(fn):
            node = ls.node
            # sinks first, against the taint state before this statement
            for call in ls.calls:
                func = call.func
                if (
                    isinstance(func, ast.Name)
                    and func.id in SCALAR_BUILTINS
                    and any(taint.expr_tainted(a) for a in call.args)
                ):
                    flag(call, f"{func.id}() on a device value")
                elif (
                    isinstance(func, ast.Attribute)
                    and func.attr in ITEM_METHODS
                    and taint.expr_tainted(func.value)
                ):
                    flag(call, f".{func.attr}() on a device value")
                elif (
                    isinstance(func, ast.Attribute)
                    and func.attr in NUMPY_SINKS
                    and isinstance(func.value, ast.Name)
                    and func.value.id in NUMPY_BASES
                    and any(taint.expr_tainted(a) for a in call.args)
                ):
                    flag(call, f"{func.value.id}.{func.attr}() on a device value")
            if isinstance(node, ast.For) and taint.expr_tainted(node.iter):
                flag(node, "iterating a device value")
            elif isinstance(node, (ast.If, ast.While)) and taint.expr_tainted(node.test):
                flag(node, "truth-testing a device value")
            elif isinstance(node, ast.Assert) and taint.expr_tainted(node.test):
                flag(node, "asserting on a device value")
            taint.assign(node)
        return out

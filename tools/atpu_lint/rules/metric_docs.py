"""Rule ``metric-docs``: the observability doc and the telemetry surface agree
in BOTH directions — for registry metrics AND for span/flight-event names.

Forward (ported from ``tools/check_metric_docs.py``): any literal metric name
passed to ``registry.counter(...)``, ``registry.gauge(...)`` or
``registry.histogram(...)`` inside ``accelerate_tpu/`` must appear verbatim
in ``docs/usage/observability.md`` — the doc is the operator-facing contract
for what a ``/metrics`` scrape can contain, and an undocumented gauge is
invisible to whoever has to build the dashboard.  The same holds for
namespaced span and flight-event names (``tracer.span("serve/...")``,
``recorder.record("serve/...")``, ``recorder.heartbeat("serve/...")``): an
undocumented event kind is noise to whoever reads a ``/debug/flight`` ring
during an incident.

Reverse (new with the port — the old script was asymmetric): every concrete
metric name in the doc's metric table must still be emitted somewhere, or the
row is an *orphan* that sends the dashboard builder hunting for a series that
no longer exists.  A doc name counts as emitted when it matches a literal
registration OR a dynamic f-string family (``f"serve/{k}_total"`` matches
``serve/preemptions_total``).  Doc names carrying ``*`` or ``<`` are
documented patterns and skipped; so are names outside the table's metrics
column.  Span/flight-event names get the same orphan check against the doc's
"Span & flight-event index" section: its table rows (first cell) must each
match a ``span``/``record``/``heartbeat`` literal still in the tree.

Only string-literal (or f-string) first arguments are checked; names built
from opaque variables are skipped, as are un-namespaced span names (no
``/``, e.g. ``span("phase")`` in examples).  ``# noqa: metric-docs`` on the
emitting line exempts it.

The orphan direction runs only when the whole ``accelerate_tpu`` package is
on the lint surface: on a partial run (``python -m tools.atpu_lint
accelerate_tpu/serving``) the absence of a registration proves nothing.
"""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from ..core import Diagnostic, Rule

FACTORIES = ("counter", "gauge", "histogram")
EVENT_EMITTERS = ("span", "record", "heartbeat")
_CONCRETE = re.compile(r"[a-z0-9_]+(?:/[a-z0-9_]+)+")
_EVENT_SECTION = "span & flight-event index"


class MetricDocsRule(Rule):
    id = "metric-docs"
    summary = "every emitted metric is documented; every documented metric is emitted"

    def __init__(self):
        self._literals: List[Tuple[str, int, str, str]] = []  # rel, line, kind, name
        self._patterns: List[re.Pattern] = []
        self._event_literals: List[Tuple[str, int, str, str]] = []
        self._event_patterns: List[re.Pattern] = []

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
            elif isinstance(node.func, ast.Name):
                # the module-level ``span("...")`` helper from telemetry
                attr = node.func.id if node.func.id == "span" else None
            else:
                continue
            first = node.args[0]
            if attr in FACTORIES:
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    self._literals.append((ctx.rel, node.lineno, attr, first.value))
                elif isinstance(first, ast.JoinedStr):
                    self._patterns.append(self._joined_pattern(first))
            elif attr in EVENT_EMITTERS:
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    # only namespaced names are part of the contract — bare
                    # span names ("phase", function qualnames) are ad hoc
                    if _CONCRETE.fullmatch(first.value):
                        self._event_literals.append(
                            (ctx.rel, node.lineno, attr, first.value)
                        )
                elif isinstance(first, ast.JoinedStr):
                    self._event_patterns.append(self._joined_pattern(first))
        return []

    @staticmethod
    def _joined_pattern(node: ast.JoinedStr) -> re.Pattern:
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(re.escape(str(piece.value)))
            else:
                parts.append(r".+")
        return re.compile("".join(parts))

    def finalize(self, project) -> List[Diagnostic]:
        doc_rel = project.observability_doc
        doc_path = project.root / doc_rel
        if not doc_path.exists():
            if not self._literals and not self._event_literals:
                return []
            return [Diagnostic(doc_rel, 1, self.id, f"missing {doc_rel}")]
        doc_text = doc_path.read_text()
        out: List[Diagnostic] = []
        for rel, lineno, kind, name in self._literals:
            if name not in doc_text:
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} '{name}' is not documented in {doc_rel}",
                ))
        for rel, lineno, kind, name in self._event_literals:
            if name not in doc_text:
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} event '{name}' is not documented in {doc_rel}",
                ))
        if not self._covers_package(project):
            return out
        emitted = {name for _, _, _, name in self._literals}
        for lineno, name in self._doc_table_names(doc_text):
            if name in emitted or any(p.fullmatch(name) for p in self._patterns):
                continue
            out.append(Diagnostic(
                doc_rel, lineno, self.id,
                f"orphan doc row: metric '{name}' is documented but no longer "
                "emitted by any registry.counter/gauge/histogram call",
                src_line=name,
            ))
        event_names = {name for _, _, _, name in self._event_literals}
        for lineno, name in self._event_index_names(doc_text):
            if name in event_names or any(
                p.fullmatch(name) for p in self._event_patterns
            ):
                continue
            out.append(Diagnostic(
                doc_rel, lineno, self.id,
                f"orphan doc row: span/flight-event '{name}' is documented "
                "but no longer emitted by any span/record/heartbeat call",
                src_line=name,
            ))
        return out

    @staticmethod
    def _covers_package(project) -> bool:
        """True when every lintable file of ``accelerate_tpu/`` was visited
        this run — the precondition for "nothing emits this name" to mean
        anything.  Fixture projects without the package count as covered."""
        pkg = project.root / "accelerate_tpu"
        if not pkg.is_dir():
            return True
        visited = {ctx.rel for ctx in project.files}
        for f in pkg.rglob("*.py"):
            rel = project.rel(f)
            if "__pycache__" in rel.split("/"):
                continue
            if rel not in visited:
                return False
        return True

    @staticmethod
    def _doc_table_names(doc_text: str) -> List[Tuple[int, str]]:
        """Concrete metric names in the metrics column (cell 2) of markdown
        table rows.  Backticked tokens with ``*``/``<`` are documented
        dynamic families, not concrete names.  Rows inside the span/event
        index section belong to :meth:`_event_index_names`, not here."""
        found = []
        in_event_section = False
        for i, line in enumerate(doc_text.splitlines(), start=1):
            if line.startswith("#"):
                in_event_section = _EVENT_SECTION in line.lower()
                continue
            if in_event_section or not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 4:
                continue
            for m in re.finditer(r"`([^`]+)`", cells[2]):
                token = m.group(1)
                if "*" in token or "<" in token:
                    continue
                if _CONCRETE.fullmatch(token):
                    found.append((i, token))
        return found

    @staticmethod
    def _event_index_names(doc_text: str) -> List[Tuple[int, str]]:
        """Concrete span/flight-event names from the doc's "Span &
        flight-event index" section: the first backticked token of each table
        row's first cell, until the next heading."""
        found = []
        in_section = False
        for i, line in enumerate(doc_text.splitlines(), start=1):
            if line.startswith("#"):
                in_section = _EVENT_SECTION in line.lower()
                continue
            if not in_section or not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 3:
                continue
            for m in re.finditer(r"`([^`]+)`", cells[1]):
                token = m.group(1)
                if "*" in token or "<" in token:
                    continue
                if _CONCRETE.fullmatch(token):
                    found.append((i, token))
        return found

"""Rule ``metric-docs``: the observability doc and the metric registry agree
in BOTH directions.

Forward (ported from ``tools/check_metric_docs.py``): any literal metric name
passed to ``registry.counter(...)``, ``registry.gauge(...)`` or
``registry.histogram(...)`` inside ``accelerate_tpu/`` must appear verbatim
in ``docs/usage/observability.md`` — the doc is the operator-facing contract
for what a ``/metrics`` scrape can contain, and an undocumented gauge is
invisible to whoever has to build the dashboard.

Reverse (new with the port — the old script was asymmetric): every concrete
metric name in the doc's metric table must still be emitted somewhere, or the
row is an *orphan* that sends the dashboard builder hunting for a series that
no longer exists.  A doc name counts as emitted when it matches a literal
registration OR a dynamic f-string family (``f"serve/{k}_total"`` matches
``serve/preemptions_total``).  Doc names carrying ``*`` or ``<`` are
documented patterns and skipped; so are names outside the table's metrics
column (the spans column names tracer spans, not registry series).

Only string-literal (or f-string) first arguments are checked; names built
from opaque variables are skipped.  ``# noqa: metric-docs`` on the
registration line exempts it.

The orphan direction runs only when the whole ``accelerate_tpu`` package is
on the lint surface: on a partial run (``python -m tools.atpu_lint
accelerate_tpu/serving``) the absence of a registration proves nothing.
"""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from ..core import Diagnostic, Rule

FACTORIES = ("counter", "gauge", "histogram")
_CONCRETE = re.compile(r"[a-z0-9_]+(?:/[a-z0-9_]+)+")


class MetricDocsRule(Rule):
    id = "metric-docs"
    summary = "every emitted metric is documented; every documented metric is emitted"

    def __init__(self):
        self._literals: List[Tuple[str, int, str, str]] = []  # rel, line, kind, name
        self._patterns: List[re.Pattern] = []

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in FACTORIES
                and node.args
            ):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                self._literals.append((ctx.rel, node.lineno, node.func.attr, first.value))
            elif isinstance(first, ast.JoinedStr):
                parts = []
                for piece in first.values:
                    if isinstance(piece, ast.Constant):
                        parts.append(re.escape(str(piece.value)))
                    else:
                        parts.append(r".+")
                self._patterns.append(re.compile("".join(parts)))
        return []

    def finalize(self, project) -> List[Diagnostic]:
        doc_rel = project.observability_doc
        doc_path = project.root / doc_rel
        if not doc_path.exists():
            if not self._literals:
                return []
            return [Diagnostic(doc_rel, 1, self.id, f"missing {doc_rel}")]
        doc_text = doc_path.read_text()
        out: List[Diagnostic] = []
        for rel, lineno, kind, name in self._literals:
            if name not in doc_text:
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} '{name}' is not documented in {doc_rel}",
                ))
        if not self._covers_package(project):
            return out
        emitted = {name for _, _, _, name in self._literals}
        for lineno, name in self._doc_table_names(doc_text):
            if name in emitted or any(p.fullmatch(name) for p in self._patterns):
                continue
            out.append(Diagnostic(
                doc_rel, lineno, self.id,
                f"orphan doc row: metric '{name}' is documented but no longer "
                "emitted by any registry.counter/gauge/histogram call",
                src_line=name,
            ))
        return out

    @staticmethod
    def _covers_package(project) -> bool:
        """True when every lintable file of ``accelerate_tpu/`` was visited
        this run — the precondition for "nothing emits this name" to mean
        anything.  Fixture projects without the package count as covered."""
        pkg = project.root / "accelerate_tpu"
        if not pkg.is_dir():
            return True
        visited = {ctx.rel for ctx in project.files}
        for f in pkg.rglob("*.py"):
            rel = project.rel(f)
            if "__pycache__" in rel.split("/"):
                continue
            if rel not in visited:
                return False
        return True

    @staticmethod
    def _doc_table_names(doc_text: str) -> List[Tuple[int, str]]:
        """Concrete metric names in the metrics column (cell 2) of markdown
        table rows.  Backticked tokens with ``*``/``<`` are documented
        dynamic families, not concrete names."""
        found = []
        for i, line in enumerate(doc_text.splitlines(), start=1):
            if not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 4:
                continue
            for m in re.finditer(r"`([^`]+)`", cells[2]):
                token = m.group(1)
                if "*" in token or "<" in token:
                    continue
                if _CONCRETE.fullmatch(token):
                    found.append((i, token))
        return found

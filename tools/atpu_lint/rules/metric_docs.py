"""Rule ``metric-docs``: the observability doc and the telemetry surface agree
in BOTH directions — for registry metrics AND for span/flight-event names.

Forward (ported from ``tools/check_metric_docs.py``): any literal metric name
passed to ``registry.counter(...)``, ``registry.gauge(...)`` or
``registry.histogram(...)`` inside ``accelerate_tpu/`` must appear verbatim
in ``docs/usage/observability.md`` — the doc is the operator-facing contract
for what a ``/metrics`` scrape can contain, and an undocumented gauge is
invisible to whoever has to build the dashboard.  The same holds for
namespaced span and flight-event names (``tracer.span("serve/...")``,
``recorder.record("serve/...")``, ``recorder.heartbeat("serve/...")``): an
undocumented event kind is noise to whoever reads a ``/debug/flight`` ring
during an incident.

Reverse (new with the port — the old script was asymmetric): every concrete
metric name in the doc's metric table must still be emitted somewhere, or the
row is an *orphan* that sends the dashboard builder hunting for a series that
no longer exists.  A doc name counts as emitted when it matches a literal
registration OR a dynamic f-string family (``f"serve/{k}_total"`` matches
``serve/preemptions_total``).  Doc names carrying ``*`` are documented
globs and skipped; so are names outside the table's metrics column.
Span/flight-event names get the same orphan check against the doc's
"Span & flight-event index" section: its table rows (first cell) must each
match a ``span``/``record``/``heartbeat`` literal still in the tree.

Families (per-tenant / per-class / per-SLO names) close the loop in both
directions too.  A doc token written with ``<...>`` placeholders — e.g.
``serve/ttft_s_tenant_<tenant>`` — is a *family row*: its placeholder-
stripped instance (``serve/ttft_s_tenant_tenant``) must match some f-string
registration pattern (``f"serve/ttft_s_tenant_{tenant}"``), or the family
row is an orphan like any concrete row.  Conversely every f-string
registration must be documented — once, as a family row (or by a concrete
token the pattern covers); an undocumented ``f"serve/slo_burn_rate_{name}"``
is exactly as invisible to the dashboard builder as an undocumented literal.

Only string-literal (or f-string) first arguments are checked; names built
from opaque variables are skipped, as are un-namespaced span names (no
``/``, e.g. ``span("phase")`` in examples).  ``# noqa: metric-docs`` on the
emitting line exempts it.

The orphan direction runs only when the whole ``accelerate_tpu`` package is
on the lint surface: on a partial run (``python -m tools.atpu_lint
accelerate_tpu/serving``) the absence of a registration proves nothing.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from ..core import Diagnostic, Rule

FACTORIES = ("counter", "gauge", "histogram")
EVENT_EMITTERS = ("span", "record", "heartbeat")
_CONCRETE = re.compile(r"[a-z0-9_]+(?:/[a-z0-9_]+)+")
_EVENT_SECTION = "span & flight-event index"


class MetricDocsRule(Rule):
    id = "metric-docs"
    summary = "every emitted metric is documented; every documented metric is emitted"

    def __init__(self):
        self._literals: List[Tuple[str, int, str, str]] = []  # rel, line, kind, name
        # rel, line, kind, compiled pattern, display form (``serve/<...>_total``)
        self._patterns: List[Tuple[str, int, str, re.Pattern, str]] = []
        self._event_literals: List[Tuple[str, int, str, str]] = []
        self._event_patterns: List[Tuple[str, int, str, re.Pattern, str]] = []

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
            elif isinstance(node.func, ast.Name):
                # the module-level ``span("...")`` helper from telemetry
                attr = node.func.id if node.func.id == "span" else None
            else:
                continue
            first = node.args[0]
            if attr in FACTORIES:
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    self._literals.append((ctx.rel, node.lineno, attr, first.value))
                elif isinstance(first, ast.JoinedStr):
                    pattern, display = self._joined_pattern(first)
                    self._patterns.append(
                        (ctx.rel, node.lineno, attr, pattern, display)
                    )
            elif attr in EVENT_EMITTERS:
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    # only namespaced names are part of the contract — bare
                    # span names ("phase", function qualnames) are ad hoc
                    if _CONCRETE.fullmatch(first.value):
                        self._event_literals.append(
                            (ctx.rel, node.lineno, attr, first.value)
                        )
                elif isinstance(first, ast.JoinedStr):
                    pattern, display = self._joined_pattern(first)
                    self._event_patterns.append(
                        (ctx.rel, node.lineno, attr, pattern, display)
                    )
        return []

    @staticmethod
    def _joined_pattern(node: ast.JoinedStr) -> Tuple[re.Pattern, str]:
        """Compile an f-string registration into ``(match pattern, display)``
        — the display form writes each interpolation as ``<...>``, the same
        placeholder convention family rows use in the doc."""
        parts = []
        display = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(re.escape(str(piece.value)))
                display.append(str(piece.value))
            else:
                parts.append(r".+")
                display.append("<...>")
        return re.compile("".join(parts)), "".join(display)

    @staticmethod
    def _family_instance(token: str) -> "Optional[str]":
        """A doc token with ``<...>`` placeholders (``serve/ttft_s_tenant_
        <tenant>``) collapses to a concrete *instance* (``serve/ttft_s_
        tenant_tenant``) that f-string registration patterns can fullmatch.
        Returns ``None`` for non-family tokens, globs, and malformed names.
        """
        if "<" not in token or "*" in token:
            return None
        instance = re.sub(r"<([a-z0-9_]+)>", r"\1", token)
        if "<" in instance or ">" in instance:
            return None
        return instance if _CONCRETE.fullmatch(instance) else None

    def finalize(self, project) -> List[Diagnostic]:
        doc_rel = project.observability_doc
        doc_path = project.root / doc_rel
        if not doc_path.exists():
            if not (self._literals or self._event_literals
                    or self._patterns or self._event_patterns):
                return []
            return [Diagnostic(doc_rel, 1, self.id, f"missing {doc_rel}")]
        doc_text = doc_path.read_text()
        out: List[Diagnostic] = []
        for rel, lineno, kind, name in self._literals:
            if name not in doc_text:
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} '{name}' is not documented in {doc_rel}",
                ))
        for rel, lineno, kind, name in self._event_literals:
            if name not in doc_text:
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} event '{name}' is not documented in {doc_rel}",
                ))
        # forward, family direction: an f-string registration is documented
        # when its pattern covers some backticked doc token — a concrete name
        # or a ``<...>`` family row's placeholder-stripped instance.  Tokens
        # are extracted per line: a whole-doc scan would mispair the
        # backticks of ``` code fences with inline ones and shift every
        # token after the first fence.
        doc_tokens = set()
        for doc_line in doc_text.splitlines():
            if doc_line.lstrip().startswith("```"):
                continue
            doc_tokens.update(re.findall(r"`([^`]+)`", doc_line))
        covered = {t for t in doc_tokens if _CONCRETE.fullmatch(t)}
        covered.update(
            inst for inst in map(self._family_instance, doc_tokens)
            if inst is not None
        )
        for rel, lineno, kind, pattern, display in self._patterns:
            if not any(pattern.fullmatch(t) for t in covered):
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} family '{display}' is not documented in "
                    f"{doc_rel} (document it once as a family row, e.g. "
                    f"`{display.replace('<...>', '<label>')}`)",
                ))
        for rel, lineno, kind, pattern, display in self._event_patterns:
            if not any(pattern.fullmatch(t) for t in covered):
                out.append(Diagnostic(
                    rel, lineno, self.id,
                    f"{kind} event family '{display}' is not documented in "
                    f"{doc_rel}",
                ))
        if not self._covers_package(project):
            return out
        emitted = {name for _, _, _, name in self._literals}
        for lineno, name in self._doc_table_names(doc_text):
            instance = self._family_instance(name)
            if instance is not None:
                if instance in emitted or any(
                    p.fullmatch(instance) for _, _, _, p, _ in self._patterns
                ):
                    continue
                out.append(Diagnostic(
                    doc_rel, lineno, self.id,
                    f"orphan doc row: metric family '{name}' is documented "
                    "but no f-string registry.counter/gauge/histogram call "
                    "emits it",
                    src_line=name,
                ))
                continue
            if name in emitted or any(
                p.fullmatch(name) for _, _, _, p, _ in self._patterns
            ):
                continue
            out.append(Diagnostic(
                doc_rel, lineno, self.id,
                f"orphan doc row: metric '{name}' is documented but no longer "
                "emitted by any registry.counter/gauge/histogram call",
                src_line=name,
            ))
        event_names = {name for _, _, _, name in self._event_literals}
        for lineno, name in self._event_index_names(doc_text):
            instance = self._family_instance(name)
            if instance is not None:
                if instance in event_names or any(
                    p.fullmatch(instance) for _, _, _, p, _ in self._event_patterns
                ):
                    continue
                out.append(Diagnostic(
                    doc_rel, lineno, self.id,
                    f"orphan doc row: span/flight-event family '{name}' is "
                    "documented but no f-string span/record/heartbeat call "
                    "emits it",
                    src_line=name,
                ))
                continue
            if name in event_names or any(
                p.fullmatch(name) for _, _, _, p, _ in self._event_patterns
            ):
                continue
            out.append(Diagnostic(
                doc_rel, lineno, self.id,
                f"orphan doc row: span/flight-event '{name}' is documented "
                "but no longer emitted by any span/record/heartbeat call",
                src_line=name,
            ))
        return out

    @staticmethod
    def _covers_package(project) -> bool:
        """True when every lintable file of ``accelerate_tpu/`` was visited
        this run — the precondition for "nothing emits this name" to mean
        anything.  Fixture projects without the package count as covered."""
        pkg = project.root / "accelerate_tpu"
        if not pkg.is_dir():
            return True
        visited = {ctx.rel for ctx in project.files}
        for f in pkg.rglob("*.py"):
            rel = project.rel(f)
            if "__pycache__" in rel.split("/"):
                continue
            if rel not in visited:
                return False
        return True

    @staticmethod
    def _doc_table_names(doc_text: str) -> List[Tuple[int, str]]:
        """Metric names in the metrics column (cell 2) of markdown table
        rows: concrete names plus ``<...>`` family rows (orphan-checked
        against f-string registrations via :meth:`_family_instance`).
        Backticked tokens with ``*`` are documented globs and skipped.  Rows
        inside the span/event index section belong to
        :meth:`_event_index_names`, not here."""
        found = []
        in_event_section = False
        for i, line in enumerate(doc_text.splitlines(), start=1):
            if line.startswith("#"):
                in_event_section = _EVENT_SECTION in line.lower()
                continue
            if in_event_section or not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 4:
                continue
            for m in re.finditer(r"`([^`]+)`", cells[2]):
                token = m.group(1)
                if "*" in token:
                    continue
                if "<" in token:
                    if MetricDocsRule._family_instance(token) is not None:
                        found.append((i, token))
                    continue
                if _CONCRETE.fullmatch(token):
                    found.append((i, token))
        return found

    @staticmethod
    def _event_index_names(doc_text: str) -> List[Tuple[int, str]]:
        """Span/flight-event names from the doc's "Span & flight-event
        index" section: the backticked tokens of each table row's first
        cell, until the next heading — concrete names plus ``<...>`` family
        rows; ``*`` globs are skipped."""
        found = []
        in_section = False
        for i, line in enumerate(doc_text.splitlines(), start=1):
            if line.startswith("#"):
                in_section = _EVENT_SECTION in line.lower()
                continue
            if not in_section or not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 3:
                continue
            for m in re.finditer(r"`([^`]+)`", cells[1]):
                token = m.group(1)
                if "*" in token:
                    continue
                if "<" in token:
                    if MetricDocsRule._family_instance(token) is not None:
                        found.append((i, token))
                    continue
                if _CONCRETE.fullmatch(token):
                    found.append((i, token))
        return found

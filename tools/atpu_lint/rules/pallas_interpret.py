"""Rule ``pallas-interpret``: every ``pl.pallas_call`` must thread an
``interpret=`` kwarg.

Pallas kernels only run compiled on a real TPU; everywhere else (CPU CI, dev
laptops, the CPU half of a TPU pod host) they need ``interpret=True`` to run
at all.  The repo's convention is that every kernel entry point accepts an
``interpret`` argument defaulting to ``_default_interpret()`` (off-TPU
autodetection — see ``accelerate_tpu/ops/flash_attention.py``) and threads it
into the ``pallas_call``.  A ``pallas_call`` with no ``interpret=`` kwarg
hard-codes TPU-only behavior and breaks the CPU A/B oracles the test suite is
built on, so it is a lint error even when the kernel "is only meant for TPU".

A ``**kwargs`` splat at the call site counts as threading (the kwarg may
arrive dynamically); ``# noqa: pallas-interpret`` lines are exempt.

Ported from ``tools/check_pallas_interpret.py``.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Diagnostic, Rule
from ._ast_utils import tail_name


class PallasInterpretRule(Rule):
    id = "pallas-interpret"
    summary = "every pallas_call threads interpret= so kernels run off-TPU"

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or tail_name(node.func) != "pallas_call":
                continue
            names = {kw.arg for kw in node.keywords}  # None marks a **splat
            if "interpret" in names or None in names:
                continue
            out.append(Diagnostic(
                ctx.rel, node.lineno, self.id,
                "pallas_call without interpret= — thread the caller's "
                "interpret flag (default _default_interpret()) so the kernel "
                "runs off-TPU",
            ))
        return out

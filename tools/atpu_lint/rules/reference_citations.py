"""Rule ``reference-citations``: docstring citations point at real
files/lines.

Docstrings across the package cite the upstream reference
(``/root/reference/...`` absolute paths, or ``reference <relpath>.py:<lines>``
shorthand rooted at the reference's ``src/accelerate/``) so parity claims are
checkable.  This rule — the analog of the reference repo's consistency bots
(``utils/check_copies.py`` and friends) — fails if a cited file does not
exist or a cited line number runs past the end of the file, which is how
citations rot when the docstring outlives an upstream refactor.

When the reference tree is absent (e.g. on CI) the rule reports a warning
and skips, matching the old script's behavior.

Ported from ``tools/check_reference_citations.py`` (including its
exact-path-first resolution: the basename fallback applies only when exactly
ONE file of that name exists — an ambiguous basename resolves to nothing).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from ..core import Diagnostic, Rule

ABS = re.compile(r"/root/reference/[\w/.-]+?\.(?:py|md|json|yml|yaml)(?::\d+(?:-\d+)?)?")
SHORT = re.compile(r"[Rr]eference(?:'s)?\s+`{0,2}([\w/.-]+\.py):(\d+)(?:-(\d+))?")
# any other backticked path:line citation — self-citations into this repo or
# bare reference cites without the "reference" prefix; resolved against both
# trees (a citation is stale only when NO candidate file covers the lines)
GENERIC = re.compile(r"`{1,2}([\w/.-]+\.py):(\d+)(?:-(\d+))?")


class ReferenceCitationsRule(Rule):
    id = "reference-citations"
    summary = "docstring path:line citations resolve against the reference/repo trees"

    def __init__(self):
        self._line_cache: Dict[str, Optional[int]] = {}
        self._ref_basenames: Optional[Dict[str, List[str]]] = None
        self._repo_basenames: Optional[Dict[str, List[str]]] = None
        self._warned = False

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/")

    # ------------------------------------------------------------- resolution
    def _file_lines(self, path: str) -> Optional[int]:
        if path not in self._line_cache:
            try:
                with open(path, "rb") as f:
                    self._line_cache[path] = sum(1 for _ in f)
            except OSError:
                self._line_cache[path] = None
        return self._line_cache[path]

    @staticmethod
    def _index_tree(root: str, skip=(".git", "__pycache__")) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in skip]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.setdefault(fn, []).append(os.path.join(dirpath, fn))
        return out

    def _resolve(self, project, relpath: str, include_repo: bool = False) -> Optional[int]:
        ref_root = str(project.reference_root)
        ref_src = os.path.join(ref_root, "src", "accelerate")
        bases = [ref_src, ref_root, os.path.join(ref_root, "src")]
        if include_repo:
            root = str(project.root)
            bases += [os.path.join(root, "accelerate_tpu"), root]
        for base in bases:
            total = self._file_lines(os.path.join(base, relpath))
            if total is not None:
                return total
        if self._ref_basenames is None:
            self._ref_basenames = self._index_tree(ref_root)
        candidates = list(self._ref_basenames.get(os.path.basename(relpath), []))
        if include_repo:
            if self._repo_basenames is None:
                self._repo_basenames = self._index_tree(str(project.root))
            candidates += self._repo_basenames.get(os.path.basename(relpath), [])
        totals = [t for t in (self._file_lines(c) for c in candidates) if t is not None]
        return totals[0] if len(totals) == 1 else None

    # ------------------------------------------------------------------ visit
    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        project = ctx.project
        ref_src = project.reference_root / "src" / "accelerate"
        if not ref_src.is_dir():
            if not self._warned:
                project.warn(
                    f"reference tree not present at {project.reference_root}; "
                    "skipping reference-citations"
                )
                self._warned = True
            return []
        out: List[Diagnostic] = []
        offsets = _line_offsets(src)
        seen_spans = []
        for m in ABS.finditer(src):
            seen_spans.append(m.span())
            cited = m.group(0)
            path, _, lines = cited.partition(":")
            total = self._file_lines(path)
            lineno = _lineno_at(offsets, m.start())
            if total is None:
                out.append(Diagnostic(ctx.rel, lineno, self.id,
                                      f"cited file missing: {cited}"))
            elif lines and int(lines.split("-")[-1]) > total:
                out.append(Diagnostic(
                    ctx.rel, lineno, self.id,
                    f"cited line {lines} past EOF ({total} lines): {cited}"))
        for m in SHORT.finditer(src):
            seen_spans.append(m.span())
            relpath, lo, hi = m.group(1), m.group(2), m.group(3)
            total = self._resolve(project, relpath)
            lineno = _lineno_at(offsets, m.start())
            if total is None:
                out.append(Diagnostic(ctx.rel, lineno, self.id,
                                      f"cited reference file missing: {relpath}"))
            elif int(hi or lo) > total:
                out.append(Diagnostic(
                    ctx.rel, lineno, self.id,
                    f"cited line {hi or lo} past EOF ({total} lines): "
                    f"reference {relpath}:{lo}{'-' + hi if hi else ''}"))
        for m in GENERIC.finditer(src):
            if any(a <= m.start() < b or a < m.end() <= b for a, b in seen_spans):
                continue  # already counted by ABS/SHORT
            relpath, lo, hi = m.group(1), m.group(2), m.group(3)
            total = self._resolve(project, relpath, include_repo=True)
            lineno = _lineno_at(offsets, m.start())
            if total is None:
                out.append(Diagnostic(ctx.rel, lineno, self.id,
                                      f"cited file missing: {relpath}"))
            elif int(hi or lo) > total:
                out.append(Diagnostic(
                    ctx.rel, lineno, self.id,
                    f"cited line {hi or lo} past EOF ({total} lines): "
                    f"{relpath}:{lo}{'-' + hi if hi else ''}"))
        return out


def _line_offsets(src: str) -> List[int]:
    offsets = [0]
    for line in src.splitlines(keepends=True):
        offsets.append(offsets[-1] + len(line))
    return offsets


def _lineno_at(offsets: List[int], pos: int) -> int:
    lo, hi = 0, len(offsets) - 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if offsets[mid] <= pos:
            lo = mid
        else:
            hi = mid - 1
    return lo + 1

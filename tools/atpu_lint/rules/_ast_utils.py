"""Shared AST helpers for atpu-lint rules.

Everything here is deliberately syntactic: atpu-lint runs with no jax import
and no type inference, so "is this callee jitted?" means "was a name in this
module visibly bound to a ``jax.jit`` / ``pjit`` / ``_serve_jit`` result (or
wrapped in a ``RecompileWatchdog``)", and dataflow is a linear walk over a
function's statements in source order with no branch sensitivity.  The
golden fixtures in ``tests/fixtures/lint/`` pin exactly what these
approximations catch.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

JIT_TAILS = ("jit", "pjit", "_serve_jit")


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain of Names, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def tail_name(node: ast.AST) -> str:
    """Trailing identifier of a Name / dotted Attribute, '' otherwise."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def literal_int_positions(node: Optional[ast.expr]) -> Optional[Tuple[int, ...]]:
    """``donate_argnums=2`` / ``donate_argnums=(1, 2)`` -> positions, else None."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None


def literal_str_names(node: Optional[ast.expr]) -> Tuple[str, ...]:
    """``donate_argnames=("cache",)`` / ``"cache"`` -> names, else ()."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
        return tuple(out)
    return ()


def entry_exempt_lines(tree: ast.Module,
                       entry_funcs: Sequence[str] = ("main", "_main")) -> Set[int]:
    """Line ranges inside entry-point functions and ``__main__`` guards."""
    lines: Set[int] = set()

    def mark(node: ast.AST) -> None:
        end = getattr(node, "end_lineno", node.lineno)
        lines.update(range(node.lineno, end + 1))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in entry_funcs:
                mark(node)
        elif isinstance(node, ast.If):
            test = node.test
            if (
                isinstance(test, ast.Compare)
                and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Eq)
            ):
                parts = [test.left] + list(test.comparators)
                names = [p.id for p in parts if isinstance(p, ast.Name)]
                consts = [p.value for p in parts if isinstance(p, ast.Constant)]
                if "__name__" in names and "__main__" in consts:
                    mark(node)
    return lines


@dataclasses.dataclass
class JitTarget:
    """One name visibly bound to a jit-compiled callable in this module."""

    name: str                                   # dotted binding ("step", "self._decode")
    donate_positions: Tuple[int, ...] = ()
    donate_names: Tuple[str, ...] = ()
    static_positions: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()

    @property
    def donates(self) -> bool:
        return bool(self.donate_positions or self.donate_names)


def _unwrap_jit_call(value: ast.expr) -> Optional[ast.Call]:
    """The ``jax.jit(...)``-shaped call inside ``value``, seeing through a
    ``RecompileWatchdog(<call>, ...)`` wrapper, else None."""
    if not isinstance(value, ast.Call):
        return None
    tail = tail_name(value.func)
    if tail in JIT_TAILS:
        return value
    if tail == "RecompileWatchdog" and value.args and isinstance(value.args[0], ast.Call):
        return _unwrap_jit_call(value.args[0])
    return None


def _jit_call_decorator(deco: ast.expr) -> Optional[ast.Call]:
    """``@jax.jit`` / ``@partial(jax.jit, ...)`` -> the call carrying the jit
    keywords (the partial call itself for the partial form)."""
    if isinstance(deco, (ast.Name, ast.Attribute)) and tail_name(deco) in JIT_TAILS:
        return None  # bare @jax.jit: jitted, but no keywords to read
    if isinstance(deco, ast.Call):
        if tail_name(deco.func) in JIT_TAILS:
            return deco
        if tail_name(deco.func) == "partial" and deco.args:
            if tail_name(deco.args[0]) in JIT_TAILS:
                return deco
    return None


def _target_from_call(name: str, call: Optional[ast.Call]) -> JitTarget:
    kw = {k.arg: k.value for k in (call.keywords if call is not None else []) if k.arg}
    return JitTarget(
        name=name,
        donate_positions=literal_int_positions(kw.get("donate_argnums")) or (),
        donate_names=literal_str_names(kw.get("donate_argnames")),
        static_positions=literal_int_positions(kw.get("static_argnums")) or (),
        static_names=literal_str_names(kw.get("static_argnames")),
    )


def build_jit_index(tree: ast.Module) -> Dict[str, JitTarget]:
    """name -> JitTarget for every binding this module visibly jit-compiles.

    Recognized shapes (anywhere in the module, including method bodies):

    * ``f = jax.jit(g, ...)`` / ``f = pjit(...)`` / ``f = _serve_jit(...)``
    * ``self._attr = _serve_jit(...)`` (recorded under ``self._attr``)
    * ``self._attr = RecompileWatchdog(_serve_jit(...), ...)``
    * ``@jax.jit`` / ``@partial(jax.jit, donate_argnums=...)`` on a def
    """
    index: Dict[str, JitTarget] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            name = dotted(node.targets[0])
            call = _unwrap_jit_call(node.value)
            if name and call is not None:
                index[name] = _target_from_call(name, call)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                is_bare = (
                    isinstance(deco, (ast.Name, ast.Attribute))
                    and tail_name(deco) in JIT_TAILS
                )
                call = _jit_call_decorator(deco)
                if is_bare or call is not None:
                    index[node.name] = _target_from_call(node.name, call)
                    break
    return index


#: call tails that mark a binding as a device executable even without a
#: visible jax.jit: the pool factory convention plus the watchdog wrapper
EXEC_WRAPPER_TAILS = {"RecompileWatchdog"} | set(JIT_TAILS)


def build_executable_index(tree: ast.Module) -> Set[str]:
    """Dotted names visibly bound to device executables in this module.

    Beyond the resolvable jit bindings of :func:`build_jit_index`, serving
    code binds executables through wrappers the index can't see inside —
    ``self._decode = RecompileWatchdog(make_paged_decode_window(...), ...)``,
    dict comprehensions of per-bucket executables, conditional expressions.
    A binding counts when its value subtree contains a call to ``jit`` /
    ``pjit`` / ``_serve_jit`` / ``RecompileWatchdog`` or to a ``make_*`` pool
    factory.  Calls through these names (including ``self._prefill[bucket]``
    subscript dispatch) are treated as jitted dispatches by the dataflow
    rules.
    """
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        name = dotted(node.targets[0])
        if not name:
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                tail = tail_name(sub.func)
                if tail in EXEC_WRAPPER_TAILS or tail.startswith("make_"):
                    names.add(name)
                    break
    return names


def callee_executable_name(call: ast.Call) -> Optional[str]:
    """The dotted binding a call dispatches through: ``self._decode(...)`` ->
    ``self._decode``; ``self._prefill[bucket](...)`` -> ``self._prefill``."""
    func = call.func
    if isinstance(func, ast.Subscript):
        return dotted(func.value)
    return dotted(func)


@dataclasses.dataclass
class LinearStmt:
    """One statement (or compound-statement header) in source order, with the
    dotted names it loads and stores in its *own* expressions (nested block
    bodies become their own LinearStmt entries)."""

    node: ast.stmt
    loads: Set[str]
    stores: Set[str]
    calls: List[ast.Call]

    @property
    def lineno(self) -> int:
        return self.node.lineno


def _names_in(exprs: Sequence[Optional[ast.expr]], ctx_types) -> Set[str]:
    out: Set[str] = set()
    for expr in exprs:
        if expr is None:
            continue
        for node in ast.walk(expr):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ctx_types
            ):
                name = dotted(node)
                if name:
                    out.add(name)
    return out


def _calls_in(exprs: Sequence[Optional[ast.expr]]) -> List[ast.Call]:
    out: List[ast.Call] = []
    for expr in exprs:
        if expr is None:
            continue
        out.extend(n for n in ast.walk(expr) if isinstance(n, ast.Call))
    return out


def _own_exprs(stmt: ast.stmt) -> Tuple[List[ast.expr], List[ast.expr]]:
    """(value-side exprs, target-side exprs) belonging to the statement
    itself, excluding nested statement blocks."""
    if isinstance(stmt, ast.Assign):
        return [stmt.value], list(stmt.targets)
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value, stmt.target], [stmt.target]
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.value], [stmt.target]
    if isinstance(stmt, ast.Expr):
        return [stmt.value], []
    if isinstance(stmt, ast.Return):
        return [stmt.value], []
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test], []
    if isinstance(stmt, ast.For):
        return [stmt.iter], [stmt.target]
    if isinstance(stmt, ast.With):
        vals = [item.context_expr for item in stmt.items]
        tgts = [item.optional_vars for item in stmt.items if item.optional_vars]
        return vals, tgts
    if isinstance(stmt, ast.Assert):
        return [stmt.test, stmt.msg], []
    if isinstance(stmt, (ast.Raise,)):
        return [stmt.exc, stmt.cause], []
    if isinstance(stmt, ast.Delete):
        return [], list(stmt.targets)
    return [], []


def linearize(fn: ast.AST) -> List[LinearStmt]:
    """Flatten a function body into source-ordered LinearStmt records.
    Nested function/class defs are skipped (they get their own analysis)."""
    out: List[LinearStmt] = []

    def visit_block(body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            values, targets = _own_exprs(stmt)
            loads = _names_in(values, (ast.Load,))
            # subscript/attribute stores also *load* their base (self.x[i] = v
            # reads self.x); dotted() on a Store-ctx chain captures the name
            stores = _names_in(targets, (ast.Store,))
            loads |= _names_in(targets, (ast.Load,))
            out.append(LinearStmt(stmt, loads, stores, _calls_in(values + targets)))
            for block in ("body", "orelse", "finalbody"):
                visit_block(getattr(stmt, block, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                visit_block(handler.body)

    body = getattr(fn, "body", [])
    visit_block(body)
    return out


def iter_functions(tree: ast.Module) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def call_arg_names(call: ast.Call, tuple_map: Dict[str, List[ast.expr]]) -> List[Optional[str]]:
    """Dotted names of a call's positional args, expanding ``*args`` splats
    through ``tuple_map`` (name -> tuple-literal elements assigned earlier in
    the same function).  Non-name args yield None placeholders so positions
    line up with ``donate_argnums``."""
    out: List[Optional[str]] = []
    for arg in call.args:
        if isinstance(arg, ast.Starred):
            inner = dotted(arg.value)
            elements = tuple_map.get(inner or "", [])
            if elements:
                out.extend(dotted(e) for e in elements)
            else:
                out.append(None)
        else:
            out.append(dotted(arg))
    return out


def tuple_literal_map(stmts: Sequence[LinearStmt]) -> Dict[str, List[ast.expr]]:
    """name -> elements for simple ``name = (e1, e2, ...)`` assignments."""
    out: Dict[str, List[ast.expr]] = {}
    for ls in stmts:
        node = ls.node
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            out[node.targets[0].id] = list(node.value.elts)
    return out

"""Rule ``sharding-annotations``: every jit in the serving package threads
explicit shardings.

Serving executables are compiled once and reused across thousands of steps;
a ``jax.jit``/``pjit`` without ``in_shardings``/``out_shardings`` leaves
placement to GSPMD's propagation pass, which is free to pick a layout that
silently diverges from the head-sharded KV pool (a resharding collective in
the decode loop, or worse, a replicated pool that quietly undoes the tp
memory win).  So inside ``accelerate_tpu/serving/`` every ``jax.jit`` /
``jax.pjit`` / bare ``jit(...)`` call must pass at least one of the
``in_shardings`` / ``out_shardings`` keywords — in practice by going through
``pool._serve_jit``, which threads both or documents why not.

An intentionally unconstrained call carries ``# noqa: sharding-annotations``
with a reason (the legacy bare ``# noqa: sharding`` is honored with a
migration warning).  Decorator usage (``@jax.jit``) is a call node too and
is checked the same way.

Ported from ``tools/check_sharding_annotations.py``.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Diagnostic, Rule
from ._ast_utils import tail_name

JIT_NAMES = ("jit", "pjit")
SHARDING_KWARGS = ("in_shardings", "out_shardings")


class ShardingAnnotationsRule(Rule):
    id = "sharding-annotations"
    summary = "every jit in serving/ passes in_shardings/out_shardings"

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/serving/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        out = []
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and tail_name(node.func) in JIT_NAMES
                and not any(kw.arg in SHARDING_KWARGS for kw in node.keywords)
            ):
                out.append(Diagnostic(
                    ctx.rel, node.lineno, self.id,
                    "jit without in_shardings/out_shardings — route it "
                    "through pool._serve_jit or add "
                    "'# noqa: sharding-annotations' with a reason",
                ))
        return out

"""Rule ``method-lru-cache``: no ``functools.lru_cache`` / ``functools.cache``
on instance methods.

An lru_cache on a method keys its cache on ``self``: every instance gets its
own entry, the cache keeps each instance alive for the lifetime of the class
(a memory leak), and per-instance state silently defeats the dedupe the cache
was meant to provide — exactly the bug class fixed in
``MultiProcessAdapter.warning_once`` (see ``accelerate_tpu/logging.py``).
Module-level functions are fine; methods must use an explicit container keyed
on what they actually mean to dedupe (a module-level set/dict, or
``functools.cached_property`` for a compute-once attribute).

Exempt: ``accelerate_tpu/test_utils/`` and ``accelerate_tpu/commands/``
(short-lived CLI/test objects can't leak long), ``@staticmethod`` methods
(no ``self``/``cls`` in the key), and ``# noqa: method-lru-cache`` lines.

Ported from ``tools/check_no_method_lru_cache.py``.
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import List

from ..core import Diagnostic, Rule

EXEMPT_DIRS = ("test_utils", "commands")
BANNED = ("lru_cache", "cache")


def _deco_name(deco: ast.expr) -> str:
    target = deco.func if isinstance(deco, ast.Call) else deco
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
        return f"{target.value.id}.{target.attr}"
    return ""


def _is_banned(deco: ast.expr) -> bool:
    name = _deco_name(deco)
    return name in BANNED or name in tuple(f"functools.{b}" for b in BANNED)


class MethodLruCacheRule(Rule):
    id = "method-lru-cache"
    summary = "no functools.lru_cache/cache on instance methods (keys on self, leaks)"

    def applies_to(self, rel: str) -> bool:
        parts = PurePosixPath(rel).parts
        if parts[-1] == "__main__.py":
            return False
        if parts[0] == "accelerate_tpu":
            return len(parts) < 2 or parts[1] not in EXEMPT_DIRS
        return parts[:2] == ("tools", "atpu_lint")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        out = []
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                deco_names = [_deco_name(d) for d in fn.decorator_list]
                if "staticmethod" in deco_names:
                    continue
                args = fn.args.posonlyargs + fn.args.args
                if not args or args[0].arg not in ("self", "cls"):
                    continue
                for deco in fn.decorator_list:
                    if not _is_banned(deco):
                        continue
                    out.append(Diagnostic(
                        ctx.rel, deco.lineno, self.id,
                        f"functools.{_deco_name(deco).split('.')[-1]} on method "
                        f"{cls.name}.{fn.name} — the cache keys on "
                        f"{args[0].arg!r}, leaking every instance and deduping "
                        "per-instance; use a module-level container or "
                        "cached_property",
                    ))
        return out

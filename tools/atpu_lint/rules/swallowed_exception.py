"""Rule ``swallowed-exception``: broad excepts in ``serving/`` must re-raise
or route the error somewhere an operator can see it.

The serving stack's fault-tolerance contract (ISSUE 13) is that failures are
*schedulable events*: a poisoned step reaches the router supervisor, a dead
stream closes with its error, a refused ticket propagates to the handler
thread.  A ``except Exception: pass`` (or a bare ``except``) anywhere on
that path silently converts a recoverable failure into a hung request — the
exact bug class chaos testing exists to catch, and one that stays invisible
in single-threaded tests.

Flagged inside ``accelerate_tpu/serving/``: any handler catching
``Exception`` / ``BaseException`` (bare ``except`` included, alone or in a
tuple) whose body neither

* re-raises (``raise`` anywhere in the handler), nor
* routes the error to a sanctioned sink — the flight recorder
  (``.record(...)`` / ``logger.exception``), the stream-failure path
  (``stream.close``, ``_fail_outstanding``), the HTTP error surface
  (``_safe_error`` / ``_admission_refused`` / ``_send`` / ``error_body``),
  or recovery (``cancel`` / ``_eject_and_replay``), nor
* stores it for a waiting thread (assignment to a name/attribute containing
  ``error`` — the ticket rendezvous pattern ``t.error = exc``).

Escape hatch: ``# noqa: swallowed-exception`` with a justifying comment on
the ``except`` line (e.g. best-effort writes to a socket that is already
gone).
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Diagnostic, Rule
from ._ast_utils import dotted

#: exception names whose broad catch demands a re-raise or a sink
BROAD_NAMES = ("Exception", "BaseException")
#: terminal call names that count as routing the error somewhere visible
SANCTIONED_SINKS = (
    "record", "exception", "_fail_outstanding", "close", "_safe_error",
    "_admission_refused", "_send", "error_body", "cancel",
    "_eject_and_replay",
)


def _is_broad(expr) -> bool:
    """Does this ``except`` type expression catch Exception/BaseException?"""
    if expr is None:
        return True  # bare except
    if isinstance(expr, ast.Tuple):
        return any(_is_broad(e) for e in expr.elts)
    name = dotted(expr)
    return name is not None and name.rsplit(".", 1)[-1] in BROAD_NAMES


def _handled(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, routes to a sanctioned sink, or
    stores the error for another thread."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name is not None and name.rsplit(".", 1)[-1] in SANCTIONED_SINKS:
                return True
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                label = t.attr if isinstance(t, ast.Attribute) else (
                    t.id if isinstance(t, ast.Name) else ""
                )
                if "error" in label.lower():
                    return True
    return False


class SwallowedExceptionRule(Rule):
    id = "swallowed-exception"
    summary = ("broad excepts in serving/ must re-raise or route the error "
               "to the flight recorder / stream-failure path")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/serving/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        out = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            if _handled(node):
                continue
            caught = "bare except" if node.type is None else (
                f"except {ast.unparse(node.type)}"
            )
            out.setdefault(node.lineno, Diagnostic(
                ctx.rel, node.lineno, self.id,
                f"{caught} swallows the error — re-raise, record it "
                "(flight recorder / logger.exception), close the stream "
                "with it, or justify with '# noqa: swallowed-exception'",
            ))
        return [out[k] for k in sorted(out)]

"""Rule ``handler-blocking``: HTTP handler threads cross into the engine
only through the sanctioned FrontDoor API.

The front door's threading contract (``accelerate_tpu/serving/api/``) is
that every engine host-state mutation happens on the one FrontDoor driver
thread; ``ThreadingHTTPServer`` handler threads talk to it exclusively via
the ticket API (``submit`` / ``cancel`` / ``hot_swap`` / ...) and the
per-request :class:`~accelerate_tpu.serving.api.frontdoor.TokenStream`
queues.  A handler that reaches through to ``router.step()``, pokes an
``engine`` attribute, or blocks on a device readback races the driver and
corrupts slot state — and, like a stray ``device_get`` in the serve loop,
it usually still produces correct tokens in a single-threaded test.

Three shapes are flagged inside ``accelerate_tpu/serving/api/`` (with
``frontdoor.py`` itself exempt — it *is* the sanctioned crossing point):

* imports of serving internals (``engine``, ``router``, ``scheduler``, the
  executable pool) — handler modules may import ``errors`` and the api
  package only;
* attribute chains that use ``engine`` / ``engines`` / ``router`` /
  ``scheduler`` as a receiver (``frontdoor.router.submit(...)``);
* blocking device materialization (``device_get`` / ``block_until_ready`` /
  ``fetch``) — handler threads block on ``TokenStream.get`` and nothing
  else.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Diagnostic, Rule
from ._ast_utils import dotted

#: calls that materialize device state — handler threads never block on these
BLOCKING_NAMES = ("device_get", "block_until_ready", "fetch")
#: receiver names that mean the chain reached past FrontDoor into the engine
ENGINE_RECEIVERS = ("engine", "engines", "router", "scheduler")
#: serving-internal module tails only frontdoor.py may import
FORBIDDEN_IMPORT_TAILS = (
    "engine", "router", "scheduler", "pool", "paging", "prefix_cache",
    "readback", "spec",
)


def _chain(node: ast.AST) -> Optional[List[str]]:
    name = dotted(node)
    return name.split(".") if name else None


class HandlerBlockingRule(Rule):
    id = "handler-blocking"
    summary = ("HTTP handlers cross into the engine only via the FrontDoor "
               "submit/cancel/queue API")

    def applies_to(self, rel: str) -> bool:
        return (
            rel.startswith("accelerate_tpu/serving/api/")
            and not rel.endswith("/frontdoor.py")
        )

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        out = {}

        def flag(node: ast.AST, message: str) -> None:
            # one diagnostic per line: a Call and its Attribute func both match
            out.setdefault(
                node.lineno, Diagnostic(ctx.rel, node.lineno, self.id, message)
            )

        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                module = node.module or ""
                tail = module.rsplit(".", 1)[-1]
                if tail in FORBIDDEN_IMPORT_TAILS and (
                    node.level >= 1 or "serving" in module
                ):
                    flag(node,
                         f"handler module imports serving internals "
                         f"({module}) — only frontdoor.py crosses into the "
                         "engine; handlers use the FrontDoor API")
                continue
            if isinstance(node, ast.Call):
                parts = _chain(node.func)
            elif isinstance(node, ast.Attribute):
                # the attribute access itself is the crossing: passing
                # ``frontdoor.router`` around escapes just as hard when used
                parts = _chain(node)
            else:
                continue
            if not parts:
                continue
            tail = parts[-1]
            if tail in BLOCKING_NAMES:
                flag(node,
                     f"blocking device readback ({tail}) on an HTTP handler "
                     "thread — handlers block only on TokenStream.get; the "
                     "FrontDoor driver owns all device materialization")
            elif any(seg in ENGINE_RECEIVERS for seg in parts[:-1]):
                flag(node,
                     f"direct engine crossing ({'.'.join(parts)}) from a "
                     "handler thread — route through the FrontDoor "
                     "submit/cancel/ticket API (frontdoor.py is the "
                     "sanctioned crossing point)")
        return [out[k] for k in sorted(out)]

"""Rule ``use-after-donate``: donated device buffers are dead on dispatch —
never read one afterwards, and never drop the old handle mid-flight.

Two findings, both from the bug class the async pipelined serve loop (PR 9)
hit:

* **read-after-donate** — a name passed at a ``donate_argnums`` /
  ``donate_argnames`` position of a jit call visibly donating in this module
  is read again in the same function before being rebound.  The buffer was
  aliased into the computation's outputs; the read sees freed memory (jax
  raises on CPU, silently corrupts on deferred paths).

* **dropped-handle** — the donate-and-rebind idiom
  (``kv.pages, toks = self._decode(params, kv.pages, ...)``) rebinds a device
  handle that the just-dispatched window consumes, WITHOUT parking the old
  handle first.  Dropping the last Python reference to a consumed handle
  blocks until the consuming computation retires — the engine re-serializes
  and every overlap the pipeline exists for silently disappears, with tokens
  staying bit-identical (the exact regression ``serving/readback.py``'s
  ``Readback.consumed`` parking fixes).  The rebind is clean when the old
  handles were parked into a surviving binding beforehand (``consumed =
  [kv.pages_k, ...]``) or when the function drains synchronously (a
  ``fetch(...)`` / ``_drain_inflight(...)`` call after the dispatch, so no
  window escapes in flight).

Detection is linear per function (no branch sensitivity) and recognizes
executables by the module's visible bindings (``jax.jit``/``pjit``/
``_serve_jit`` results, ``RecompileWatchdog``-wrapped pool ``make_*``
factories, per-bucket dicts thereof); ``*args`` splats are expanded through
same-function tuple literals.  Scope: ``accelerate_tpu/serving/``.  Escape:
``# noqa: use-after-donate`` with a justifying comment.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from ..core import Diagnostic, Rule
from ._ast_utils import (
    LinearStmt,
    build_executable_index,
    build_jit_index,
    call_arg_names,
    callee_executable_name,
    dotted,
    iter_functions,
    linearize,
    tail_name,
    tuple_literal_map,
)

DRAIN_MARKERS = {"fetch", "_drain_inflight"}


def _targets_of(stmt: ast.stmt) -> List[str]:
    """Flattened dotted assignment-target names of an Assign statement."""
    if not isinstance(stmt, ast.Assign):
        return []
    out: List[str] = []

    def flatten(node: ast.expr) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                flatten(elt)
        elif isinstance(node, ast.Starred):
            flatten(node.value)
        else:
            name = dotted(node)
            if name:
                out.append(name)

    for target in stmt.targets:
        flatten(target)
    return out


def _top_call(stmt: ast.stmt) -> Optional[ast.Call]:
    value = getattr(stmt, "value", None)
    return value if isinstance(value, ast.Call) else None


def _is_parking_stmt(ls: LinearStmt, name: str) -> bool:
    """Does this statement park ``name`` into a surviving binding?  An Assign
    or AugAssign whose value side loads the name (``consumed = [x, ...]``,
    ``consumed += [x]``), or a ``something.append(x)`` / ``.extend([... x])``
    call.  A bare call argument (``audit_donation(x)``) does NOT park — the
    reference dies with the call."""
    node = ls.node
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        value = node.value
        if value is not None:
            for sub in ast.walk(value):
                if isinstance(sub, (ast.Name, ast.Attribute)) and dotted(sub) == name:
                    return True
        return False
    if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        call = node.value
        if tail_name(call.func) in ("append", "extend"):
            for arg in call.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, (ast.Name, ast.Attribute)) and dotted(sub) == name:
                        return True
    return False


def _has_drain_after(stmts: Sequence[LinearStmt], idx: int) -> bool:
    for ls in stmts[idx + 1:]:
        for call in ls.calls:
            if tail_name(call.func) in DRAIN_MARKERS:
                return True
    return False


class UseAfterDonateRule(Rule):
    id = "use-after-donate"
    summary = "no read of a donated buffer; donate-and-rebind must park old handles"

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("accelerate_tpu/serving/")

    def visit(self, tree, src, ctx) -> List[Diagnostic]:
        jit_index = build_jit_index(tree)
        executables = build_executable_index(tree) | set(jit_index)
        out: List[Diagnostic] = []
        for fn in iter_functions(tree):
            out.extend(self._check_function(fn, jit_index, executables, ctx))
        return out

    def _check_function(self, fn, jit_index, executables: Set[str], ctx) -> List[Diagnostic]:
        stmts = linearize(fn)
        tuple_map = tuple_literal_map(stmts)
        out: List[Diagnostic] = []
        reported: Set[tuple] = set()
        for idx, ls in enumerate(stmts):
            call = _top_call(ls.node)
            if call is None:
                continue
            callee = callee_executable_name(call)
            targets = _targets_of(ls.node)
            arg_names = call_arg_names(call, tuple_map)
            arg_set = {a for a in arg_names if a}

            # --- read-after-donate: resolvable donate positions ------------
            target = jit_index.get(dotted(call.func) or "")
            if target is not None and target.donates:
                donated = [
                    arg_names[i]
                    for i in target.donate_positions
                    if i < len(arg_names) and arg_names[i]
                ]
                donated += [
                    dotted(kw.value)
                    for kw in call.keywords
                    if kw.arg in target.donate_names and dotted(kw.value)
                ]
                for name in donated:
                    if name in targets:
                        continue  # rebound by this very statement
                    for later in stmts[idx + 1:]:
                        if name in later.loads and (later.lineno, name) not in reported:
                            reported.add((later.lineno, name))
                            out.append(Diagnostic(
                                ctx.rel, later.lineno, self.id,
                                f"'{name}' was donated to {target.name}() on "
                                f"line {ls.lineno} and is read here — the "
                                "buffer is dead after dispatch; use the "
                                "returned handle instead",
                            ))
                        if name in later.stores:
                            break

            # --- dropped-handle: donate-and-rebind without parking ---------
            if callee not in executables:
                continue
            rebound = sorted(arg_set & set(targets))
            if not rebound:
                continue
            if _has_drain_after(stmts, idx):
                continue  # synchronous drain: no window escapes in flight
            unparked = [
                name for name in rebound
                if not any(
                    _is_parking_stmt(prev, name) and prev.node is not ls.node
                    for prev in stmts[:idx]
                )
            ]
            if unparked and (ls.lineno, "rebind") not in reported:
                reported.add((ls.lineno, "rebind"))
                out.append(Diagnostic(
                    ctx.rel, ls.lineno, self.id,
                    f"donate-and-rebind of {', '.join(unparked)} through "
                    f"{callee}(...) drops the old device handle(s) while the "
                    "dispatched window may still consume them — dropping the "
                    "last reference blocks until the window retires and "
                    "silently re-serializes the pipeline; park the old "
                    "handles (e.g. on Readback.consumed) before dispatch, or "
                    "drain with fetch() in this function",
                ))
        return out

"""Rule registry: one place every rule is declared, so the runner, the CLI's
``--list-rules``/``--select``, the noqa validator, and the docs all agree on
the rule set."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from ..core import Rule
from .bare_print import BarePrintRule
from .blocking_readback import BlockingReadbackRule
from .handler_blocking import HandlerBlockingRule
from .implicit_host_sync import ImplicitHostSyncRule
from .jit_signature_drift import JitSignatureDriftRule
from .metric_docs import MetricDocsRule
from .method_lru_cache import MethodLruCacheRule
from .pallas_interpret import PallasInterpretRule
from .reference_citations import ReferenceCitationsRule
from .sharding_annotations import ShardingAnnotationsRule
from .swallowed_exception import SwallowedExceptionRule
from .use_after_donate import UseAfterDonateRule

#: declaration order is display order in --list-rules and the docs
ALL_RULES: List[Type[Rule]] = [
    BarePrintRule,
    BlockingReadbackRule,
    HandlerBlockingRule,
    MethodLruCacheRule,
    PallasInterpretRule,
    MetricDocsRule,
    ShardingAnnotationsRule,
    ReferenceCitationsRule,
    UseAfterDonateRule,
    ImplicitHostSyncRule,
    JitSignatureDriftRule,
    SwallowedExceptionRule,
]

RULES_BY_ID: Dict[str, Type[Rule]] = {cls.id: cls for cls in ALL_RULES}


def get_rules(select: Optional[Sequence[str]] = None) -> List[Rule]:
    """Fresh rule instances (rules keep per-run state), optionally narrowed
    to the given ids.  Unknown ids raise ``KeyError`` with the valid set."""
    if select is None:
        return [cls() for cls in ALL_RULES]
    unknown = [rid for rid in select if rid not in RULES_BY_ID]
    if unknown:
        raise KeyError(
            f"unknown rule id(s): {', '.join(unknown)} — valid: "
            f"{', '.join(sorted(RULES_BY_ID))}"
        )
    return [RULES_BY_ID[rid]() for rid in select]

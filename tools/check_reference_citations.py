#!/usr/bin/env python
"""Repo-consistency check: reference citations must point at real files/lines.

Docstrings across the package cite the upstream reference
(``/root/reference/...`` absolute paths, or ``reference <relpath>.py:<lines>``
shorthand rooted at the reference's ``src/accelerate/``) so parity claims are
checkable.  This script — the analog of the reference repo's consistency bots
(``utils/check_copies.py`` and friends) — fails if a cited file does not
exist or a cited line number runs past the end of the file, which is how
citations rot when the docstring outlives an upstream refactor.

Exit 0 = all citations resolve (or the reference tree is absent, e.g. on CI —
reported and skipped).  Wired into ``make quality``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "accelerate_tpu")
REF_ROOT = "/root/reference"
REF_SRC = os.path.join(REF_ROOT, "src", "accelerate")

ABS = re.compile(r"/root/reference/[\w/.-]+?\.(?:py|md|json|yml|yaml)(?::\d+(?:-\d+)?)?")
SHORT = re.compile(r"[Rr]eference(?:'s)?\s+`{0,2}([\w/.-]+\.py):(\d+)(?:-(\d+))?")
# any other backticked path:line citation — self-citations into this repo or
# bare reference cites without the "reference" prefix; resolved against both
# trees (a citation is stale only when NO candidate file covers the lines)
GENERIC = re.compile(r"`{1,2}([\w/.-]+\.py):(\d+)(?:-(\d+))?")


def _file_lines(cache: dict, path: str) -> int | None:
    if path not in cache:
        try:
            with open(path, "rb") as f:
                cache[path] = sum(1 for _ in f)
        except OSError:
            cache[path] = None
    return cache[path]


_BASENAMES: dict = {}


def _basename_index() -> dict:
    """basename -> [paths] over the whole reference tree (built once)."""
    if not _BASENAMES:
        for dirpath, dirnames, filenames in os.walk(REF_ROOT):
            dirnames[:] = [d for d in dirnames if d != ".git"]
            for fn in filenames:
                if fn.endswith(".py"):
                    _BASENAMES.setdefault(fn, []).append(os.path.join(dirpath, fn))
    return _BASENAMES


def _resolve(cache: dict, relpath: str, include_repo: bool = False) -> int | None:
    """Line count of a shorthand-cited reference file.  Docstrings cite
    relative to ``src/accelerate/`` ("utils/dataclasses.py"), the repo root
    ("tests/test_multigpu.py", "benchmarks/..."), or by bare filename when the
    module mirrors its reference counterpart ("operations.py").  Resolution is
    exact-path first, in base-priority order — taking the max across colliding
    candidates would let any long same-named file mask a stale citation.  The
    basename fallback applies only when exactly ONE file of that name exists;
    an ambiguous basename resolves to nothing (cite a qualified path instead).
    ``include_repo`` additionally resolves against this repo's own tree (the
    GENERIC self-citation form, e.g. ``models/transformer.py:208``)."""
    bases = [REF_SRC, REF_ROOT, os.path.join(REF_ROOT, "src")]
    if include_repo:
        bases += [PKG, REPO, os.path.join(REPO, "accelerate_tpu")]
    for base in bases:
        total = _file_lines(cache, os.path.join(base, relpath))
        if total is not None:
            return total
    candidates = list(_basename_index().get(os.path.basename(relpath), []))
    if include_repo:
        candidates += _repo_basename_index().get(os.path.basename(relpath), [])
    totals = [t for t in (_file_lines(cache, c) for c in candidates) if t is not None]
    return totals[0] if len(totals) == 1 else None


_REPO_BASENAMES: dict = {}


def _repo_basename_index() -> dict:
    if not _REPO_BASENAMES:
        for dirpath, dirnames, filenames in os.walk(REPO):
            dirnames[:] = [d for d in dirnames if d not in (".git", "__pycache__")]
            for fn in filenames:
                if fn.endswith(".py"):
                    _REPO_BASENAMES.setdefault(fn, []).append(os.path.join(dirpath, fn))
    return _REPO_BASENAMES


def check() -> int:
    if not os.path.isdir(REF_SRC):
        print(f"reference tree not present at {REF_ROOT}; skipping citation check")
        return 0
    cache: dict = {}
    problems = []
    n_citations = 0
    for dirpath, _dirnames, filenames in os.walk(PKG):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            src = os.path.join(dirpath, fn)
            with open(src, encoding="utf-8") as f:
                text = f.read()
            rel = os.path.relpath(src, REPO)
            seen_spans = []
            for m in ABS.finditer(text):
                n_citations += 1
                seen_spans.append(m.span())
                cited = m.group(0)
                path, _, lines = cited.partition(":")
                total = _file_lines(cache, path)
                if total is None:
                    problems.append(f"{rel}: cited file missing: {cited}")
                elif lines and int(lines.split("-")[-1]) > total:
                    problems.append(
                        f"{rel}: cited line {lines} past EOF ({total} lines): {cited}"
                    )
            for m in SHORT.finditer(text):
                n_citations += 1
                seen_spans.append(m.span())
                relpath, lo, hi = m.group(1), m.group(2), m.group(3)
                total = _resolve(cache, relpath)
                if total is None:
                    problems.append(f"{rel}: cited reference file missing: {relpath}")
                elif int(hi or lo) > total:
                    problems.append(
                        f"{rel}: cited line {hi or lo} past EOF ({total} lines): "
                        f"reference {relpath}:{lo}{'-' + hi if hi else ''}"
                    )
            for m in GENERIC.finditer(text):
                if any(a <= m.start() < b or a < m.end() <= b for a, b in seen_spans):
                    continue  # already counted by ABS/SHORT
                n_citations += 1
                relpath, lo, hi = m.group(1), m.group(2), m.group(3)
                total = _resolve(cache, relpath, include_repo=True)
                if total is None:
                    problems.append(f"{rel}: cited file missing: {relpath}")
                elif int(hi or lo) > total:
                    problems.append(
                        f"{rel}: cited line {hi or lo} past EOF ({total} lines): "
                        f"{relpath}:{lo}{'-' + hi if hi else ''}"
                    )
    for p in problems:
        print(f"STALE CITATION  {p}")
    print(f"{n_citations} citations checked, {len(problems)} stale")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(check())

#!/usr/bin/env python
"""Lint: every ``pl.pallas_call`` must thread an ``interpret=`` kwarg.

Pallas kernels only run compiled on a real TPU; everywhere else (CPU CI, dev
laptops, the CPU half of a TPU pod host) they need ``interpret=True`` to run
at all.  The repo's convention is that every kernel entry point accepts an
``interpret`` argument defaulting to ``_default_interpret()`` (off-TPU
autodetection — see ``accelerate_tpu/ops/flash_attention.py``) and threads it
into the ``pallas_call``.  A ``pallas_call`` with no ``interpret=`` kwarg
hard-codes TPU-only behavior and breaks the CPU A/B oracles the test suite is
built on, so it is a lint error even when the kernel "is only meant for TPU".

A ``**kwargs`` splat at the call site counts as threading (the kwarg may
arrive dynamically); lines carrying a ``# noqa: pallas-interpret`` pragma are
exempt.

Exit status 1 with one ``path:line`` diagnostic per violation; 0 when clean.
Wired into ``make quality``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "accelerate_tpu"
PRAGMA = "noqa: pallas-interpret"


def _is_pallas_call(node: ast.Call) -> bool:
    """Matches ``pl.pallas_call(...)`` / ``pallas_call(...)`` under any alias
    whose attribute name is exactly ``pallas_call``."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "pallas_call"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "pallas_call"
    return False


def check_file(path: Path) -> list:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:  # quality target also runs compileall; be loud
        return [f"{path}:{exc.lineno}: syntax error: {exc.msg}"]
    src_lines = source.splitlines()
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_pallas_call(node):
            continue
        names = {kw.arg for kw in node.keywords}  # None marks a **splat
        if "interpret" in names or None in names:
            continue
        if PRAGMA in src_lines[node.lineno - 1]:
            continue
        rel = path.relative_to(REPO_ROOT)
        violations.append(
            f"{rel}:{node.lineno}: pallas_call without interpret= — thread the "
            "caller's interpret flag (default _default_interpret()) so the "
            "kernel runs off-TPU"
        )
    return violations


def main() -> int:
    violations = []
    for path in sorted(PACKAGE.rglob("*.py")):
        violations.extend(check_file(path))
    for v in violations:
        print(v)
    if violations:
        print(f"check_pallas_interpret: {len(violations)} violation(s)")
        return 1
    print("check_pallas_interpret: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Big-model streaming-inference benchmark — tokens/s with host-resident weights.

The reference's only published benchmark is big-model inference with CPU/disk
offload (``/root/reference/benchmarks/big_model_inference.py``;
``benchmarks/README.md:27-37``): e.g. OPT-30B fp16 with CPU offload generates
at 2.37 s/token on 2x Titan RTX — every token streams the full 60GB of weights
host→GPU, an effective ~25 GB/s of overlapped transfer.

This benchmark measures the same engine quality on TPU: model weights live in
host RAM, :class:`StreamingTransformer` double-buffers them layer-by-layer into
HBM while the MXU computes.  Tasks:

* ``--task decode`` (default) — THE reference workload: autoregressive
  generation with a KV cache, every token streaming the full weight set
  host→HBM.  Reports decode tokens/s and s/token
  (``benchmarks/big_model_inference.py:141-155`` measures exactly this);
* ``--task prefill`` — batch x seq tokens per forward / wall time;
* ``--task serve`` — the continuous-batching engine
  (:mod:`accelerate_tpu.serving`) on a log-normal mixed-length workload vs
  static ``generate`` over the same requests in FCFS groups padded to the
  workload max — the padding + lockstep waste the slot pool exists to
  reclaim.  HBM-resident weights (serving is not an offload bench); reports
  tokens/s, per-token latency percentiles, slot occupancy, and ``vs_baseline``
  = engine tokens/s over static tokens/s.
* ``--task spec`` — speculative decoding A/B: the SAME serving engine with
  ``speculate_k`` on vs off over a repetitive (tiled-motif) greedy workload —
  n-gram drafting's home turf.  Outputs must be token-identical between the
  runs (the bench hard-fails otherwise; verification is exact), and
  ``vs_baseline`` = speculation-on tokens/s over speculation-off, with the
  draft-acceptance rate in ``detail``.

Either way ``effective stream GB/s`` — model bytes transferred per step / wall
time — is the engine-quality number; ``vs_baseline`` compares it to the
reference's ~25 GB/s OPT-30B CPU-offload figure.

Presets: ``gpt2-xl`` is the offload-parity geometry (2.1B) — pass it
explicitly on rigs with direct host links; TPU defaults to ``small``
(~0.53 GB; the tunneled dev rig's host link makes bigger streams
impractically slow), CPU to ``tiny``.  ``--bits 8`` streams int8-quantized
weights (4x less traffic — compose quantization with streaming).

Transport caveat: on a *tunneled* TPU (axon dev rig) host→HBM transfers run
over the network at ~1.5 GB/s with high fixed latency, so absolute numbers
there reflect the tunnel, not the engine; on a real TPU host the same code
rides local DMA.  The engine minimizes round-trips either way: one packed
buffer per stage (StreamingExecutor.pack_transfers), multi-layer chunks
(layers_per_stage), and transfer/compute double-buffering.

Prints ONE JSON line like bench.py.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# reference benchmarks/README.md:36 — OPT-30B fp16 CPU offload, 2.37 s/token,
# ~60GB of fp16 weights streamed per token => ~25.3 GB/s effective.
REFERENCE_STREAM_GBPS = 25.3

def _presets():
    """Named geometries — canonical ones come from TransformerConfig so the
    benchmark can never drift from the model the name promises."""
    from accelerate_tpu.models.transformer import TransformerConfig

    return {
        "gpt2-xl": TransformerConfig.gpt2_xl_equiv,
        "tiny": TransformerConfig.tiny,
        "small": lambda **kw: TransformerConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=4096,
            num_layers=12, num_heads=16, num_kv_heads=16, max_seq_len=512, **kw
        ),
    }


def _cost_detail(eng, dt_engine):
    """XLA cost-table numbers for the serve JSON contract: ``mfu`` and
    ``hbm_peak_bytes``.  Decode MFU = window invocations x decode-window FLOPs
    over wall time against the chip peak — prefill FLOPs are excluded, so this
    understates true utilization (it is the steady-state decode number).
    Empty when XLA cost analysis is unavailable on this backend."""
    eng.analyze_costs()
    out = {}
    decode_flops = eng.cost_table.flops("serve/decode_window")
    if decode_flops:
        windows = eng.stats["decode_steps"] / eng.window
        out["mfu"] = round(
            min(1.0, windows * decode_flops / dt_engine / eng.device_peaks.flops_per_s), 6
        )
        out["mfu_source"] = "xla_cost_analysis"
        out["decode_flops_per_token"] = round(
            decode_flops / (eng.window * eng.num_slots), 1
        )
    hbm = eng.cost_table.max_hbm_peak_bytes()
    if hbm:
        out["hbm_peak_bytes"] = int(hbm)
    return out


def _shared_prefix_result(args, preset, shared, prompt_lens, out_lens,
                          useful_tokens, run_engine, eng, reqs, dt_on,
                          registry, samples, buckets, slots, window):
    """Cache-on vs cache-off on the shared-prefix workload (one JSON result).

    The cache-off engine is the baseline — identical requests, identical
    executables minus the copies — so ``vs_baseline`` isolates exactly what
    prefix reuse buys.  Outputs must be token-identical between the runs (the
    cache skips compute, never changes it); the bench hard-fails otherwise.
    """
    eng_off, reqs_off, dt_off, registry_off, _ = run_engine(0)
    if [q.tokens for q in reqs] != [q.tokens for q in reqs_off]:
        raise SystemExit(
            "prefix cache changed outputs: cache-on tokens differ from "
            "cache-off on the same workload"
        )
    tps_on = useful_tokens / dt_on
    tps_off = useful_tokens / dt_off
    hit = eng.stats["prefix_hit_tokens"]
    miss = eng.stats["prefix_miss_tokens"]
    ttft_on = registry.get("serve/ttft_s").snapshot()
    ttft_off = registry_off.get("serve/ttft_s").snapshot()
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "num_slots": slots,
        "decode_window": window,
        "prefill_buckets": list(buckets),
        "shared_prefix": shared,
        "prefix_cache_mb": args.prefix_cache_mb,
        "prompt_len_p50_max": [int(np.median(prompt_lens)), int(prompt_lens.max())],
        "out_len_p50_max": [int(np.median(out_lens)), int(out_lens.max())],
        "useful_tokens": useful_tokens,
        "engine_wall_s": round(dt_on, 3),
        "cache_off_wall_s": round(dt_off, 3),
        "cache_off_tokens_per_s": round(tps_off, 2),
        "prefix_hit_rate": round(hit / (hit + miss), 3) if hit + miss else 0.0,
        "prefix_hit_tokens": hit,
        "prefix_cache": eng.prefix_cache_stats(),
        "outputs_token_identical": True,
        "token_latency_p50_ms": round(1e3 * float(np.percentile(samples, 50)), 2),
        "token_latency_p99_ms": round(1e3 * float(np.percentile(samples, 99)), 2),
        "ttft_ms": {k: round(1e3 * ttft_on[k], 2) for k in ("p50", "p90", "p99", "mean")},
        "cache_off_ttft_ms": {
            k: round(1e3 * ttft_off[k], 2) for k in ("p50", "p90", "p99", "mean")
        },
        "mean_slot_occupancy": round(eng.mean_slot_occupancy(), 3),
        "compiled_executables": eng.compiled_executable_counts(),
    }
    detail.update(_cost_detail(eng, dt_on))
    return {
        "metric": "serving_prefix_cache_tokens_per_sec",
        "value": round(tps_on, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tps_on / tps_off, 3),
        "detail": detail,
    }


def _spec_bench(args, model, cfg, params, preset):
    """Speculation on vs off on a repetitive greedy workload (one JSON result).

    The speculation-off engine is the baseline — identical requests, identical
    executables minus the verify window — so ``vs_baseline`` isolates exactly
    what n-gram drafting + batched verification buy.  The workload is tiled
    short motifs (the structured/repetitive shape — code, JSON, quoting — that
    prompt-lookup drafting targets); greedy outputs must be token-identical
    between the two runs and the bench hard-fails if they are not.

    ``--tree-ab`` switches to the draft-model + token-tree A/B
    (:func:`_tree_ab_bench`): identity matrix across pools / KV dtypes /
    tp, an acceptance-rate-vs-speedup curve on a non-repetitive workload,
    and compiled-budget hard checks.
    """
    import dataclasses

    if getattr(args, "tree_ab", False):
        return _tree_ab_bench(args, model, cfg, params, preset)

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.models.transformer import Transformer
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)  # HBM-resident: speculation is a decode bench
    slots = args.batch
    window = args.decode_window
    k = args.speculate_k
    if k < 1:
        raise SystemExit("--task spec needs --speculate-k >= 1")
    max_len = cfg.max_seq_len
    mp = max(8, min(args.seq, max_len) // 2)
    buckets = tuple(sorted({max(8, mp // 4), max(8, mp // 2)}))
    span = max(window, k + 1)

    # Speculation pays off in the steady state — once generation locks into
    # the motif's cycle, drafts verify near-perfectly — so the bench wants
    # generations long enough for steady state to dominate the chaotic
    # opening tokens.  Rope params carry no position table, so the context
    # window can be widened to fit the requested generation with the SAME
    # weights (both A/B arms get the identical widened model).
    need = mp + args.spec_new_tokens + span
    if need > max_len and cfg.positional == "rope":
        max_len = min(need, 1024)
        cfg = dataclasses.replace(cfg, max_seq_len=max_len)
        model = Transformer(cfg)

    r = np.random.default_rng(args.serve_seed)
    out_len = int(min(args.spec_new_tokens, max_len - mp - span))
    prompts = []
    for _ in range(args.requests):
        motif = r.integers(1, cfg.vocab_size, (int(r.integers(3, 8)),)).astype(np.int32)
        prompts.append(np.tile(motif, mp // motif.size + 1)[:mp])
    gen = GenerationConfig(max_new_tokens=out_len)
    useful_tokens = args.requests * out_len
    slot_len = min(max_len, mp + out_len + span)

    def run(spec_k):
        """One warmed, timed engine pass (prefix cache off: one variable)."""
        registry = MetricsRegistry()
        eng = ServingEngine(
            model, params, num_slots=slots, max_len=slot_len,
            prefill_buckets=buckets, max_prompt_len=mp, decode_window=window,
            registry=registry, prefix_cache_mb=0, speculate_k=spec_k,
        )
        # warmup compiles every executable before timing: non-drafting random
        # prompts exercise each prefill bucket + insert + the decode window;
        # a tiled prompt drives the verify window when speculation is on
        for b in buckets:
            eng.submit(r.integers(1, cfg.vocab_size, (b,)).astype(np.int32),
                       config=GenerationConfig(max_new_tokens=2 * span),
                       speculate=False)
            eng.run()
        eng.submit(np.tile(np.arange(1, 4, dtype=np.int32), mp)[:mp],
                   config=GenerationConfig(max_new_tokens=2 * span))
        eng.run()
        for key in eng.stats:
            eng.stats[key] = 0
        registry.reset()
        t0 = time.perf_counter()
        reqs = eng.serve(prompts, gen)
        dt = time.perf_counter() - t0
        return eng, reqs, dt, registry

    eng_on, reqs_on, dt_on, registry = run(k)
    eng_off, reqs_off, dt_off, _ = run(0)
    if [q.tokens for q in reqs_on] != [q.tokens for q in reqs_off]:
        raise SystemExit(
            "speculative decoding changed greedy outputs: speculation-on "
            "tokens differ from speculation-off on the same workload"
        )
    tps_on = useful_tokens / dt_on
    tps_off = useful_tokens / dt_off
    drafted = eng_on.stats["spec_drafted"]
    accepted = eng_on.stats["spec_accepted"]
    tok = registry.get("serve/token_latency_s").snapshot()
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "num_slots": slots,
        "decode_window": window,
        "speculate_k": k,
        "prompt_len": mp,
        "new_tokens_per_request": out_len,
        "useful_tokens": useful_tokens,
        "spec_on_wall_s": round(dt_on, 3),
        "spec_off_wall_s": round(dt_off, 3),
        "spec_off_tokens_per_s": round(tps_off, 2),
        "spec_accept_rate": round(accepted / drafted, 3) if drafted else 0.0,
        "spec_drafted": drafted,
        "spec_accepted": accepted,
        "outputs_token_identical": True,
        "token_latency_p50_ms": round(1e3 * tok["p50"], 2),
        "token_latency_p99_ms": round(1e3 * tok["p99"], 2),
        "compiled_executables": eng_on.compiled_executable_counts(),
        "watchdog_over_budget": any(
            wd.over_budget()
            for wd in [eng_on._decode, eng_on._verify, eng_on._insert,
                       *eng_on._prefill.values()]
        ),
    }
    return {
        "metric": "serving_speculative_tokens_per_sec",
        "value": round(tps_on, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tps_on / tps_off, 3),
        "detail": detail,
    }


def _tree_ab_bench(args, model, cfg, params, preset):
    """Tree speculation with an on-device draft model: identity matrix,
    acceptance-vs-speedup curve, and compiled-budget gates (one JSON result).

    Three hard checks, each a nonzero exit:

    * **Identity matrix** — greedy outputs token-identical between the tree
      arm and speculation-off on the SAME engine configuration, across
      {slab, paged} x {bf16, int8 KV} x {tp=1, tp=2}, with the tp=2 paged
      arm additionally asserting the Pallas kernel fell back to the XLA
      reference (the single-chip kernel does not shard).  int8 pages only
      exist on the paged pool, so the matrix is six arms, not eight; the
      tp=2 arms run float32 for the same precision reason ``--tp-ab``
      documents.
    * **Speedup on a non-repetitive workload** — the draft-model + tree arm
      must reach >= 1.4x tokens/s over speculation-off at a curve point
      where the n-gram drafter, run on the *same* prompts and params,
      measures an accept rate < 0.05.  Prompts are drawn WITHOUT token
      replacement from an 8k vocab, so no trailing n-gram recurs in the
      context and prompt-lookup drafting has nothing to match — exactly the
      workload regime the draft model exists for.
    * **Compiled budget** — relative to speculation-off, the tree engine's
      executable set grows by exactly {draft_forward, tree_verify_window}
      (one entry each), and repeat serve passes add zero retraces.

    The curve sweeps draft fidelity on one geometry: the draft is the
    target's own first two layers (``draft_model=2``), and the layers the
    draft does NOT share are scaled by ``eps``.  At ``eps=0`` the target
    effectively *is* its two-layer head, so drafts verify near-exactly
    (the draft's sliding context window is the only divergence); at
    ``eps=1`` the target is the unmodified 8-layer model and the
    truncated draft is near-random (accept ~0).  Each point re-measures its own
    speculation-off baseline and n-gram arm on the softened params, so
    ``curve`` in the JSON is acceptance rate vs speedup with everything
    else held fixed.  The headline gate takes the best point whose n-gram
    accept qualifies (< 0.05).  Each point times its two arms in paired
    interleaved passes and compares medians: CPU wall clocks drift on the
    scale of a bench run, and a baseline measured minutes before the tree
    arm would put that drift straight into the gated ratio.

    Bench-local geometry: the preset models are 2 layers on CPU, too
    shallow for a truncated-layer head to be meaningfully cheaper than its
    target, so the bench builds its own 8-layer float32 target (the
    identity arms recast it to bf16).  ``decode_window=1`` for every arm:
    both sides then pay one dispatch per landed token batch, which is the
    cost speculation amortizes — window fusion is the orthogonal axis
    ``--task serve`` measures.  ``num_slots=1`` keeps the arms
    dispatch-bound rather than batch-bound, the regime the tree targets:
    with one lane the baseline pays one dispatch per token, the tree two
    dispatches per ``depth+1`` tokens.

    The tp=2 arms need >= 2 devices; on a 1-device host they — and ONLY
    they — run in an 8-fake-CPU-mesh subprocess.  Unlike ``--tp-ab``, the
    bench does not re-exec wholesale: forcing the host platform to 8
    devices splits XLA's intra-op thread pool, and the wall-clock curve
    the speedup gate reads must be measured on the undivided machine.
    """
    import subprocess
    import sys

    import re as _re

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.models.transformer import Transformer
    from accelerate_tpu.parallel.mesh import build_mesh
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    cfg = dataclasses.replace(
        cfg, num_layers=8, vocab_size=8192, max_seq_len=256,
        hidden_size=64, intermediate_size=128, num_heads=4, num_kv_heads=2,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(args.serve_seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    draft_layers = 2

    def soften(eps):
        """Scale the layers the draft does not share by ``eps``."""
        out = {}
        for key, val in params.items():
            m = _re.fullmatch(r"layers_(\d+)", key)
            if m and int(m.group(1)) >= draft_layers:
                out[key] = jax.tree_util.tree_map(
                    lambda a: (np.asarray(a) * eps).astype(a.dtype), val)
            else:
                out[key] = val
        return out

    # distinct-token prompts: with no repeated token anywhere in the
    # context, the n-gram drafter's suffix index never finds a match to
    # extend — the workload is non-repetitive by construction.  The draft
    # is TWO layers, not one: a single attention layer is near-Markov
    # (next token mostly a function of the last), so its greedy stream
    # revisits a token and loops, and the n-gram drafter starts scoring
    # on the loop; attention over attention conditions on the whole
    # prefix and the softened streams never recur
    n_req, plen, out_len, reps = 8, 24, 24, 4
    tree_kw = dict(draft_model=draft_layers, tree_width=1, tree_depth=11,
                   draft_ctx=60)
    r = np.random.default_rng(args.serve_seed)
    prompts = [
        r.choice(cfg.vocab_size - 1, size=plen, replace=False).astype(np.int32) + 1
        for _ in range(n_req)
    ]
    gen = GenerationConfig(max_new_tokens=out_len)
    useful_tokens = n_req * out_len

    def run(arm_model, arm_params, n_reps=reps, out=out_len, **kw):
        """One warmed engine; best-of-``n_reps`` timed serve passes."""
        eng = ServingEngine(
            arm_model, arm_params, num_slots=1, max_len=256,
            prefill_buckets=(8, 24), decode_window=1,
            registry=MetricsRegistry(), prefix_cache_mb=0, **kw,
        )
        for b in (8, 24):
            eng.submit(r.integers(1, cfg.vocab_size, (b,)).astype(np.int32),
                       config=GenerationConfig(max_new_tokens=8))
        eng.run()
        g = GenerationConfig(max_new_tokens=out)
        best, toks = 0.0, None
        for _ in range(n_reps):
            for key in eng.stats:
                eng.stats[key] = 0
            t0 = time.perf_counter()
            reqs = eng.serve([p.copy() for p in prompts], g)
            dt = time.perf_counter() - t0
            best = max(best, sum(len(q.tokens) for q in reqs) / dt)
            toks = [q.tokens for q in reqs]
        return eng, toks, best

    def timed_pair(arm_params, **extra_tree_kw):
        """Speculation-off and tree engines timed in ALTERNATING passes.

        CPU wall clocks drift on the scale of a bench run (load, thermal,
        cache state); measuring the baseline once and every tree point
        minutes later puts that drift straight into the speedup ratio.
        Interleaving the passes and taking the ratio of medians cancels
        it — both arms sample the same seconds of machine."""
        eng_off, _, _ = run(model, arm_params, n_reps=1)
        eng_tree, _, _ = run(model, arm_params, n_reps=1,
                             **{**tree_kw, **extra_tree_kw})
        offs, trees = [], []
        toks_off = toks_tree = None
        for _ in range(reps):
            for eng, acc in ((eng_off, offs), (eng_tree, trees)):
                for key in eng.stats:
                    eng.stats[key] = 0
                t0 = time.perf_counter()
                reqs = eng.serve([p.copy() for p in prompts], gen)
                dt = time.perf_counter() - t0
                acc.append(sum(len(q.tokens) for q in reqs) / dt)
                toks = [q.tokens for q in reqs]
                if eng is eng_off:
                    toks_off = toks
                else:
                    toks_tree = toks
        return (eng_off, eng_tree, toks_off, toks_tree,
                float(np.median(offs)), float(np.median(trees)))

    def run_tp2_arms():
        """The three tp=2 identity arms (float32 — see the matrix note)."""
        mesh = build_mesh({"tp": 2}, devices=jax.devices()[:2])
        int8_kw = dict(paged=True, kv_dtype="int8", page_size=1)
        rows = []
        for name, kw in [
            ("slab_f32_tp2", dict(mesh=mesh)),
            ("paged_f32_tp2",
             dict(paged=True, mesh=mesh, decode_kernel="pallas")),
            ("paged_int8_tp2", dict(int8_kw, mesh=mesh)),
        ]:
            _, toks_off, _ = run(model, params, n_reps=1, out=12, **kw)
            eng_on, toks_on, _ = run(model, params, n_reps=1, out=12,
                                     **kw, **tree_kw)
            if toks_on != toks_off:
                raise SystemExit(
                    f"tree speculation changed greedy outputs on the "
                    f"{name} arm: tree tokens differ from speculation-off"
                )
            if name == "paged_f32_tp2" and eng_on.decode_kernel != "xla":
                raise SystemExit(
                    "tp=2 paged arm kept decode_kernel="
                    f"{eng_on.decode_kernel!r}; the single-chip Pallas "
                    "kernel must fall back to the XLA reference under a "
                    "tp mesh"
                )
            rows.append({
                "arm": name, "token_identical": True,
                "decode_kernel": getattr(eng_on, "decode_kernel", None),
            })
        return rows

    if os.environ.get("ACCEL_TREE_AB_TP_CHILD") == "1":
        # scoped child: the fake-device mesh exists only here
        print("TREE_AB_TP2 " + json.dumps(run_tp2_arms()), flush=True)
        raise SystemExit(0)

    # --- acceptance-rate-vs-speedup curve -------------------------------
    curve = []
    budget_off = budget_tree = budget_first = None
    for eps in (0.0, 0.25, 0.5, 1.0):
        pe = soften(eps)
        eng_off, eng_tree, t_off, t_tree, tps_off, tps_tree = timed_pair(pe)
        eng_ng, _, _ = run(model, pe, n_reps=1, speculate_k=args.speculate_k)
        if eps == 0.0:
            budget_off = eng_off.compiled_executable_counts()
            budget_tree = eng_tree.compiled_executable_counts()
            # one more full pass AFTER the budget snapshot: any retrace
            # (shape drift, cache miss) would grow the counts
            eng_tree.serve([p.copy() for p in prompts], gen)
            budget_first = eng_tree.compiled_executable_counts()
        if t_tree != t_off:
            raise SystemExit(
                f"tree speculation changed greedy outputs at eps={eps}: "
                "tree-arm tokens differ from speculation-off on the same "
                "softened params"
            )
        dd, aa = eng_tree.stats["spec_drafted"], eng_tree.stats["spec_accepted"]
        dn, an = eng_ng.stats["spec_drafted"], eng_ng.stats["spec_accepted"]
        curve.append({
            "eps": eps,
            "accept_rate": round(aa / dd, 3) if dd else 0.0,
            "ngram_accept_rate": round(an / dn, 3) if dn else 0.0,
            "ngram_drafted": int(dn),
            "tokens_per_s": round(tps_tree, 2),
            "baseline_tokens_per_s": round(tps_off, 2),
            "speedup": round(tps_tree / tps_off, 3),
        })

    # --- compiled-budget gates ------------------------------------------
    if budget_tree != budget_first:
        raise SystemExit(
            f"tree engine retraced across repeat serve passes: "
            f"{budget_tree} -> {budget_first}"
        )
    grown = {k for k, n in budget_tree.items() if n and not budget_off.get(k, 0)}
    if grown != {"draft_forward", "tree_verify_window"} or (
        budget_tree["draft_forward"] != 1
        or budget_tree["tree_verify_window"] != 1
    ):
        raise SystemExit(
            "tree speculation must grow the compiled budget by exactly "
            f"{{draft_forward, tree_verify_window}}, one entry each; got "
            f"growth {sorted(grown)} with counts {budget_tree}"
        )

    # --- headline gate ---------------------------------------------------
    eligible = [p for p in curve if p["ngram_accept_rate"] < 0.05]
    if not eligible:
        raise SystemExit(
            "no curve point qualifies as non-repetitive: the n-gram "
            "drafter's accept rate is >= 0.05 at every eps — "
            f"{[(p['eps'], p['ngram_accept_rate']) for p in curve]}"
        )
    head = max(eligible, key=lambda p: p["speedup"])
    if head["speedup"] < 1.4:
        raise SystemExit(
            f"draft-model tree speculation reached only {head['speedup']}x "
            f"tokens/s over speculation-off (eps={head['eps']}, accept "
            f"{head['accept_rate']}, n-gram accept "
            f"{head['ngram_accept_rate']}); the bench requires >= 1.4x"
        )

    # width-2 reference point (not gated): same node budget rules, the
    # extra branch pays node compute for branch diversity the near-exact
    # draft does not need — visible in the JSON, useful on real models
    pe = soften(0.0)
    _, _, t_off0, t_w2, tps_off0, tps_w2 = timed_pair(pe, tree_width=2)
    if t_w2 != t_off0:
        raise SystemExit(
            "tree speculation changed greedy outputs at width=2"
        )
    curve.append({
        "eps": 0.0, "tree_width": 2,
        "tokens_per_s": round(tps_w2, 2),
        "speedup": round(tps_w2 / tps_off0, 3),
    })

    # --- identity matrix: {slab, paged} x {bf16, int8} x {tp1, tp2} ------
    # the tp=2 arms run float32 for the same reason --tp-ab does: token-
    # exactness under a mesh needs full-precision argmax margins — bf16
    # rounding differs between the stepwise decode and the batched verify
    # forward just enough to flip tied argmaxes once reductions are sharded
    bcfg = dataclasses.replace(cfg, dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    bmodel = Transformer(bcfg)
    bparams = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.bfloat16), params
    )
    int8_kw = dict(paged=True, kv_dtype="int8", page_size=1)
    identity = []
    for name, kw in [
        ("slab_bf16_tp1", {}),
        ("paged_bf16_tp1", dict(paged=True)),
        ("paged_int8_tp1", dict(int8_kw)),
    ]:
        _, toks_off, _ = run(bmodel, bparams, n_reps=1, out=12, **kw)
        eng_on, toks_on, _ = run(bmodel, bparams, n_reps=1, out=12,
                                 **kw, **tree_kw)
        if toks_on != toks_off:
            raise SystemExit(
                f"tree speculation changed greedy outputs on the {name} "
                "arm: tree tokens differ from speculation-off"
            )
        identity.append({
            "arm": name, "token_identical": True,
            "decode_kernel": getattr(eng_on, "decode_kernel", None),
        })
    if len(jax.devices()) >= 2:
        identity += run_tp2_arms()
    else:
        env = dict(os.environ)
        env["ACCEL_TREE_AB_TP_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env, capture_output=True, text=True,
        )
        rows = None
        for line in proc.stdout.splitlines():
            if line.startswith("TREE_AB_TP2 "):
                rows = json.loads(line[len("TREE_AB_TP2 "):])
        if proc.returncode != 0 or rows is None:
            raise SystemExit(
                "tp=2 tree identity arms failed in the fake-device mesh "
                f"subprocess (rc={proc.returncode}):\n"
                f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
            )
        identity += rows

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "geometry": {
            "num_layers": cfg.num_layers, "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
        },
        "workload": {
            "requests": n_req, "prompt_len": plen,
            "new_tokens_per_request": out_len,
            "useful_tokens": useful_tokens,
            "distinct_token_prompts": True,
        },
        "tree": dict(tree_kw),
        "num_slots": 1,
        "decode_window": 1,
        "headline_eps": head["eps"],
        "headline_accept_rate": head["accept_rate"],
        "headline_ngram_accept_rate": head["ngram_accept_rate"],
        "curve": curve,
        "identity_matrix": identity,
        "compiled_executables": budget_tree,
        "executable_growth": sorted(grown),
        "retraces": 0,
        "outputs_token_identical": True,
    }
    return {
        "metric": "serving_tree_spec_tokens_per_sec",
        "value": round(head["tokens_per_s"], 2),
        "unit": "tokens/s",
        "vs_baseline": head["speedup"],
        "detail": detail,
    }


def _paged_ab_bench(args, model, cfg, params, preset):
    """Paged KV allocator vs legacy slab pool at the SAME KV HBM budget.

    The workload is heavy-tailed chat traffic: every 8th request carries a
    long prompt (0.75-1x the longest admissible), the rest are short turns.
    The legacy arm reserves a full ``max_len`` slab per lane, so its KV
    budget — ``(slots + 1)`` slabs counting the prefill scratch — admits only
    a couple of lanes.  The paged arm gets a page pool of the same byte
    budget rounded DOWN to whole pages, scale arrays included (asserted
    ``<=`` via ``kv_pool_bytes``), but allocates per page, so short
    requests stop paying for the tail's worst case.  The headline
    metric is the ratio of peak concurrent lanes; outputs must be
    token-identical between the arms or the bench exits nonzero.

    Both arms run with ``max_prompt_len == max_len``: the paged prefill
    gathers a full-width view, and bitwise-identical logits across the arms
    require the legacy scratch to span that same width.
    """
    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)
    window = args.decode_window
    mp = max(16, min(args.seq, cfg.max_seq_len) // 2)
    page = max(4, mp // 4)
    buckets = (page, 2 * page)
    max_len = (min(cfg.max_seq_len, 2 * mp) // page) * page

    r = np.random.default_rng(args.serve_seed)
    n = args.requests
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(4, mp // 12)), 0.6, n)), 4, page - 1
    ).astype(int)
    long_idx = np.arange(0, n, 8)
    prompt_lens[long_idx] = r.integers(3 * mp // 4, mp + 1, long_idx.size)
    prompts = [
        r.integers(1, cfg.vocab_size, (int(p),)).astype(np.int32)
        for p in prompt_lens
    ]
    out_cap = max(window, (max_len - mp - window) // 2)
    out_lens = np.clip(
        np.rint(r.lognormal(np.log(max(window, out_cap // 4)), 0.6, n)),
        window, out_cap,
    ).astype(int)
    gens = [GenerationConfig(max_new_tokens=int(o)) for o in out_lens]
    useful_tokens = int(out_lens.sum())

    legacy_slots = 2
    pages_per_lane = max_len // page
    # equal KV HBM: legacy pays (slots + 1) full-width slabs (pool + prefill
    # scratch); the paged pool gets AT MOST that many bytes worth of pages.
    # A paged page costs more than its slab-equivalent span: since the
    # quantized-KV PR every page carries per-(page, kv-head) f32 scale
    # arrays even at native dtype, so the page count comes from dividing the
    # legacy byte budget by the full per-page cost (scales included) and
    # rounding DOWN — the paged arm absorbs both the rounding and the
    # reserved null page rather than rounding the budget up.
    from accelerate_tpu.serving.paging import PagedKVPool

    # 2-page probe (1-page lane + null) just to read the per-page byte cost
    probe = PagedKVPool(cfg, 1, page, page, 2, registry=MetricsRegistry())
    page_data_bytes = (int(probe.pages_k.nbytes) + int(probe.pages_v.nbytes)) // 2
    legacy_bytes = (legacy_slots + 1) * pages_per_lane * page_data_bytes
    num_pages = max(pages_per_lane + 1, legacy_bytes // probe.page_kv_bytes)
    del probe

    def run_arm(paged):
        registry = MetricsRegistry()
        kwargs = dict(
            num_slots=args.batch if paged else legacy_slots,
            max_len=max_len, max_prompt_len=max_len, prefill_buckets=buckets,
            decode_window=window, registry=registry, prefix_cache_mb=0,
        )
        if paged:
            kwargs.update(paged=True, page_size=page, num_pages=num_pages)
        eng = ServingEngine(model, params, **kwargs)
        warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32) for b in buckets]
        eng.serve(warm, GenerationConfig(max_new_tokens=window))
        for k in eng.stats:
            eng.stats[k] = 0
        eng.peak_active_lanes = 0
        registry.reset()
        t0 = time.perf_counter()
        reqs = eng.serve(prompts, gens)
        dt = time.perf_counter() - t0
        return eng, reqs, dt

    eng_paged, reqs_paged, dt_paged = run_arm(True)
    eng_slab, reqs_slab, dt_slab = run_arm(False)
    if [q.tokens for q in reqs_paged] != [q.tokens for q in reqs_slab]:
        raise SystemExit(
            "paged KV allocator changed greedy outputs: paged-arm tokens "
            "differ from the legacy slab arm on the same workload"
        )
    if eng_paged.kv_pool_bytes() > eng_slab.kv_pool_bytes():
        raise SystemExit(
            f"KV budgets diverged: paged arm holds {eng_paged.kv_pool_bytes()} "
            f"bytes vs legacy {eng_slab.kv_pool_bytes()} — the A/B is only "
            "meaningful when the paged arm fits the legacy byte budget"
        )
    peak_ratio = eng_paged.peak_active_lanes / max(1, eng_slab.peak_active_lanes)

    def arm_detail(eng, reqs, dt):
        return {
            "num_slots": eng.num_slots,
            "peak_active_lanes": eng.peak_active_lanes,
            "kv_pool_bytes": eng.kv_pool_bytes(),
            "wall_s": round(dt, 3),
            "tokens_per_s": round(useful_tokens / dt, 2),
            "preemptions": eng.stats.get("preemptions", 0),
            "cow_copies": eng.stats.get("cow_copies", 0),
            "compiled_executables": eng.compiled_executable_counts(),
        }

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "decode_window": window,
        "prefill_buckets": list(buckets),
        "page_size": page,
        "num_pages": num_pages,
        "max_len": max_len,
        "prompt_len_p50_max": [int(np.median(prompt_lens)), int(prompt_lens.max())],
        "out_len_p50_max": [int(np.median(out_lens)), int(out_lens.max())],
        "useful_tokens": useful_tokens,
        "outputs_token_identical": True,
        "paged": arm_detail(eng_paged, reqs_paged, dt_paged),
        "legacy": arm_detail(eng_slab, reqs_slab, dt_slab),
    }
    return {
        "metric": "serving_paged_peak_lanes_ratio",
        "value": round(peak_ratio, 3),
        "unit": "x",
        "vs_baseline": round(peak_ratio, 3),
        "detail": detail,
    }


def _async_ab_bench(args, model, cfg, params, preset):
    """Depth-1 pipelined serve loop vs the synchronous loop.

    Two claims, both hard-enforced:

    * **Token identity** — ``async_depth=1`` must produce bitwise-identical
      outputs to ``async_depth=0`` on the same request stream, across every
      sampling/pool mode the pipeline threads through: greedy and sampled on
      the slab pool, speculative decoding, the paged pool, and int8
      quantized KV pages.  Any divergence exits nonzero.
    * **Overlap pays** — on a timed greedy arm whose decode window carries
      real compute (a fixed ~10M-param float32 geometry; the identity
      presets price a CPU window near zero, where an A/B only measures
      scheduler noise), with every token streamed through an ``on_token``
      consumer with ~100us of client delivery latency (the network flush a
      real streaming server pays per token — exactly the host-side time the
      pipeline exists to hide), the async loop must be >= 10% faster
      tokens/s, publish ``serve/host_overlap_ratio > 0``, and compile
      EXACTLY the same executable set as the sync loop (the pipeline
      re-orders host work; it must never add device programs).  Arm timings
      are best-of-two, interleaved, to keep background-load drift
      symmetric.

    The headline metric is the async/sync tokens/s ratio; ``detail.overlap``
    records the published overlap ratio and cumulative device idle ms of
    both arms.
    """
    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    STREAM_DELAY_S = 100e-6  # per-token client delivery latency, timed arms

    params = jax.device_put(params)
    window = args.decode_window
    max_len = cfg.max_seq_len
    mp = max(8, min(args.seq, max_len) // 2)
    # bucket pair with bucket[0] | bucket[1] so the paged arms' default
    # page_size (the bucket gcd) divides every bucket and the page-aligned
    # slot length below
    page = max(8, mp // 4)
    buckets = (page, 2 * page)

    r = np.random.default_rng(args.serve_seed)
    n = args.requests
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, mp // 3)), 0.8, n)), 4, mp
    ).astype(int)
    prompts = [
        r.integers(1, cfg.vocab_size, (int(p),)).astype(np.int32)
        for p in prompt_lens
    ]
    out_cap = min(max_len - window - mp, 2 * mp)
    out_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, out_cap // 4)), 0.8, n)),
        window, out_cap,
    ).astype(int)
    useful_tokens = int(out_lens.sum())
    need = int(max(p + o for p, o in zip(prompt_lens, out_lens))) + window
    slot_len = min((max_len // page) * page, -(-need // page) * page)

    def run(async_depth, configs, timed=False, bundle=None, **kw):
        b_model, b_params, b_vocab, b_slot_len, b_buckets, b_mp, b_prompts = (
            bundle if bundle is not None
            else (model, params, cfg.vocab_size, slot_len, buckets, mp, prompts)
        )
        registry = MetricsRegistry()
        eng = ServingEngine(
            b_model, b_params, num_slots=args.batch, max_len=b_slot_len,
            prefill_buckets=b_buckets, max_prompt_len=b_mp, decode_window=window,
            registry=registry, prefix_cache_mb=0, async_depth=async_depth,
            **kw,
        )
        # warm must cover every executable the timed serve dispatches,
        # including the ``lane_install`` scatter — that one only compiles on
        # an admission AFTER the first decode window (the device lane mirror
        # must already exist), so warm with more requests than slots
        warm = [r.integers(1, b_vocab, (b_buckets[0],)).astype(np.int32)
                for _ in range(args.batch + 2)]
        warm[:len(b_buckets)] = [
            r.integers(1, b_vocab, (b,)).astype(np.int32) for b in b_buckets
        ]
        eng.serve(warm, GenerationConfig(max_new_tokens=window))
        for k in eng.stats:
            eng.stats[k] = 0
        registry.reset()
        # streaming consumers: each token is delivered to a client that takes
        # ~100us to flush (the SSE/network round-trip every streaming server
        # pays).  The wait releases the GIL, so the in-flight window computes
        # right through it — this is exactly the host-side latency the
        # pipeline hides.  The sync loop pays it serially: its drain runs
        # with nothing in flight.  Kept as a wait, not spin: on a shared-core
        # CPU host, busy host work would steal cycles from the "device"
        stamps = {}

        def on_token(req, tok):
            stamps.setdefault(req.rid, []).append(tok)
            time.sleep(STREAM_DELAY_S)

        t0 = time.perf_counter()
        reqs = eng.serve(b_prompts, configs, on_token=on_token if timed else None)
        dt = time.perf_counter() - t0
        return eng, [q.tokens for q in reqs], dt, registry

    greedy = [GenerationConfig(max_new_tokens=int(o)) for o in out_lens]
    sampled = [
        GenerationConfig(max_new_tokens=int(o), do_sample=True,
                         temperature=0.8, top_k=40, top_p=0.9)
        for o in out_lens
    ]
    arms = {
        "greedy_slab": (greedy, {}),
        "sampled_slab": (sampled, {}),
        "speculative": (greedy, {"speculate_k": 4}),
        "paged": (greedy, {"paged": True}),
        "paged_int8_kv": (greedy, {"paged": True, "kv_dtype": "int8"}),
    }
    identity = {}
    for name, (configs, kw) in arms.items():
        _, toks_async, _, _ = run(1, configs, **kw)
        _, toks_sync, _, _ = run(0, configs, **kw)
        if toks_async != toks_sync:
            raise SystemExit(
                f"async pipelined loop changed outputs on the {name} arm: "
                "async_depth=1 tokens differ from async_depth=0 on the same "
                "request stream"
            )
        identity[name] = True

    # Timed arm: greedy + streaming callbacks.  Overlap can only pay when a
    # decode window *costs* something next to the host/stream side it hides —
    # on the identity presets a CPU window is ~1ms against ~15ms of streaming
    # waits, so an A/B there measures scheduler noise, not the pipeline.  The
    # timed arm therefore runs a fixed geometry that prices a window at
    # ~20ms on a CPU host (comparable to emit + admission + streaming), with
    # short prompts so prefill stays a sliver of the wall.  Interleaved
    # best-of-two per arm — single-run wall times on a small shared host
    # swing with background load, and alternating keeps any drift symmetric.
    from accelerate_tpu.models.transformer import Transformer, TransformerConfig

    cfg_t = TransformerConfig(
        vocab_size=2048, hidden_size=192, intermediate_size=768,
        num_layers=3, num_heads=6, num_kv_heads=6, max_seq_len=256,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    model_t = Transformer(cfg_t)
    params_t = jax.device_put(
        model_t.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    prompts_t = [
        r.integers(1, cfg_t.vocab_size, (16,)).astype(np.int32) for _ in range(n)
    ]
    out_t = [int(o) for o in r.integers(6 * window, 12 * window + 1, n)]
    timed_tokens = int(sum(out_t))
    greedy_t = [GenerationConfig(max_new_tokens=o) for o in out_t]
    bundle_t = (model_t, params_t, cfg_t.vocab_size,
                16 + 12 * window + 2 * window, (16, 32), 32, prompts_t)
    eng_s, _, dt_s1, reg_s = run(0, greedy_t, timed=True, bundle=bundle_t)
    eng_a, _, dt_a1, reg_a = run(1, greedy_t, timed=True, bundle=bundle_t)
    _, _, dt_s2, _ = run(0, greedy_t, timed=True, bundle=bundle_t)
    _, _, dt_a2, _ = run(1, greedy_t, timed=True, bundle=bundle_t)
    dt_sync = min(dt_s1, dt_s2)
    dt_async = min(dt_a1, dt_a2)
    tps_sync = timed_tokens / dt_sync
    tps_async = timed_tokens / dt_async
    speedup = tps_async / tps_sync
    overlap = float(reg_a.get("serve/host_overlap_ratio").value)
    overlap_sync = float(reg_s.get("serve/host_overlap_ratio").value)
    if eng_a.compiled_executable_counts() != eng_s.compiled_executable_counts():
        raise SystemExit(
            f"async loop changed the compiled-executable budget: "
            f"{eng_a.compiled_executable_counts()} vs "
            f"{eng_s.compiled_executable_counts()}"
        )
    if overlap <= 0.0:
        raise SystemExit(
            "async arm published serve/host_overlap_ratio == 0: the pipeline "
            "never overlapped host work with device compute"
        )
    if speedup < 1.10:
        raise SystemExit(
            f"async pipelined loop too slow: {tps_async:.1f} vs "
            f"{tps_sync:.1f} tokens/s ({speedup:.3f}x, need >= 1.10x)"
        )
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "num_slots": args.batch,
        "decode_window": window,
        "useful_tokens": useful_tokens,
        "timed_tokens": timed_tokens,
        "timed_config": {
            "hidden_size": cfg_t.hidden_size, "num_layers": cfg_t.num_layers,
            "vocab_size": cfg_t.vocab_size, "dtype": "float32",
        },
        "stream_delay_us": round(STREAM_DELAY_S * 1e6, 1),
        "outputs_token_identical": identity,
        "tokens_per_s": {"async": round(tps_async, 2), "sync": round(tps_sync, 2)},
        "wall_s": {"async": round(dt_async, 3), "sync": round(dt_sync, 3)},
        "overlap": {
            "host_overlap_ratio": round(overlap, 4),
            "host_overlap_ratio_sync": round(overlap_sync, 4),
            "device_idle_ms": round(float(reg_a.get("serve/device_idle_ms").value), 2),
            "device_idle_ms_sync": round(float(reg_s.get("serve/device_idle_ms").value), 2),
        },
        "compiled_executables": eng_a.compiled_executable_counts(),
    }
    return {
        "metric": "serving_async_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "detail": detail,
    }


def _tp_ab_bench(args, model, cfg, params, preset):
    """Tensor-parallel serving A/B: tp=2 vs tp=1, then router affinity vs
    round-robin — the multi-chip serve entry (MULTICHIP_r06).

    Arm 1/2 (tp identity): the SAME engine, workload, and request stream on a
    single chip and on a ``{"tp": 2}`` mesh (params column-parallel under
    ``SERVING_TP_RULES``, KV pool head-sharded).  Hard checks, each a
    nonzero exit:

    * greedy outputs token-identical between the arms (SERVING_TP_RULES
      shard no contraction, so sharded reductions run in the tp=1 order);
    * per-device KV pool bytes at tp=2 at most 55% of tp=1 — the whole point
      of sharding the pool;
    * ``compiled_executable_counts()`` identical — the mesh must not cost
      executables, only shard the existing ones.

    The identity arms run in float32 (prompts and params recast) for the same
    reason ``tests/test_serving.py`` does: token-exactness needs full-precision
    argmax margins, not bf16 ties.

    Arm 3/4 (router A/B): two engine replicas behind a
    :class:`~accelerate_tpu.serving.ReplicaRouter`, a shared-prefix workload
    submitted in waves (each wave drains before the next arrives, so the
    radix trees the router probes reflect served traffic).  The affinity
    policy must beat round-robin on the aggregate token-weighted prefix-hit
    rate — strictly, or the bench exits nonzero.

    Needs >= 2 devices; on a 1-device host it self-provisions the 8-fake-CPU
    mesh in a subprocess, mirroring ``__graft_entry__.dryrun_multichip``.
    """
    import subprocess
    import sys

    if len(jax.devices()) < 2:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]], env=env
        )
        raise SystemExit(proc.returncode)

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.models.transformer import Transformer
    from accelerate_tpu.parallel.mesh import build_mesh
    from accelerate_tpu.serving import ReplicaRouter, ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    model = Transformer(cfg)
    params = jax.device_put(
        jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32), params)
    )
    window = args.decode_window
    mp = max(16, min(args.seq, cfg.max_seq_len) // 2)
    buckets = (max(8, mp // 4), max(8, mp // 2))
    max_len = min(cfg.max_seq_len, 2 * mp)

    r = np.random.default_rng(args.serve_seed)
    n = args.requests
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(4, mp // 3)), 0.6, n)), 4, mp
    ).astype(int)
    prompts = [
        r.integers(1, cfg.vocab_size, (int(p),)).astype(np.int32)
        for p in prompt_lens
    ]
    out_cap = max(window, (max_len - mp - window) // 2)
    out_lens = np.clip(
        np.rint(r.lognormal(np.log(max(window, out_cap // 2)), 0.6, n)),
        window, out_cap,
    ).astype(int)
    gens = [GenerationConfig(max_new_tokens=int(o)) for o in out_lens]
    useful_tokens = int(out_lens.sum())

    def run_arm(mesh):
        registry = MetricsRegistry()
        eng = ServingEngine(
            model, params, num_slots=args.batch, max_len=max_len,
            max_prompt_len=mp, prefill_buckets=buckets, decode_window=window,
            registry=registry, prefix_cache_mb=0, paged=True, mesh=mesh,
        )
        warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32) for b in buckets]
        eng.serve(warm, GenerationConfig(max_new_tokens=window))
        for k in eng.stats:
            eng.stats[k] = 0
        registry.reset()
        t0 = time.perf_counter()
        reqs = eng.serve(prompts, gens)
        dt = time.perf_counter() - t0
        return eng, reqs, dt

    mesh2 = build_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng1, reqs1, dt1 = run_arm(None)
    eng2, reqs2, dt2 = run_arm(mesh2)
    if [q.tokens for q in reqs1] != [q.tokens for q in reqs2]:
        raise SystemExit(
            "tensor-parallel serving changed greedy outputs: tp=2 tokens "
            "differ from tp=1 on the same workload"
        )
    bytes1, bytes2 = eng1.kv_pool_bytes(), eng2.kv_pool_bytes()
    if bytes2 > 0.55 * bytes1:
        raise SystemExit(
            f"tp=2 per-device KV pool holds {bytes2} bytes vs {bytes1} at "
            "tp=1 — sharding the pool on the head axis must at least halve it"
        )
    counts1 = eng1.compiled_executable_counts()
    counts2 = eng2.compiled_executable_counts()
    if counts1 != counts2:
        raise SystemExit(
            f"mesh changed the compiled-executable budget: tp=1 {counts1} "
            f"vs tp=2 {counts2}"
        )

    # ---- router A/B: shared-prefix waves, affinity vs round-robin --------
    # 3 prefix groups over 2 replicas: coprime, so round-robin rotates each
    # group across replicas wave over wave (repaying the prefill everywhere)
    # while affinity pins each group to the replica that first served it
    n_groups, n_waves = 3, 5
    shared = buckets[1]
    commons = [
        r.integers(1, cfg.vocab_size, (shared,)).astype(np.int32)
        for _ in range(n_groups)
    ]
    waves = []
    for _ in range(n_waves):
        wave = []
        for c in commons:
            sfx = r.integers(1, cfg.vocab_size, (int(r.integers(4, 12)),))
            wave.append(np.concatenate([c, sfx.astype(np.int32)]))
        waves.append(wave)
    router_gen = GenerationConfig(max_new_tokens=window)

    def run_router(policy):
        registry = MetricsRegistry()
        engines = [
            ServingEngine(
                model, params, num_slots=args.batch, max_len=max_len,
                max_prompt_len=mp, prefill_buckets=buckets,
                decode_window=window, registry=MetricsRegistry(),
                prefix_cache_mb=args.prefix_cache_mb, paged=True,
            )
            for _ in range(2)
        ]
        router = ReplicaRouter(engines, policy=policy, registry=registry)
        for wave in waves:
            for p in wave:
                router.submit(p, config=router_gen)
            router.run()
        return router

    router_aff = run_router("affinity")
    router_rr = run_router("round_robin")
    hit_aff = router_aff.prefix_cache_stats()["hit_rate"]
    hit_rr = router_rr.prefix_cache_stats()["hit_rate"]
    if not hit_aff > hit_rr:
        raise SystemExit(
            f"prefix-affinity routing found no more cached tokens than "
            f"round-robin ({hit_aff:.3f} vs {hit_rr:.3f}) on a shared-prefix "
            "workload it was built for"
        )

    n_dev = len(jax.devices())
    tail = (
        f"serve_tp_ab({n_dev}): mesh={{'tp': 2}} token_identical=True "
        f"kv_per_device_ratio={bytes2 / bytes1:.2f} "
        f"router_hit affinity={hit_aff:.3f} > round_robin={hit_rr:.3f} OK"
    )
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "MULTICHIP_r06.json"), "w") as f:
        json.dump({"n_devices": n_dev, "rc": 0, "ok": True,
                   "skipped": False, "tail": tail}, f)

    def arm_detail(eng, dt):
        return {
            "kv_pool_bytes_per_device": eng.kv_pool_bytes(),
            "tp_degree": eng.tp_degree,
            "wall_s": round(dt, 3),
            "tokens_per_s": round(useful_tokens / dt, 2),
            "compiled_executables": eng.compiled_executable_counts(),
        }

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "n_devices": n_dev,
        "requests": n,
        "decode_window": window,
        "prefill_buckets": list(buckets),
        "max_len": max_len,
        "useful_tokens": useful_tokens,
        "outputs_token_identical": True,
        "tp1": arm_detail(eng1, dt1),
        "tp2": arm_detail(eng2, dt2),
        "router": {
            "replicas": 2,
            "waves": n_waves,
            "prefix_groups": n_groups,
            "shared_prefix": int(shared),
            "affinity_hit_rate": round(hit_aff, 4),
            "round_robin_hit_rate": round(hit_rr, 4),
            "affinity_routed_hits": router_aff.health()["affinity_hit_rate"],
        },
    }
    return {
        "metric": "serving_tp_kv_per_device_ratio",
        "value": round(bytes2 / bytes1, 3),
        "unit": "x",
        "vs_baseline": round((useful_tokens / dt2) / (useful_tokens / dt1), 3),
        "detail": detail,
    }


def _quantized_logit_divergence(model, cfg, params, seq, plen, page, kv_dtype):
    """True logit-divergence oracle for quantized KV pages.

    Teacher-forces one completed sequence two ways and compares logits
    position by position over the decode region:

    * the exact reference — one full causal forward with no cache at all;
    * a single-lane quantized :class:`PagedKVCache` replay, one token per
      step through the SAME XLA paged-attention program the engine decodes
      with, so every page requantization the engine would perform happens
      here too.

    Returns ``max |logits_quantized - logits_exact|`` — the number the
    ``serve/kv_quant_error`` gauge only upper-bounds by proxy.
    """
    from accelerate_tpu.models.transformer import PagedKVCache
    from accelerate_tpu.ops.paged_attention import kv_storage_dtype

    seq = np.asarray(seq, np.int32)
    t_total = len(seq)
    exact = model.apply({"params": params}, jnp.asarray(seq)[None])

    storage = kv_storage_dtype(kv_dtype, cfg.dtype)
    n_pages = (t_total + page - 1) // page + 1  # + the null page
    shape = (cfg.num_layers, n_pages, page, cfg.num_kv_heads, cfg.resolved_head_dim)
    cache = PagedKVCache(
        pages_k=jnp.zeros(shape, storage), pages_v=jnp.zeros(shape, storage),
        k_scales=jnp.ones((cfg.num_layers, n_pages, cfg.num_kv_heads), jnp.float32),
        v_scales=jnp.ones((cfg.num_layers, n_pages, cfg.num_kv_heads), jnp.float32),
        tables=jnp.arange(1, n_pages, dtype=jnp.int32)[None],
        index=jnp.zeros((1,), jnp.int32), active=jnp.ones((1,), bool),
        quant_err=jnp.float32(0.0),
    )

    def step(c, tok):
        logits, c = model.apply({"params": params}, tok[:, None], cache=c)
        return c, logits[:, 0]

    _, replay = jax.jit(lambda c, xs: jax.lax.scan(step, c, xs))(
        cache, jnp.asarray(seq[:-1])[:, None]
    )
    # position t's logits predict token t+1; the decode region starts at the
    # last prompt position (the engine's first generated token)
    diff = jnp.abs(replay[:, 0] - exact[0, :-1])
    return float(jnp.max(diff[plen - 1:]))


def _kernel_ab_bench(args, model, cfg, params, preset):
    """Decode-kernel / KV-dtype A/B on the paged engine (one JSON line).

    Four arms, all paged, all the same heavy-tail workload:

    * **xla** (baseline) — the PR-6 gathered reference program, native KV;
    * **pallas** — the in-place paged-attention kernel, native KV.  Greedy
      outputs must be token-identical to the xla arm or the bench exits
      nonzero (the kernel swap must be invisible in the tokens);
    * **quantized** (``--kv-dtype``, default int8) at the SAME lane/page
      config — checked against a true max-logit-divergence oracle
      (:func:`_quantized_logit_divergence`; hard limit ``--kv-quant-tol``)
      and required to cut the KV pool bytes >= 40% and strictly shrink the
      decode window's ``hbm_peak_bytes`` (whose weight/activation share
      quantized KV cannot touch — the measured drop rides in ``detail``);
    * a **capacity probe** pair at BYTE-EQUAL KV HBM — a page-starved native
      arm vs a quantized arm whose pool holds the same bytes (so ~2x the
      pages at bf16->int8): quantized peak concurrent lanes must be >= 1.8x.

    The headline metric is the pallas/xla tokens/s ratio; everything else
    rides in ``detail``.
    """
    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)
    window = args.decode_window
    mp = max(16, min(args.seq, cfg.max_seq_len) // 2)
    page = max(4, mp // 4)
    buckets = (page, 2 * page)
    max_len = (min(cfg.max_seq_len, 2 * mp) // page) * page
    pages_per_lane = max_len // page
    slots = args.batch

    # the paged-ab heavy-tail chat mix: every 8th prompt long, the rest short
    r = np.random.default_rng(args.serve_seed)
    n = args.requests
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(4, mp // 12)), 0.6, n)), 4, page - 1
    ).astype(int)
    long_idx = np.arange(0, n, 8)
    prompt_lens[long_idx] = r.integers(3 * mp // 4, mp + 1, long_idx.size)
    prompts = [
        r.integers(1, cfg.vocab_size, (int(p),)).astype(np.int32)
        for p in prompt_lens
    ]
    out_cap = max(window, (max_len - mp - window) // 2)
    out_lens = np.clip(
        np.rint(r.lognormal(np.log(max(window, out_cap // 4)), 0.6, n)),
        window, out_cap,
    ).astype(int)
    gens = [GenerationConfig(max_new_tokens=int(o)) for o in out_lens]
    useful_tokens = int(out_lens.sum())

    def run_arm(kernel, kv_dtype, num_pages, num_slots, workload):
        registry = MetricsRegistry()
        eng = ServingEngine(
            model, params, num_slots=num_slots, max_len=max_len,
            max_prompt_len=max_len, prefill_buckets=buckets,
            decode_window=window, registry=registry, prefix_cache_mb=0,
            paged=True, page_size=page, num_pages=num_pages,
            decode_kernel=kernel, kv_dtype=kv_dtype,
        )
        warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32) for b in buckets]
        eng.serve(warm, GenerationConfig(max_new_tokens=window))
        for k in eng.stats:
            eng.stats[k] = 0
        eng.peak_active_lanes = 0
        registry.reset()
        t0 = time.perf_counter()
        reqs = eng.serve(workload[0], workload[1])
        dt = time.perf_counter() - t0
        return eng, reqs, dt, registry

    roomy = slots * pages_per_lane + 1  # pressure never binds the equal arms
    mix = (prompts, gens)
    eng_x, reqs_x, dt_x, reg_x = run_arm("xla", None, roomy, slots, mix)
    eng_p, reqs_p, dt_p, reg_p = run_arm("pallas", None, roomy, slots, mix)
    eng_q, reqs_q, dt_q, reg_q = run_arm("xla", args.kv_dtype, roomy, slots, mix)

    if [q.tokens for q in reqs_p] != [q.tokens for q in reqs_x]:
        raise SystemExit(
            "pallas decode kernel changed greedy outputs: pallas-arm tokens "
            "differ from the xla reference arm on the same workload"
        )

    # quantized accuracy: replay the longest completed sequence against the
    # exact no-cache forward and bound the true logit divergence
    longest = max(range(n), key=lambda i: len(prompts[i]) + len(reqs_q[i].tokens))
    seq = np.concatenate([prompts[longest], np.asarray(reqs_q[longest].tokens, np.int32)])
    divergence = _quantized_logit_divergence(
        model, cfg, params, seq, len(prompts[longest]), page, args.kv_dtype
    )
    if divergence > args.kv_quant_tol:
        raise SystemExit(
            f"quantized KV ({args.kv_dtype}) max logit divergence {divergence:.3f} "
            f"exceeds --kv-quant-tol {args.kv_quant_tol} on the replay oracle"
        )

    # quantized memory: the page pool itself, and the decode executable's
    # XLA-reported HBM peak, must both shrink >= 40% at the SAME lane count
    kv_drop = 1.0 - eng_q.kv.kv_bytes() / eng_x.kv.kv_bytes()
    if kv_drop < 0.4:
        raise SystemExit(
            f"quantized KV pool shrank only {100 * kv_drop:.1f}% "
            f"({eng_q.kv.kv_bytes()} vs {eng_x.kv.kv_bytes()} bytes); >= 40% required"
        )
    # the executable-wide serve/hbm_peak_bytes also carries weights and
    # activations, which quantized KV cannot touch — so the hard check there
    # is strict improvement, with the measured drop reported alongside
    eng_x.analyze_costs()
    eng_q.analyze_costs()
    hbm_x = eng_x.cost_table.max_hbm_peak_bytes()
    hbm_q = eng_q.cost_table.max_hbm_peak_bytes()
    hbm_drop = 1.0 - hbm_q / hbm_x if hbm_x else None
    if hbm_x and hbm_q >= hbm_x:
        raise SystemExit(
            f"quantized KV failed to shrink serve/hbm_peak_bytes "
            f"({hbm_q} vs {hbm_x}) at equal lanes"
        )

    # capacity probe at byte-equal KV HBM: uniform near-full-lane requests so
    # concurrency is page-bound, a native pool two lanes wide vs a quantized
    # pool of exactly the same bytes (integer page count rounds DOWN — the
    # quantized arm absorbs the handicap)
    probe_n = max(8, n // 2)
    probe_prompts = [
        r.integers(1, cfg.vocab_size, (mp,)).astype(np.int32) for _ in range(probe_n)
    ]
    probe_gens = [GenerationConfig(max_new_tokens=max_len - mp - window)] * probe_n
    probe_slots = max(slots, 8)
    pages_native = 2 * pages_per_lane + 1
    native_bytes = pages_native * eng_x.kv.page_kv_bytes
    pages_quant = native_bytes // eng_q.kv.page_kv_bytes
    probe = (probe_prompts, probe_gens)
    eng_cn, _, dt_cn, _ = run_arm("xla", None, pages_native, probe_slots, probe)
    eng_cq, _, dt_cq, _ = run_arm("xla", args.kv_dtype, pages_quant, probe_slots, probe)
    if eng_cq.kv.kv_bytes() > eng_cn.kv.kv_bytes():
        raise SystemExit(
            f"capacity probe budgets diverged: quantized pool {eng_cq.kv.kv_bytes()} "
            f"bytes exceeds native {eng_cn.kv.kv_bytes()} — only meaningful at "
            "byte-equal KV HBM"
        )
    lane_ratio = eng_cq.peak_active_lanes / max(1, eng_cn.peak_active_lanes)
    if lane_ratio < 1.8:
        raise SystemExit(
            f"byte-equal quantized pool peaked at {eng_cq.peak_active_lanes} lanes vs "
            f"native {eng_cn.peak_active_lanes} ({lane_ratio:.2f}x); >= 1.8x required"
        )

    def arm_detail(eng, reqs, dt, registry):
        ttft = registry.get("serve/ttft_s").snapshot()
        out = {
            "tokens_per_s": round(useful_tokens / dt, 2),
            "wall_s": round(dt, 3),
            "ttft_p50_ms": round(1e3 * ttft["p50"], 2),
            "kv_pool_bytes": eng.kv.kv_bytes(),
            "peak_active_lanes": eng.peak_active_lanes,
            "outputs_token_identical": [q.tokens for q in reqs] == [q.tokens for q in reqs_x],
            "compiled_executables": eng.compiled_executable_counts(),
            "watchdog_over_budget": eng._decode.over_budget(),
        }
        snap = registry.snapshot()
        if "serve/kv_quant_error" in snap:
            out["kv_quant_error"] = round(snap["serve/kv_quant_error"], 6)
        # set once at pool construction (the pre-timing registry reset wiped
        # the gauge), so recompute from the pool itself
        out["kv_bytes_per_token"] = round(eng.kv.page_kv_bytes / eng.kv.page_size, 2)
        return out

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "num_slots": slots,
        "decode_window": window,
        "page_size": page,
        "num_pages": roomy,
        "max_len": max_len,
        "kv_dtype": args.kv_dtype,
        "useful_tokens": useful_tokens,
        "xla": arm_detail(eng_x, reqs_x, dt_x, reg_x),
        "pallas": arm_detail(eng_p, reqs_p, dt_p, reg_p),
        "quantized": arm_detail(eng_q, reqs_q, dt_q, reg_q),
        "quantized_max_logit_divergence": round(divergence, 6),
        "kv_quant_tol": args.kv_quant_tol,
        "kv_pool_drop": round(kv_drop, 3),
        "hbm_peak_drop": round(hbm_drop, 3) if hbm_drop is not None else None,
        "capacity_probe": {
            "requests": probe_n,
            "num_slots": probe_slots,
            "native_pages": pages_native,
            "quantized_pages": int(pages_quant),
            "native_peak_lanes": eng_cn.peak_active_lanes,
            "quantized_peak_lanes": eng_cq.peak_active_lanes,
            "native_wall_s": round(dt_cn, 3),
            "quantized_wall_s": round(dt_cq, 3),
            "peak_lanes_ratio": round(lane_ratio, 3),
        },
    }
    return {
        "metric": "serving_pallas_vs_xla_tokens_per_sec_ratio",
        "value": round((useful_tokens / dt_p) / (useful_tokens / dt_x), 3),
        "unit": "x",
        "vs_baseline": round(dt_x / dt_p, 3),
        "detail": detail,
    }


def _prefill_ab_bench(args, model, cfg, params, preset):
    """Flash-prefill kernel + decode-interleaved chunked prefill A/B.

    The adversarial tenant mix the interleave exists for: one bulk tenant
    streaming near-context-length prompts (the scaled stand-in for 100k-token
    prompts) woven through chat traffic with heavy-tail log-normal output
    lengths, every request labelled via ``request_class`` so the per-class
    TTFT histograms split the two populations.  Three arms, same workload,
    same page pool:

    * **base** — non-interleaved, XLA gather/scatter prefill (the PR-6 path:
      admit-then-decode, one open prefill at a time);
    * **inter** — interleaved chunked prefill, XLA prefill program (chunks
      dispatched behind the decode window, SRTF across open prefills, joint
      per-cycle token budget);
    * **flash** — interleaved + ``prefill_kernel="pallas"`` (the paged
      flash-prefill kernel writing pages in place; interpreted off-TPU).

    Hard checks, each a nonzero exit:

    * greedy outputs of BOTH treatment arms token-identical to base — the
      kernel swap and the dispatch reorder must be invisible in the tokens;
    * ``compiled_executable_counts()`` identical across all three arms and
      every watchdog within budget — the flash kernel REPLACES each
      per-bucket prefill executable and the interleave only reorders
      dispatch; neither may add a compiled shape;
    * the treatment arms actually interleaved (``interleaved_chunks > 0``);
    * chat-class p99 TTFT >= 1.3x better than base.  On TPU the gate runs
      against the full treatment (flash); off-TPU against the XLA
      interleaved arm — interpret-mode pallas prices a prefill chunk at
      pure-Python cost, which would measure the interpreter, not the
      interleave;
    * on TPU only: flash-arm prefill tokens/s >= 0.9x the gather/scatter
      base (off-TPU the interpreted kernel makes the ratio meaningless —
      reported, not gated).

    The headline metric is the chat p99 TTFT improvement (base over
    treatment); prefill throughput and the bulk tenant's numbers ride in
    ``detail``.
    """
    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    window = args.decode_window
    # small pages so a bulk prompt takes MANY chunk cycles — that is the
    # window chat traffic must not be starved through
    max_len = cfg.max_seq_len
    page = max(4, max_len // 32)
    buckets = (page, 2 * page)
    max_len = (max_len // page) * page
    pages_per_lane = max_len // page
    mp = max_len - 2 * window  # longest admissible (bulk) prompt
    slots = args.batch

    # chat: short prompts (single chunk), heavy-tail log-normal outputs
    r = np.random.default_rng(args.serve_seed)
    n_chat = args.requests
    chat_plens = np.clip(
        np.rint(r.lognormal(np.log(max(3, page // 2)), 0.5, n_chat)), 2, page
    ).astype(int)
    out_cap = max_len - 2 * page - window
    chat_olens = np.clip(
        np.rint(r.lognormal(np.log(max(window, out_cap // 6)), 1.0, n_chat)),
        window, out_cap,
    ).astype(int)
    # bulk: near-mp prompts, minimal outputs (the tenant streams prompts in)
    n_bulk = max(2, n_chat // 8)
    bulk_plens = r.integers(3 * mp // 4, mp + 1, n_bulk)

    workload = []  # (prompt, config, class) in submission order
    for i in range(n_chat):
        workload.append((
            r.integers(1, cfg.vocab_size, (int(chat_plens[i]),)).astype(np.int32),
            GenerationConfig(max_new_tokens=int(chat_olens[i])),
            "chat",
        ))
    # bulk requests woven in FIRST in each stripe: FCFS admission puts the
    # long prefill ahead of the chat requests behind it — the starvation the
    # interleave must break
    stride = max(1, len(workload) // n_bulk)
    for j in range(n_bulk):
        workload.insert(j * (stride + 1), (
            r.integers(1, cfg.vocab_size, (int(bulk_plens[j]),)).astype(np.int32),
            GenerationConfig(max_new_tokens=window),
            "bulk",
        ))
    useful_tokens = int(chat_olens.sum()) + n_bulk * window
    roomy = slots * pages_per_lane + 1  # page pressure never binds

    def run_arm(interleave, prefill_kernel):
        registry = MetricsRegistry()
        eng = ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            max_prompt_len=mp, prefill_buckets=buckets,
            decode_window=window, registry=registry, prefix_cache_mb=0,
            paged=True, page_size=page, num_pages=roomy,
            prefill_kernel=prefill_kernel, interleave_prefill=interleave,
        )
        # warm every executable the timed serve dispatches, including the
        # lane_install scatter (compiles only on an admission AFTER the
        # first window — warm with more requests than slots)
        warm = [r.integers(1, cfg.vocab_size, (buckets[0],)).astype(np.int32)
                for _ in range(slots + 2)]
        warm[:len(buckets)] = [
            r.integers(1, cfg.vocab_size, (b,)).astype(np.int32) for b in buckets
        ]
        eng.serve(warm, GenerationConfig(max_new_tokens=window))
        for k in eng.stats:
            eng.stats[k] = 0
        registry.reset()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, config=g, request_class=c) for p, g, c in workload]
        eng.run()
        dt = time.perf_counter() - t0
        return eng, reqs, dt, registry

    eng_b, reqs_b, dt_b, reg_b = run_arm(False, "xla")
    eng_i, reqs_i, dt_i, reg_i = run_arm(True, "xla")
    eng_f, reqs_f, dt_f, reg_f = run_arm(True, "pallas")

    for name, reqs in (("interleaved", reqs_i), ("flash-prefill", reqs_f)):
        if [q.tokens for q in reqs] != [q.tokens for q in reqs_b]:
            raise SystemExit(
                f"{name} arm changed greedy outputs: tokens differ from the "
                "non-interleaved xla-prefill base arm on the same workload"
            )
    for name, eng in (("interleaved", eng_i), ("flash-prefill", eng_f)):
        if eng.compiled_executable_counts() != eng_b.compiled_executable_counts():
            raise SystemExit(
                f"{name} arm changed the compiled-executable budget: "
                f"{eng.compiled_executable_counts()} vs "
                f"{eng_b.compiled_executable_counts()}"
            )
        if eng.stats["interleaved_chunks"] <= 0:
            raise SystemExit(
                f"{name} arm never interleaved a chunk behind a decode "
                "window — the bench is not measuring interleaved prefill"
            )
        if any(f.over_budget() for f in eng._prefill.values()) or eng._decode.over_budget():
            raise SystemExit(f"{name} arm blew a recompile-watchdog budget")

    def klass_p99(reg, cls):
        return reg.get(f"serve/ttft_s_class_{cls}").snapshot()["p99"]

    ttft_base = klass_p99(reg_b, "chat")
    ttft_inter = klass_p99(reg_i, "chat")
    ttft_flash = klass_p99(reg_f, "chat")
    # off-TPU the flash arm prices prefill chunks at interpret cost; gate the
    # interleave on the kernel-equal arm there, the full treatment on TPU
    gate_ttft = ttft_flash if on_tpu else ttft_inter
    ttft_ratio = ttft_base / max(gate_ttft, 1e-9)
    if ttft_ratio < 1.3:
        raise SystemExit(
            f"interleaved chunked prefill left chat p99 TTFT at "
            f"{1e3 * gate_ttft:.1f}ms vs base {1e3 * ttft_base:.1f}ms "
            f"({ttft_ratio:.2f}x; >= 1.3x required)"
        )

    pf_tps = {
        "base": eng_b.stats["prefill_tokens"] / dt_b,
        "inter": eng_i.stats["prefill_tokens"] / dt_i,
        "flash": eng_f.stats["prefill_tokens"] / dt_f,
    }
    pf_ratio = pf_tps["flash"] / max(pf_tps["base"], 1e-9)
    if on_tpu and pf_ratio < 0.9:
        raise SystemExit(
            f"flash prefill kernel slowed prefill throughput: "
            f"{pf_tps['flash']:.1f} vs gather/scatter {pf_tps['base']:.1f} "
            f"prompt tokens/s ({pf_ratio:.2f}x; >= 0.9x required)"
        )

    def arm_detail(eng, dt, reg):
        snap = reg.snapshot()
        out = {
            "tokens_per_s": round(useful_tokens / dt, 2),
            "wall_s": round(dt, 3),
            "prefill_tokens_per_s": round(eng.stats["prefill_tokens"] / dt, 2),
            "interleaved_chunks": eng.stats["interleaved_chunks"],
            "prefill_chunks": eng.stats["prefill_chunks"],
            "interleave_ratio": round(
                float(snap.get("serve/prefill_interleave_ratio", 0.0)), 3),
            "compiled_executables": eng.compiled_executable_counts(),
        }
        for cls in ("chat", "bulk"):
            h = snap.get(f"serve/ttft_s_class_{cls}")
            if h:
                out[f"ttft_{cls}_p50_ms"] = round(1e3 * h["p50"], 2)
                out[f"ttft_{cls}_p99_ms"] = round(1e3 * h["p99"], 2)
        return out

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "chat_requests": n_chat,
        "bulk_requests": n_bulk,
        "num_slots": slots,
        "decode_window": window,
        "page_size": page,
        "max_len": max_len,
        "bulk_prompt_lens": [int(p) for p in bulk_plens],
        "useful_tokens": useful_tokens,
        "ttft_gate_arm": "flash" if on_tpu else "inter",
        "chat_ttft_p99_ratio_inter": round(ttft_base / max(ttft_inter, 1e-9), 3),
        "chat_ttft_p99_ratio_flash": round(ttft_base / max(ttft_flash, 1e-9), 3),
        "prefill_tokens_per_s_ratio_flash": round(pf_ratio, 3),
        "prefill_tps_gate": "hard" if on_tpu else "report-only (interpret)",
        "base": arm_detail(eng_b, dt_b, reg_b),
        "inter": arm_detail(eng_i, dt_i, reg_i),
        "flash": arm_detail(eng_f, dt_f, reg_f),
    }
    return {
        "metric": "serving_chat_ttft_p99_interleave_speedup",
        "value": round(ttft_ratio, 3),
        "unit": "x",
        "vs_baseline": round(ttft_ratio, 3),
        "detail": detail,
    }


def _http_ab_bench(args, model, cfg, params, preset):
    """Over-the-wire A/B of the OpenAI front door against the in-process engine.

    Four arms over one workload, each a HARD check (SystemExit on failure):

    * identity — concurrent greedy ``POST /v1/completions`` must return
      token-identical outputs to the same engine driven in-process
      (``eng.serve``) before the HTTP stack was attached;
    * streaming — every streamed request's first SSE token chunk must arrive
      strictly before its own completion ([DONE]) — TTFT < full latency;
    * flood — a burst far past ``max_queue`` must surface >= 1 HTTP 429
      (with Retry-After) and NOTHING but 200/429: admission refusals never
      become engine errors, and every 200 stays token-identical;
    * hot-swap — workers keep requests in flight while the main thread
      rolls new weights through ``FrontDoor.hot_swap``; zero failed
      requests, and every response must equal ENTIRELY the old-weights or
      ENTIRELY the new-weights in-process reference (the drain barrier
      means no request ever sees both).

    ``value`` is over-the-wire tokens/s; ``vs_baseline`` divides by the
    in-process ``eng.serve`` tokens/s on the same workload — the full HTTP +
    SSE + ticket-crossing overhead in one ratio.
    """
    import http.client
    import threading

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ReplicaRouter, ServingEngine
    from accelerate_tpu.serving.api import ApiServer, FrontDoor
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)
    slots = args.batch
    window = args.decode_window
    max_len = cfg.max_seq_len
    mp = max(8, min(args.seq, max_len) // 4)
    buckets = tuple(sorted({max(8, mp // 2), mp}))
    new_tokens = 4 * window                    # >= 2 decode windows: the first
    n = args.requests                          # SSE chunk beats [DONE]

    r = np.random.default_rng(args.serve_seed)
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, mp // 3)), 0.8, n)), 4, mp
    ).astype(int)
    prompts = [r.integers(1, cfg.vocab_size, (int(k),)).astype(np.int32)
               for k in prompt_lens]
    gen = GenerationConfig(max_new_tokens=new_tokens)
    useful_tokens = n * new_tokens

    # the queue must hold the whole in-process reference workload (serve()
    # submits every request before stepping); the flood arm scales past it
    mq = max(8, slots, n)
    registry = MetricsRegistry()
    eng = ServingEngine(
        model, params, num_slots=slots, max_len=min(max_len, mp + new_tokens + window),
        prefill_buckets=buckets, max_prompt_len=mp, decode_window=window,
        registry=registry, max_queue=mq,
    )
    warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32) for b in buckets]
    eng.serve(warm, GenerationConfig(max_new_tokens=window))

    # in-process reference + baseline timing: same engine, same executables
    t0 = time.perf_counter()
    reqs = eng.serve(prompts, [gen] * n)
    dt_inproc = time.perf_counter() - t0
    old_ref = [[int(t) for t in q.tokens] for q in reqs]

    router = ReplicaRouter([eng])
    fd = FrontDoor(router, model_name=f"bench-{preset}").start()
    srv = ApiServer(fd, registry=registry)
    host, port = srv.host, srv.port

    def post_json(path, payload, timeout=600.0):
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", path, json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = resp.read()
            return resp.status, dict(resp.getheaders()), json.loads(raw)
        finally:
            conn.close()

    def completion(i, max_tokens=new_tokens):
        return post_json("/v1/completions", {
            "prompt": [int(t) for t in prompts[i]],
            "max_tokens": max_tokens, "temperature": 0,
        })

    def fanout(fn, work):
        """Run ``fn(*item)`` for every work item on its own thread."""
        out = [None] * len(work)

        def run(k, item):
            try:
                out[k] = fn(*item)
            except Exception as exc:  # surfaced as a hard bench failure
                out[k] = exc

        threads = [threading.Thread(target=run, args=(k, item), daemon=True)
                   for k, item in enumerate(work)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = [o for o in out if isinstance(o, Exception)]
        if errs:
            raise SystemExit(f"--http-ab: client transport error: {errs[0]!r}")
        return out

    # ---- arm 1: identity (concurrent, timed — the throughput number)
    t0 = time.perf_counter()
    responses = fanout(completion, [(i,) for i in range(n)])
    dt_http = time.perf_counter() - t0
    for i, (status, _, body) in enumerate(responses):
        if status != 200:
            raise SystemExit(f"--http-ab identity: request {i} got HTTP "
                             f"{status}: {body}")
        got = body["choices"][0]["token_ids"]
        if got != old_ref[i]:
            raise SystemExit(
                f"--http-ab identity: request {i} over-the-wire tokens "
                f"{got[:8]}... != in-process {old_ref[i][:8]}..."
            )

    # ---- arm 2: streaming — TTFT strictly before the same request's [DONE]
    def stream_one(i):
        conn = http.client.HTTPConnection(host, port, timeout=600.0)
        try:
            t0 = time.perf_counter()
            conn.request("POST", "/v1/completions", json.dumps({
                "prompt": [int(t) for t in prompts[i]],
                "max_tokens": new_tokens, "temperature": 0, "stream": True,
            }), {"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                raise SystemExit(f"--http-ab stream: request {i} got HTTP "
                                 f"{resp.status}")
            toks, t_first, saw_done = [], None, False
            for raw in iter(resp.readline, b""):
                line = raw.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    saw_done = True
                    break
                ids = json.loads(data)["choices"][0]["token_ids"]
                if ids and t_first is None:
                    t_first = time.perf_counter() - t0
                toks.extend(int(t) for t in ids)
            return t_first, time.perf_counter() - t0, toks, saw_done
        finally:
            conn.close()

    n_stream = min(n, 8)
    ttfts, fulls = [], []
    for i in range(n_stream):
        ttft, full, toks, saw_done = stream_one(i)
        if not saw_done:
            raise SystemExit(f"--http-ab stream: request {i} never got the "
                             "data: [DONE] terminator")
        if toks != old_ref[i]:
            raise SystemExit(f"--http-ab stream: request {i} streamed tokens "
                             "diverge from the in-process reference")
        if ttft is None or not ttft < full:
            raise SystemExit(
                f"--http-ab stream: request {i} first token at "
                f"{ttft}s did not beat its own completion ({full:.3f}s) — "
                "SSE is buffering the whole response"
            )
        ttfts.append(ttft)
        fulls.append(full)

    # ---- arm 3: flood — burst far past max_queue; 429s, never engine errors
    flood_n = 6 * mq
    flood = fanout(lambda i: completion(i % n, window),
                   [(i,) for i in range(flood_n)])
    n_429 = sum(1 for status, _, _ in flood if status == 429)
    bad = [(status, body) for status, _, body in flood
           if status not in (200, 429)]
    if bad:
        raise SystemExit(f"--http-ab flood: non-200/429 response: {bad[0]}")
    if n_429 < 1:
        raise SystemExit(
            f"--http-ab flood: {flood_n} concurrent requests against "
            f"max_queue={mq} produced zero 429s — backpressure is not wired"
        )
    for status, headers, _ in flood:
        if status == 429 and "Retry-After" not in headers:
            raise SystemExit("--http-ab flood: 429 without a Retry-After hint")
    for k, (status, _, body) in enumerate(flood):
        if status == 200 and body["choices"][0]["token_ids"] != old_ref[k % n][:window]:
            raise SystemExit(f"--http-ab flood: admitted request {k} returned "
                             "corrupted tokens under load")

    # ---- arm 4: hot-swap under fire — zero failed, zero mixed-weight outputs
    params2 = jax.tree_util.tree_map(lambda x: x * 1.01, params)
    n_probe = min(n, 8)
    swap_results = []
    swap_lock = threading.Lock()
    stop = threading.Event()

    def hammer(widx):
        k = 0
        while not stop.is_set():
            i = (widx + k) % n_probe
            k += 1
            status, _, body = completion(i)
            with swap_lock:
                swap_results.append((i, status, body))

    workers = [threading.Thread(target=hammer, args=(w,), daemon=True)
               for w in range(3)]
    for t in workers:
        t.start()
    time.sleep(0.2)                      # get requests genuinely in flight
    n_swapped = fd.hot_swap(params2, version="v1")
    time.sleep(0.2)                      # a few post-swap requests too
    stop.set()
    for t in workers:
        t.join()
    if n_swapped != 1:
        raise SystemExit(f"--http-ab hot-swap: swapped {n_swapped} replicas, "
                         "expected 1")

    srv.stop()
    fd.stop()
    # the engine is single-threaded again: new-weights in-process reference
    new_reqs = eng.serve([prompts[i] for i in range(n_probe)], [gen] * n_probe)
    new_ref = [[int(t) for t in q.tokens] for q in new_reqs]
    n_old = n_new = 0
    for i, status, body in swap_results:
        if status != 200:
            raise SystemExit(f"--http-ab hot-swap: in-flight request failed "
                             f"with HTTP {status}: {body}")
        got = body["choices"][0]["token_ids"]
        if got == old_ref[i]:
            n_old += 1
        elif got == new_ref[i]:
            n_new += 1
        else:
            raise SystemExit(
                f"--http-ab hot-swap: probe {i} returned tokens matching "
                "NEITHER weights version entirely — a request crossed the "
                "swap barrier mid-decode"
            )
    if not swap_results:
        raise SystemExit("--http-ab hot-swap: no requests were in flight")

    http_tps = useful_tokens / dt_http
    snap = registry.snapshot()
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "num_slots": slots,
        "decode_window": window,
        "max_queue": mq,
        "new_tokens_per_request": new_tokens,
        "useful_tokens": useful_tokens,
        "http_wall_s": round(dt_http, 3),
        "inproc_wall_s": round(dt_inproc, 3),
        "inproc_tokens_per_s": round(useful_tokens / dt_inproc, 2),
        "outputs_token_identical": True,       # hard-checked above
        "streaming": {
            "requests": n_stream,
            "ttft_p50_s": round(float(np.median(ttfts)), 4),
            "full_p50_s": round(float(np.median(fulls)), 4),
            "ttft_beats_completion": True,     # hard-checked above
        },
        "flood": {
            "requests": flood_n,
            "http_429": n_429,
            "http_200": sum(1 for s, _, _ in flood if s == 200),
            "engine_errors": 0,                # hard-checked above
        },
        "hot_swap": {
            "replicas_swapped": n_swapped,
            "in_flight_requests": len(swap_results),
            "served_old_weights": n_old,
            "served_new_weights": n_new,
            "failed": 0,                       # hard-checked above
        },
        "http_requests_total": int(snap.get("serve/http_requests_total", 0)),
        "http_429_total": int(snap.get("serve/http_429_total", 0)),
        "hot_swaps_total": int(snap.get("serve/hot_swaps_total", 0)),
    }
    return {
        "metric": "http_serving_tokens_per_sec",
        "value": round(http_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(http_tps / (useful_tokens / dt_inproc), 3),
        "detail": detail,
    }


def _chaos_ab_bench(args, model, cfg, params, preset):
    """Chaos A/B: replica failure, seeded fault soak, zero-cost-when-off.

    Three arms over one greedy workload, each a HARD check (SystemExit):

    * kill — two paged replicas behind the front door; the busy one is
      poisoned mid-decode (``ServingEngine.kill``, the ``replica_kill``
      stand-in for a device loss).  Every concurrent request must still
      return HTTP 200 with tokens identical to the pre-chaos in-process
      reference (in-flight lanes replay on the survivor from prompt +
      generated prefix; greedy replay is token-exact), the router must
      record >= 1 ejection, and the dead replica must re-admit through the
      half-open circuit breaker before the arm ends;
    * soak — a seeded probabilistic fault mix (stalled fetches, injected
      page exhaustion, a one-shot fetch failure and a one-shot dispatch
      error) runs under a 2x concurrent burst: >= 99% of requests must
      complete HTTP 200 token-identical, and ZERO ``serve/driver_error``
      flight events may land — infrastructure faults never crash the
      FrontDoor driver thread;
    * off — with faults disabled the hot path must cost nothing: the
      disabled serve must be within 1% of an armed-but-inert run
      (interleaved best-of-N mins damp CPU noise), and the compile counts
      of every watchdog on both replicas must be IDENTICAL to the
      pre-chaos snapshot — kill, replay, preemption and the fault checks
      compiled zero new executables.

    ``value`` is over-the-wire tokens/s during the kill arm;
    ``vs_baseline`` divides by the in-process ``eng.serve`` tokens/s on the
    same workload — what surviving a replica loss costs end to end.
    """
    import http.client
    import threading

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ReplicaRouter, ServingEngine, faults
    from accelerate_tpu.serving.api import ApiServer, FrontDoor
    from accelerate_tpu.telemetry import MetricsRegistry, get_flight_recorder

    params = jax.device_put(params)
    slots = args.batch
    window = args.decode_window
    page = 4
    # page-aligned geometry: paged replicas so the injected page_exhaustion
    # point exercises the real preemption ladder
    mp = -(-max(8, min(args.seq, cfg.max_seq_len) // 4) // page) * page
    buckets = tuple(sorted({max(8, -(-(mp // 2) // page) * page), mp}))
    new_tokens = 4 * window
    n = args.requests
    max_len = min(cfg.max_seq_len, -(-(mp + new_tokens + window) // page) * page)
    # generous pool: exhaustion in this bench is INJECTED, a tight pool
    # would add real (but still deterministic) preemptions on top
    num_pages = 2 * slots * (max_len // page) + 1
    # the soak arm replays one replica's whole in-flight set plus a 2x burst
    # onto the survivor; the queue must absorb all of it without 429s
    mq = max(8, slots, 4 * n)

    r = np.random.default_rng(args.serve_seed)
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, mp // 3)), 0.8, n)), 4, mp
    ).astype(int)
    prompts = [r.integers(1, cfg.vocab_size, (int(k),)).astype(np.int32)
               for k in prompt_lens]
    gen = GenerationConfig(max_new_tokens=new_tokens)
    useful_tokens = n * new_tokens

    registry = MetricsRegistry()

    def build():
        return ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            prefill_buckets=buckets, decode_window=window,
            registry=registry, max_queue=mq, paged=True, page_size=page,
            num_pages=num_pages, prefix_cache_mb=0,
        )

    e1, e2 = build(), build()
    warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32)
            for b in buckets]
    for e in (e1, e2):
        e.serve(warm, GenerationConfig(max_new_tokens=window))

    # in-process reference + baseline timing (identical weights on both
    # replicas: greedy tokens are replica-independent)
    t0 = time.perf_counter()
    reqs = e1.serve(prompts, [gen] * n)
    dt_inproc = time.perf_counter() - t0
    ref = [[int(t) for t in q.tokens] for q in reqs]

    def compile_counts():
        return {f"r{k}/{wd.name}": wd.compile_count
                for k, e in enumerate((e1, e2))
                for wd in [e._decode, e._lane_install, e._copy_page,
                           *e._prefill.values()]
                if wd is not None}

    compiles_before = compile_counts()
    flight = get_flight_recorder()

    def driver_errors():
        return sum(1 for ev in flight.tail()
                   if ev.get("kind") == "serve/driver_error")

    derr_before = driver_errors()

    router = ReplicaRouter([e1, e2], registry=registry, breaker_base_s=0.05)
    fd = FrontDoor(router, model_name=f"bench-{preset}").start()
    srv = ApiServer(fd, registry=registry)
    host, port = srv.host, srv.port

    def post_json(path, payload, timeout=600.0):
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", path, json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = resp.read()
            return resp.status, dict(resp.getheaders()), json.loads(raw)
        finally:
            conn.close()

    def completion(i, max_tokens=new_tokens):
        return post_json("/v1/completions", {
            "prompt": [int(t) for t in prompts[i]],
            "max_tokens": max_tokens, "temperature": 0,
        })

    def fanout(fn, work):
        out = [None] * len(work)

        def run(k, item):
            try:
                out[k] = fn(*item)
            except Exception as exc:  # surfaced as a hard bench failure
                out[k] = exc

        threads = [threading.Thread(target=run, args=(k, item), daemon=True)
                   for k, item in enumerate(work)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = [o for o in out if isinstance(o, Exception)]
        if errs:
            raise SystemExit(f"--chaos-ab: client transport error: {errs[0]!r}")
        return out

    # ---- arm 1: replica kill mid-generation — zero failed, token identity
    killed = {}

    def assassin():
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            for name, e in (("r1", e2), ("r0", e1)):
                if e in router.engines and e._active.any():
                    e.kill("chaos-ab: injected mid-decode device loss")
                    killed["replica"] = name
                    return
            time.sleep(0.002)

    kt = threading.Thread(target=assassin, daemon=True)
    kt.start()
    t0 = time.perf_counter()
    responses = fanout(completion, [(i,) for i in range(n)])
    dt_chaos = time.perf_counter() - t0
    kt.join()
    if "replica" not in killed:
        raise SystemExit("--chaos-ab kill: no replica ever had in-flight "
                         "lanes to kill — the workload never got going")
    for i, (status, _, body) in enumerate(responses):
        if status != 200:
            raise SystemExit(f"--chaos-ab kill: request {i} failed with HTTP "
                             f"{status} after the replica kill: {body}")
        got = body["choices"][0]["token_ids"]
        if got != ref[i]:
            raise SystemExit(
                f"--chaos-ab kill: request {i} returned {got[:8]}... != "
                f"in-process reference {ref[i][:8]}... — replay after the "
                "kill was not token-identical"
            )
    snap = registry.snapshot()
    ejections = int(snap.get("serve/replica_ejections_total", 0))
    if ejections < 1:
        raise SystemExit("--chaos-ab kill: a replica was poisoned but "
                         "serve/replica_ejections_total is 0 — the router "
                         "supervisor never ejected it")
    replays = sum(e.stats["requests_replayed"] for e in (e1, e2))
    t_end = time.monotonic() + 30.0
    while time.monotonic() < t_end and len(router.engines) < 2:
        time.sleep(0.01)
    if len(router.engines) < 2:
        raise SystemExit("--chaos-ab kill: the ejected replica never "
                         "re-admitted through the half-open circuit breaker")

    # ---- arm 2: seeded fault-mix soak — >= 99% completion, driver survives
    soak_n = 2 * n
    soak_plan = (f"seed={args.serve_seed},fetch_slow=0.05,slow_ms=5,"
                 f"page_exhaustion=0.01,fetch_fail@7,decode_dispatch@29")
    faults.install(soak_plan, registry=registry)
    try:
        soak = fanout(completion, [(i % n,) for i in range(soak_n)])
    finally:
        faults.clear()
    completed = sum(
        1 for k, (status, _, body) in enumerate(soak)
        if status == 200 and body["choices"][0]["token_ids"] == ref[k % n]
    )
    for k, (status, _, body) in enumerate(soak):
        if status == 200 and body["choices"][0]["token_ids"] != ref[k % n]:
            raise SystemExit(
                f"--chaos-ab soak: request {k} returned HTTP 200 with "
                "tokens diverging from the reference — a fault corrupted a "
                "surviving lane"
            )
    rate = completed / soak_n
    if rate < 0.99:
        bad = [(k, s) for k, (s, _, _) in enumerate(soak) if s != 200]
        raise SystemExit(
            f"--chaos-ab soak: {completed}/{soak_n} completed "
            f"({rate:.1%}) under the fault mix; gate is >= 99%. "
            f"non-200s: {bad[:5]}"
        )
    derr = driver_errors() - derr_before
    if derr != 0:
        raise SystemExit(
            f"--chaos-ab soak: {derr} serve/driver_error flight event(s) — "
            "an injected fault escaped containment and crashed the "
            "FrontDoor driver thread"
        )
    faults_fired = int(registry.snapshot().get(
        "serve/faults_injected_total", 0))
    if faults_fired < 1:
        raise SystemExit("--chaos-ab soak: the fault plan never fired — the "
                         "soak arm tested nothing")
    t_end = time.monotonic() + 30.0
    while time.monotonic() < t_end and len(router.engines) < 2:
        time.sleep(0.01)

    srv.stop()
    fd.stop()

    # ---- arm 3: faults disabled — zero hot-path cost, zero new executables
    # interleave disabled and armed-but-inert (one-shot parked far beyond
    # the workload: every check consults the injector, none fire) runs,
    # alternating which goes first, and gate on the MEDIAN of per-rep
    # paired ratios: back-to-back pairs cancel machine drift, alternation
    # cancels ordering bias, the median kills outlier pairs — min-of-N on
    # its own still carries multi-percent jitter on shared hosts
    reps = 8
    rounds = 3  # serve() calls per timed sample — lifts each sample well
    # above scheduler/timer jitter so the 1% gate measures the hot path
    t_off, t_armed = [], []
    inert = f"seed={args.serve_seed},decode_dispatch@1000000000"
    faults.clear()
    e1.serve(prompts, [gen] * n)  # discarded warm-up

    def _timed_off():
        faults.clear()
        t0 = time.perf_counter()
        for _ in range(rounds):
            e1.serve(prompts, [gen] * n)
        t_off.append(time.perf_counter() - t0)

    def _timed_armed():
        faults.install(inert, registry=registry)
        try:
            t0 = time.perf_counter()
            for _ in range(rounds):
                e1.serve(prompts, [gen] * n)
            t_armed.append(time.perf_counter() - t0)
        finally:
            faults.clear()

    for k in range(reps):
        first, second = ((_timed_off, _timed_armed) if k % 2 == 0
                         else (_timed_armed, _timed_off))
        first()
        second()
    best_off, best_armed = min(t_off), min(t_armed)
    ratios = sorted(o / a for o, a in zip(t_off, t_armed))
    mid = len(ratios) // 2
    med_ratio = (ratios[mid] if len(ratios) % 2
                 else 0.5 * (ratios[mid - 1] + ratios[mid]))
    if med_ratio > 1.01:
        raise SystemExit(
            f"--chaos-ab off: faults-disabled serve is {med_ratio - 1.0:+.1%} "
            f"vs the armed-but-inert run (median of {reps} paired ratios; "
            f"mins {best_off:.3f}s vs {best_armed:.3f}s) — the disabled "
            "path is doing work; gate is <= 1%"
        )
    compiles_after = compile_counts()
    if compiles_after != compiles_before:
        diff = {k: (compiles_before.get(k), v)
                for k, v in compiles_after.items()
                if compiles_before.get(k) != v}
        raise SystemExit(f"--chaos-ab off: chaos compiled new executables "
                         f"(name: before -> after): {diff}")

    chaos_tps = useful_tokens / dt_chaos
    snap = registry.snapshot()
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "num_slots": slots,
        "decode_window": window,
        "new_tokens_per_request": new_tokens,
        "useful_tokens": useful_tokens,
        "chaos_wall_s": round(dt_chaos, 3),
        "inproc_wall_s": round(dt_inproc, 3),
        "inproc_tokens_per_s": round(useful_tokens / dt_inproc, 2),
        "kill": {
            "killed_replica": killed["replica"],
            "failed": 0,                       # hard-checked above
            "outputs_token_identical": True,   # hard-checked above
            "ejections": ejections,
            "requests_replayed": replays,
            "breaker_readmitted": True,        # hard-checked above
        },
        "soak": {
            "plan": soak_plan,
            "requests": soak_n,
            "completed": completed,
            "completion_rate": round(rate, 4),
            "faults_injected": faults_fired,
            "driver_errors": 0,                # hard-checked above
        },
        "off": {
            "repeats": reps,
            "disabled_best_s": round(best_off, 4),
            "armed_inert_best_s": round(best_armed, 4),
            "disabled_vs_armed": round(best_off / best_armed, 4),
            "disabled_vs_armed_median": round(med_ratio, 4),
            "new_executables": 0,              # hard-checked above
        },
        "replica_ejections_total": int(
            snap.get("serve/replica_ejections_total", 0)),
        "requests_replayed_total": sum(
            e.stats["requests_replayed"] for e in (e1, e2)),
        "faults_injected_total": int(
            snap.get("serve/faults_injected_total", 0)),
        "deadline_shed_total": sum(
            e.stats["deadline_shed"] for e in (e1, e2)),
    }
    return {
        "metric": "chaos_serving_tokens_per_sec",
        "value": round(chaos_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(chaos_tps / (useful_tokens / dt_inproc), 3),
        "detail": detail,
    }


def _trace_ab_bench(args, model, cfg, params, preset):
    """Request-trace A/B: waterfall fidelity on vs zero cost off.

    Three arms over one greedy workload, each a HARD check (SystemExit):

    * waterfall — two paged replicas behind the front door; the busy one is
      killed mid-decode.  Every request must return HTTP 200 token-identical
      to the in-process reference, and every response's ``X-Request-Id``
      must resolve at ``GET /debug/requests/<id>`` to a waterfall whose
      tiled phase sum attributes the trace's own TTFT within 5% (20ms
      noise floor on shared CPU hosts).  At least one surviving request
      must carry a ``failover`` phase spanning BOTH replica ids — the
      trace rode ``export_inflight``/``adopt`` instead of restarting —
      and the ``/debug/requests`` index must hold populated slowest-K
      rings (the tail the tracing exists to explain);
    * off — tracing toggled off (``reqtrace.set_enabled(False)``) must
      serve token-identical to tracing on, and the null-calibrated paired
      overhead (pooled median of rotating on/off/control min-of-2 samples)
      must be <= 1% beyond the off-vs-off control drift measured in the
      same run — per-request attribution may not tax serve throughput;
    * budget — compile counts of every watchdog on both replicas must be
      IDENTICAL before and after: tracing is host-side bookkeeping and
      compiles NOTHING.

    ``value`` is over-the-wire tokens/s during the kill arm (the traced,
    failover-surviving path); ``vs_baseline`` divides by in-process
    ``eng.serve`` tokens/s on the same workload.
    """
    import http.client
    import threading

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ReplicaRouter, ServingEngine
    from accelerate_tpu.serving.api import ApiServer, FrontDoor
    from accelerate_tpu.telemetry import MetricsRegistry, get_reqtrace
    from accelerate_tpu.telemetry import reqtrace as reqtrace_mod

    params = jax.device_put(params)
    slots = args.batch
    window = args.decode_window
    page = 4
    mp = -(-max(8, min(args.seq, cfg.max_seq_len) // 4) // page) * page
    buckets = tuple(sorted({max(8, -(-(mp // 2) // page) * page), mp}))
    new_tokens = 4 * window
    n = args.requests
    max_len = min(cfg.max_seq_len, -(-(mp + new_tokens + window) // page) * page)
    num_pages = 2 * slots * (max_len // page) + 1
    mq = max(8, slots, 2 * n)

    r = np.random.default_rng(args.serve_seed)
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, mp // 3)), 0.8, n)), 4, mp
    ).astype(int)
    prompts = [r.integers(1, cfg.vocab_size, (int(k),)).astype(np.int32)
               for k in prompt_lens]
    gen = GenerationConfig(max_new_tokens=new_tokens)
    useful_tokens = n * new_tokens

    registry = MetricsRegistry()
    reqtrace_mod.set_enabled(None)
    get_reqtrace().reset()

    def build():
        return ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            prefill_buckets=buckets, decode_window=window,
            registry=registry, max_queue=mq, paged=True, page_size=page,
            num_pages=num_pages, prefix_cache_mb=0,
        )

    e1, e2 = build(), build()
    warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32)
            for b in buckets]
    for e in (e1, e2):
        e.serve(warm, GenerationConfig(max_new_tokens=window))

    t0 = time.perf_counter()
    reqs = e1.serve(prompts, [gen] * n)
    dt_inproc = time.perf_counter() - t0
    ref = [[int(t) for t in q.tokens] for q in reqs]

    def compile_counts():
        return {f"r{k}/{wd.name}": wd.compile_count
                for k, e in enumerate((e1, e2))
                for wd in [e._decode, e._lane_install, e._copy_page,
                           *e._prefill.values()]
                if wd is not None}

    compiles_before = compile_counts()
    get_reqtrace().reset()  # warmup/reference traces are not part of the arm

    router = ReplicaRouter([e1, e2], registry=registry, breaker_base_s=0.05)
    fd = FrontDoor(router, model_name=f"bench-{preset}").start()
    srv = ApiServer(fd, registry=registry)
    host, port = srv.host, srv.port

    def http_json(method, path, payload=None, timeout=600.0):
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            body = None if payload is None else json.dumps(payload)
            headers = {} if payload is None else {
                "Content-Type": "application/json"}
            conn.request(method, path, body, headers)
            resp = conn.getresponse()
            raw = resp.read()
            return resp.status, dict(resp.getheaders()), json.loads(raw)
        finally:
            conn.close()

    def completion(i):
        return http_json("POST", "/v1/completions", {
            "prompt": [int(t) for t in prompts[i]],
            "max_tokens": new_tokens, "temperature": 0,
        })

    def fanout(fn, work):
        out = [None] * len(work)

        def run(k, item):
            try:
                out[k] = fn(*item)
            except Exception as exc:
                out[k] = exc

        threads = [threading.Thread(target=run, args=(k, item), daemon=True)
                   for k, item in enumerate(work)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = [o for o in out if isinstance(o, Exception)]
        if errs:
            raise SystemExit(f"--trace-ab: client transport error: {errs[0]!r}")
        return out

    # ---- arm 1: traced workload + mid-generation kill — waterfall fidelity
    killed = {}

    def assassin():
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            for name, e in (("r1", e2), ("r0", e1)):
                if e in router.engines and e._active.any():
                    e.kill("trace-ab: injected mid-decode device loss")
                    killed["replica"] = name
                    return
            time.sleep(0.002)

    kt = threading.Thread(target=assassin, daemon=True)
    kt.start()
    t0 = time.perf_counter()
    responses = fanout(completion, [(i,) for i in range(n)])
    dt_traced = time.perf_counter() - t0
    kt.join()
    if "replica" not in killed:
        raise SystemExit("--trace-ab: no replica ever had in-flight lanes "
                         "to kill — the workload never got going")

    failovers = 0
    worst_attr_err = 0.0
    for i, (status, headers, body) in enumerate(responses):
        if status != 200:
            raise SystemExit(f"--trace-ab: request {i} failed with HTTP "
                             f"{status} after the replica kill: {body}")
        got = body["choices"][0]["token_ids"]
        if got != ref[i]:
            raise SystemExit(
                f"--trace-ab: request {i} returned {got[:8]}... != "
                f"in-process reference {ref[i][:8]}... under tracing"
            )
        rid = headers.get("X-Request-Id")
        if not rid:
            raise SystemExit(f"--trace-ab: request {i} response carried no "
                             "X-Request-Id header")
        wstatus, _, wf = http_json("GET", f"/debug/requests/{rid}")
        if wstatus != 200:
            raise SystemExit(
                f"--trace-ab: GET /debug/requests/{rid} -> {wstatus}; the "
                "completed trace fell out of retention while addressable"
            )
        if wf["status"] != "done":
            raise SystemExit(f"--trace-ab: request {i} trace status "
                             f"{wf['status']!r} != 'done'")
        ttft, attr = wf["ttft_s"], wf["ttft_attributed_s"]
        err = abs(attr - ttft)
        worst_attr_err = max(worst_attr_err, err / max(ttft, 1e-9))
        if err > max(0.05 * ttft, 0.02):
            raise SystemExit(
                f"--trace-ab: request {i} ({rid}) phase sum {attr:.4f}s "
                f"diverges from measured TTFT {ttft:.4f}s by more than "
                "max(5%, 20ms) — the waterfall does not attribute latency"
            )
        if wf["failover"]:
            failovers += 1
            if len(wf["replicas"]) < 2:
                raise SystemExit(
                    f"--trace-ab: failover trace {rid} lists replicas "
                    f"{wf['replicas']} — the trace did not span both"
                )
            if not any(p["phase"] == "failover" for p in wf["phase_list"]):
                raise SystemExit(
                    f"--trace-ab: failover trace {rid} has no 'failover' "
                    "phase — adoption restarted the waterfall"
                )
    if failovers < 1:
        raise SystemExit("--trace-ab: a replica died mid-generation but no "
                         "completed trace records a failover — the trace "
                         "did not ride export_inflight/adopt")
    istatus, _, index = http_json("GET", "/debug/requests")
    if istatus != 200:
        raise SystemExit(f"--trace-ab: GET /debug/requests -> {istatus}")
    if not index["slowest_ttft"] or not index["slowest_total"]:
        raise SystemExit("--trace-ab: the slowest-K retention rings are "
                         "empty after a full workload — tail-based "
                         "retention is not retaining the tail")

    t_end = time.monotonic() + 30.0
    while time.monotonic() < t_end and len(router.engines) < 2:
        time.sleep(0.01)
    srv.stop()
    fd.stop()

    # ---- arm 2: tracing off — token identity + <= 1% interleaved overhead
    reqtrace_mod.set_enabled(False)
    try:
        off_reqs = e1.serve(prompts, [gen] * n)
    finally:
        reqtrace_mod.set_enabled(None)
    off_tokens = [[int(t) for t in q.tokens] for q in off_reqs]
    if off_tokens != ref:
        raise SystemExit("--trace-ab: tokens with tracing disabled diverge "
                         "from the traced reference — the trace hooks "
                         "touch the decode path")

    # Overhead is measured as a NULL-CALIBRATED paired A/B.  Three arms
    # rotate back to back per pair — tracing ON, tracing OFF, and a second
    # tracing-off CONTROL with identical plumbing.  Each sample is the min
    # of two consecutive serves (host contention is one-sided; the min
    # filters the spike tail), and the pooled medians are re-checked after
    # each sequential batch with early exit.  The gate is
    #
    #     median(on/off)  <=  1.01 + |median(ctl/off) - 1|
    #
    # i.e. tracing may cost at most 1% BEYOND what the instrument itself
    # drifts between two IDENTICAL arms in the same run.  On a quiet host
    # the control median sits at 1.000 and the gate is a strict 1%; on a
    # host where two identical arms differ by 2%, a 1% verdict would be
    # astrology — the demonstrated noise floor widens the gate by exactly
    # what the null shows, and a real multi-percent regression still fails
    # because the control does not move with the treatment.
    # The arm runs on a FRESH replica with a reset registry: e1's
    # post-kill state differs run to run (it may or may not be the revived
    # victim), and the retention rings full of HTTP-arm traces were already
    # hard-checked above — what this arm isolates is the steady marginal
    # cost of tracing on a healthy replica.
    # One more defence: pairs where EITHER sample sits far above its own
    # arm's floor were hit by a contention burst mid-pair — both medians
    # drop them (symmetrically, so a real regression cannot hide: a serve
    # that is slower BECAUSE of tracing raises the on-arm floor itself and
    # survives the trim).  The gate judges the uncontended regime, which
    # is the regime "<= 1% overhead" is a statement about.
    pairs_per_batch = 24
    max_batches = 4
    min_kept = 12
    t_on, t_off, t_ctl = [], [], []
    e3 = build()
    e3.serve(warm, GenerationConfig(max_new_tokens=window))
    get_reqtrace().reset()
    for _ in range(2):  # discarded warm-up; also settles server teardown
        e3.serve(prompts, [gen] * n)

    def _timed(flag, sink):
        reqtrace_mod.set_enabled(flag)
        try:
            best = None
            for _ in range(2):
                t0 = time.perf_counter()
                e3.serve(prompts, [gen] * n)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            sink.append(best)
        finally:
            reqtrace_mod.set_enabled(None)

    def _median(vals):
        vals = sorted(vals)
        mid = len(vals) // 2
        return (vals[mid] if len(vals) % 2
                else 0.5 * (vals[mid - 1] + vals[mid]))

    arms = [(True, t_on), (False, t_off), (False, t_ctl)]
    med_ratio = null_ratio = allowance = None
    for _ in range(max_batches):
        for k in range(pairs_per_batch):
            for flag, sink in arms[k % 3:] + arms[:k % 3]:
                _timed(flag, sink)
        lim_on = 1.25 * min(t_on)
        lim_off = 1.25 * min(t_off)
        lim_ctl = 1.25 * min(t_ctl)
        kept = [(on, off, c) for on, off, c in zip(t_on, t_off, t_ctl)
                if on <= lim_on and off <= lim_off and c <= lim_ctl]
        if len(kept) < min_kept:
            continue
        med_ratio = _median([on / off for on, off, _ in kept])
        null_ratio = _median([c / off for _, off, c in kept])
        allowance = abs(null_ratio - 1.0)
        if med_ratio <= 1.01 + allowance:
            break
    if med_ratio is None:
        raise SystemExit(
            f"--trace-ab: host contention too heavy to measure — fewer than "
            f"{min_kept} of {len(t_on)} paired samples survived the burst "
            f"trim; rerun on a quieter host"
        )
    if med_ratio > 1.01 + allowance:
        raise SystemExit(
            f"--trace-ab: tracing-on serve is {med_ratio - 1.0:+.1%} vs "
            f"tracing-off (pooled median of {len(t_on)} paired min-of-2 "
            f"samples after burst trim) while the off-vs-off control shows "
            f"{null_ratio - 1.0:+.1%} instrument drift — tracing costs "
            f">1% beyond the demonstrated noise floor; gate is <= "
            f"{1.01 + allowance - 1.0:.1%}"
        )

    # ---- arm 3: tracing compiled nothing
    compiles_after = compile_counts()
    if compiles_after != compiles_before:
        diff = {k: (compiles_before.get(k), v)
                for k, v in compiles_after.items()
                if compiles_before.get(k) != v}
        raise SystemExit(f"--trace-ab: tracing compiled new executables "
                         f"(name: before -> after): {diff}")

    traced_tps = useful_tokens / dt_traced
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "num_slots": slots,
        "decode_window": window,
        "new_tokens_per_request": new_tokens,
        "useful_tokens": useful_tokens,
        "traced_wall_s": round(dt_traced, 3),
        "inproc_wall_s": round(dt_inproc, 3),
        "inproc_tokens_per_s": round(useful_tokens / dt_inproc, 2),
        "waterfall": {
            "killed_replica": killed["replica"],
            "outputs_token_identical": True,   # hard-checked above
            "failover_traces": failovers,
            "worst_ttft_attribution_error": round(worst_attr_err, 4),
            "slowest_ttft_retained": len(index["slowest_ttft"]),
            "slowest_total_retained": len(index["slowest_total"]),
        },
        "off": {
            "pairs": len(t_on),
            "outputs_token_identical": True,   # hard-checked above
            "on_best_s": round(min(t_on), 4),
            "off_best_s": round(min(t_off), 4),
            "on_vs_off_median": round(med_ratio, 4),
            "off_vs_off_control_median": round(null_ratio, 4),
            "gate": round(1.01 + allowance, 4),
            "new_executables": 0,              # hard-checked above
        },
    }
    return {
        "metric": "traced_serving_tokens_per_sec",
        "value": round(traced_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(traced_tps / (useful_tokens / dt_inproc), 3),
        "detail": detail,
    }


def _slo_ab_bench(args, model, cfg, params, preset):
    """Fleet-health A/B: exact tenant attribution, forced burn, zero cost.

    Four arms over one greedy workload, each a HARD check (SystemExit):

    * tenants — two tenants flood the HTTP front door over two paged
      replicas, half resolved from the ``X-Tenant`` header and half from
      the ``Authorization: Bearer <tenant>-...`` key prefix.  Every 200
      response must echo ``X-Tenant`` and return tokens identical to the
      in-process reference, and for EVERY per-request counter key the
      engines bumped, the per-tenant family deltas must sum EXACTLY to the
      global counter delta (attribution is accounting, not sampling) — the
      per-tenant TTFT histogram counts likewise, and the
      ``stats()["tenants"]`` rollup must agree with the counter families;
    * burn — a TTFT SLO sized off a clean run of the same workload must
      NOT burn clean, then ``fetch_slow`` stalls (the ``ATPU_FAULTS``
      injector) push every TTFT over threshold and the engine must capture
      EXACTLY ONE diagnostics bundle — the cooldown must hold across
      several more fast-burning ticks — whose JSON carries the triggering
      verdict, stacks, the flight-ring tail, and the time-series window
      that shows the burn itself;
    * off — SLOs + tenant attribution + ring sampling on, vs all of it
      off: the null-calibrated paired overhead (same methodology and gate
      as ``--trace-ab``) must be <= 1% beyond the off-vs-off control
      drift, with outputs token-identical;
    * budget — compile counts of every watchdog on all three replicas
      must be IDENTICAL before and after: the fleet-health layer is
      host-side bookkeeping and compiles NOTHING.

    ``value`` is over-the-wire tokens/s during the tenant flood (the
    attributed path); ``vs_baseline`` divides by in-process ``eng.serve``
    tokens/s on the same workload.
    """
    import http.client
    import tempfile
    import threading

    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ReplicaRouter, ServingEngine, faults
    from accelerate_tpu.serving.api import ApiServer, FrontDoor
    from accelerate_tpu.telemetry import (
        MetricsRegistry,
        SloSpec,
        TimeSeriesStore,
        default_specs,
        install_slos,
        uninstall_slos,
    )

    params = jax.device_put(params)
    slots = args.batch
    window = args.decode_window
    page = 4
    mp = -(-max(8, min(args.seq, cfg.max_seq_len) // 4) // page) * page
    buckets = tuple(sorted({max(8, -(-(mp // 2) // page) * page), mp}))
    new_tokens = 4 * window
    n = args.requests
    max_len = min(cfg.max_seq_len, -(-(mp + new_tokens + window) // page) * page)
    num_pages = 2 * slots * (max_len // page) + 1
    mq = max(8, slots, 2 * n)

    r = np.random.default_rng(args.serve_seed)
    prompt_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, mp // 3)), 0.8, n)), 4, mp
    ).astype(int)
    prompts = [r.integers(1, cfg.vocab_size, (int(k),)).astype(np.int32)
               for k in prompt_lens]
    gen = GenerationConfig(max_new_tokens=new_tokens)
    useful_tokens = n * new_tokens
    tenants = ("acme", "umbrella")

    registry = MetricsRegistry()
    uninstall_slos()  # a leftover global engine would tick into our arms

    def build():
        return ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            prefill_buckets=buckets, decode_window=window,
            registry=registry, max_queue=mq, paged=True, page_size=page,
            num_pages=num_pages, prefix_cache_mb=0,
        )

    e1, e2, e3 = build(), build(), build()
    warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32)
            for b in buckets]
    for e in (e1, e2, e3):
        e.serve(warm, GenerationConfig(max_new_tokens=window))

    t0 = time.perf_counter()
    reqs = e1.serve(prompts, [gen] * n)
    dt_inproc = time.perf_counter() - t0
    ref = [[int(t) for t in q.tokens] for q in reqs]

    def compile_counts():
        return {f"r{k}/{wd.name}": wd.compile_count
                for k, e in enumerate((e1, e2, e3))
                for wd in [e._decode, e._lane_install, e._copy_page,
                           *e._prefill.values()]
                if wd is not None}

    compiles_before = compile_counts()

    # the probe is the tentpole's own windowed store: two manual samples
    # bracket the flood, and every gate below is a windowed delta over them
    probe = TimeSeriesStore(registry=registry, capacity=8, interval_s=0.0)

    def rollup():
        merged = {}
        for e in (e1, e2):
            for t, keys in e.stats().get("tenants", {}).items():
                bucket = merged.setdefault(t, {})
                for key, v in keys.items():
                    bucket[key] = bucket.get(key, 0) + v
        return merged

    router = ReplicaRouter([e1, e2], registry=registry)
    fd = FrontDoor(router, model_name=f"bench-{preset}").start()
    srv = ApiServer(fd, registry=registry)
    host, port = srv.host, srv.port

    def http_json(method, path, payload=None, headers=None, timeout=600.0):
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            body = None if payload is None else json.dumps(payload)
            hdrs = dict(headers or {})
            if payload is not None:
                hdrs.setdefault("Content-Type", "application/json")
            conn.request(method, path, body, hdrs)
            resp = conn.getresponse()
            raw = resp.read()
            return resp.status, dict(resp.getheaders()), json.loads(raw)
        finally:
            conn.close()

    def completion(i):
        # even requests carry the explicit header, odd ones the API-key
        # prefix — both resolution paths must attribute identically
        tenant = tenants[i % 2]
        if i % 4 < 2:
            hdrs = {"X-Tenant": tenant}
        else:
            hdrs = {"Authorization": f"Bearer {tenant}-s3cr3t{i}"}
        return http_json("POST", "/v1/completions", {
            "prompt": [int(t) for t in prompts[i]],
            "max_tokens": new_tokens, "temperature": 0,
        }, headers=hdrs)

    def fanout(fn, work):
        out = [None] * len(work)

        def run(k, item):
            try:
                out[k] = fn(*item)
            except Exception as exc:
                out[k] = exc

        threads = [threading.Thread(target=run, args=(k, item), daemon=True)
                   for k, item in enumerate(work)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = [o for o in out if isinstance(o, Exception)]
        if errs:
            raise SystemExit(f"--slo-ab: client transport error: {errs[0]!r}")
        return out

    # ---- arm 1: tenant flood — attribution must sum exactly to globals
    before = probe.sample()
    roll_before = rollup()
    t0 = time.perf_counter()
    responses = fanout(completion, [(i,) for i in range(n)])
    dt_flood = time.perf_counter() - t0
    after = probe.sample()
    roll_after = rollup()
    srv.stop()
    fd.stop()

    for i, (status, headers, body) in enumerate(responses):
        if status != 200:
            raise SystemExit(
                f"--slo-ab: request {i} failed with HTTP {status}: {body}")
        got = body["choices"][0]["token_ids"]
        if got != ref[i]:
            raise SystemExit(
                f"--slo-ab: request {i} returned {got[:8]}... != in-process "
                f"reference {ref[i][:8]}... under tenant attribution")
        echo = headers.get("X-Tenant")
        if echo != tenants[i % 2]:
            raise SystemExit(
                f"--slo-ab: request {i} (tenant {tenants[i % 2]!r}, "
                f"{'header' if i % 4 < 2 else 'api-key'}-resolved) echoed "
                f"X-Tenant {echo!r} — the front door lost the attribution")

    def cdelta(name):
        return (after["counters"].get(name, 0.0)
                - before["counters"].get(name, 0.0))

    keys = set()
    for name in after["counters"]:
        for t in tenants:
            tag = f"_tenant_{t}_total"
            if name.startswith("serve/") and name.endswith(tag):
                keys.add(name[len("serve/"):-len(tag)])
    if not {"requests_submitted", "tokens_generated"} <= keys:
        raise SystemExit(
            f"--slo-ab: tenant counter families missing after the flood — "
            f"saw keys {sorted(keys)}; attribution never engaged")
    for key in sorted(keys):
        by_tenant = {t: cdelta(f"serve/{key}_tenant_{t}_total")
                     for t in tenants}
        total = cdelta(f"serve/{key}_total")
        if sum(by_tenant.values()) != total:
            raise SystemExit(
                f"--slo-ab: serve/{key}_total grew by {total} during the "
                f"flood but the tenant families account for {by_tenant} — "
                f"per-tenant attribution does not sum to the global counter")
        for t in tenants:
            r_delta = (roll_after.get(t, {}).get(key, 0)
                       - roll_before.get(t, {}).get(key, 0))
            if r_delta != by_tenant[t]:
                raise SystemExit(
                    f"--slo-ab: stats()['tenants'][{t!r}][{key!r}] delta "
                    f"{r_delta} != counter-family delta {by_tenant[t]} — "
                    f"the rollup and the registry disagree")

    def hist_count(sample, name):
        return sample["hists"].get(name, {}).get("count", 0)

    ttft_total = (hist_count(after, "serve/ttft_s")
                  - hist_count(before, "serve/ttft_s"))
    ttft_by_tenant = {
        t: (hist_count(after, f"serve/ttft_s_tenant_{t}")
            - hist_count(before, f"serve/ttft_s_tenant_{t}"))
        for t in tenants}
    if ttft_total != n or sum(ttft_by_tenant.values()) != ttft_total:
        raise SystemExit(
            f"--slo-ab: serve/ttft_s observed {ttft_total} TTFTs for {n} "
            f"requests and the tenant histograms hold {ttft_by_tenant} — "
            f"per-tenant TTFT attribution is lossy")

    # ---- arm 2: forced fast-burn — exactly one bundle, cooldown holds
    t0 = time.perf_counter()
    tiny_ref = e1.serve(prompts[:2], [GenerationConfig(max_new_tokens=window)] * 2)
    dt_tiny = time.perf_counter() - t0
    del tiny_ref
    bounds = None
    for name, metric in registry.items():
        if name == "serve/ttft_s":
            bounds = metric.bucket_snapshot()["bounds"]
    if not bounds:
        raise SystemExit("--slo-ab: serve/ttft_s histogram missing")
    # round the threshold UP to a bucket bound: clean TTFTs then always
    # land in buckets wholly at-or-under it (counted good, no split-bucket
    # interpolation), and stalled TTFTs wholly above it (never good)
    thr_raw = max(3.0 * dt_tiny, 0.05)
    thr = next((b for b in bounds if b >= thr_raw), bounds[-1])
    stall_s = max(0.25, 2.0 * thr)
    store = TimeSeriesStore(registry=registry, capacity=512, interval_s=0.02)
    eng_slo = install_slos(
        specs=[SloSpec(name="ttft_burn", kind="latency", objective=0.99,
                       hist="serve/ttft_s", threshold_s=thr)],
        store=store, registry=registry,
        fast_window_s=0.3, slow_window_s=1.2, cooldown_s=3600.0)
    flight_dir = tempfile.mkdtemp(prefix="slo-ab-")
    env_before = os.environ.get("ATPU_FLIGHT_DIR")
    os.environ["ATPU_FLIGHT_DIR"] = flight_dir
    try:
        e1.serve(prompts[:2], [GenerationConfig(max_new_tokens=window)] * 2,
                 metrics_interval=0.01)
        store.sample()
        clean = eng_slo.evaluate()["ttft_burn"]
        if clean["fast_burning"] or eng_slo.bundles:
            raise SystemExit(
                f"--slo-ab: the CLEAN workload fast-burned a "
                f"{thr:.3f}s TTFT SLO ({clean}) — either the threshold "
                f"sizing is astrology or the host is too contended; rerun "
                f"on a quieter host")
        fault_counter = "serve/faults_injected_total"
        fired_before = next(
            (m.value for nm, m in registry.items() if nm == fault_counter), 0.0)
        faults.install(
            f"seed={args.serve_seed},fetch_slow=1.0,slow_ms={stall_s * 1e3}",
            registry=registry)
        try:
            e1.serve(prompts[:2],
                     [GenerationConfig(max_new_tokens=window)] * 2,
                     metrics_interval=0.01)
            # keep ticking while fast-burning: the first tick captures, the
            # cooldown must swallow every later one
            ticks_while_burning = 0
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and ticks_while_burning < 6:
                if eng_slo.tick() and eng_slo.bundles:
                    ticks_while_burning += 1
                time.sleep(0.03)
        finally:
            faults.clear()
        fired = next(
            (m.value for nm, m in registry.items() if nm == fault_counter), 0.0
        ) - fired_before
        if not eng_slo.bundles:
            raise SystemExit(
                f"--slo-ab: {stall_s * 1e3:.0f}ms fetch stalls "
                f"({fired:.0f} injected) never tripped the {thr:.3f}s TTFT "
                f"SLO — the burn-rate trigger is dead")
        artifacts = sorted(f for f in os.listdir(flight_dir)
                           if f.startswith("slo-") and f.endswith(".json"))
        if len(eng_slo.bundles) != 1 or len(artifacts) != 1:
            raise SystemExit(
                f"--slo-ab: expected EXACTLY ONE diagnostics bundle after "
                f"{ticks_while_burning} fast-burning ticks, got "
                f"{len(eng_slo.bundles)} recorded / {artifacts} on disk — "
                f"the per-SLO cooldown does not rate-limit capture")
        with open(os.path.join(flight_dir, artifacts[0])) as fh:
            bundle = json.load(fh)
        verdict = bundle.get("slo", {})
        series = bundle.get("timeseries", [])
        burned = (
            bundle.get("kind") == "slo_bundle"
            and verdict.get("slo") == "ttft_burn"
            and verdict.get("fast_burning") is True
            and verdict.get("fast_burn", 0.0) >= 14.4
            and "stacks" in bundle and "events" in bundle
            and len(series) >= 2
            and (hist_count(series[-1], "serve/ttft_s")
                 - hist_count(series[0], "serve/ttft_s")) >= 1
        )
        if not burned:
            raise SystemExit(
                f"--slo-ab: bundle {artifacts[0]} does not contain the "
                f"offending window (kind={bundle.get('kind')!r}, "
                f"verdict={verdict}, {len(series)} time-series samples) — "
                f"the diagnostics froze the wrong evidence")
    finally:
        uninstall_slos()
        if env_before is None:
            os.environ.pop("ATPU_FLIGHT_DIR", None)
        else:
            os.environ["ATPU_FLIGHT_DIR"] = env_before

    # ---- arm 3: fleet health on vs off — <= 1% null-calibrated overhead
    # Same instrument as --trace-ab: rotating on/off/control arms, min-of-2
    # samples, 1.25x burst trim on each arm's own floor, pooled medians
    # re-checked per batch, gate = 1.01 + |control drift|.  The ON arm is
    # the full feature stack (SLO engine installed over a fresh ring store,
    # every request tenant-attributed, the run loop ticking at 20ms); the
    # OFF arms are a plain untenanted serve with no engine installed.
    pairs_per_batch = 24
    max_batches = 4
    min_kept = 12
    t_on, t_off, t_ctl = [], [], []
    for _ in range(2):  # discarded warm-up; also settles server teardown
        e3.serve(prompts, [gen] * n)

    def _serve_on():
        install_slos(
            specs=default_specs(ttft_threshold_s=3600.0,
                                tokens_floor_per_s=1e-9),
            store=TimeSeriesStore(registry=registry, capacity=1024,
                                  interval_s=0.02),
            registry=registry, cooldown_s=3600.0)
        try:
            out = [e3.submit(p, config=gen, tenant=tenants[i % 2])
                   for i, p in enumerate(prompts)]
            e3.run(metrics_interval=0.02)
            return out
        finally:
            uninstall_slos()

    on_reqs = _serve_on()
    on_tokens = [[int(t) for t in q.tokens] for q in on_reqs]
    if on_tokens != ref:
        raise SystemExit(
            "--slo-ab: tokens with the fleet-health layer on diverge from "
            "the reference — attribution touches the decode path")

    def _timed(on, sink):
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            if on:
                _serve_on()
            else:
                e3.serve(prompts, [gen] * n)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        sink.append(best)

    def _median(vals):
        vals = sorted(vals)
        mid = len(vals) // 2
        return (vals[mid] if len(vals) % 2
                else 0.5 * (vals[mid - 1] + vals[mid]))

    arms = [(True, t_on), (False, t_off), (False, t_ctl)]
    med_ratio = null_ratio = allowance = None
    for _ in range(max_batches):
        for k in range(pairs_per_batch):
            for flag, sink in arms[k % 3:] + arms[:k % 3]:
                _timed(flag, sink)
        lim_on = 1.25 * min(t_on)
        lim_off = 1.25 * min(t_off)
        lim_ctl = 1.25 * min(t_ctl)
        kept = [(on, off, c) for on, off, c in zip(t_on, t_off, t_ctl)
                if on <= lim_on and off <= lim_off and c <= lim_ctl]
        if len(kept) < min_kept:
            continue
        med_ratio = _median([on / off for on, off, _ in kept])
        null_ratio = _median([c / off for _, off, c in kept])
        allowance = abs(null_ratio - 1.0)
        if med_ratio <= 1.01 + allowance:
            break
    if med_ratio is None:
        raise SystemExit(
            f"--slo-ab: host contention too heavy to measure — fewer than "
            f"{min_kept} of {len(t_on)} paired samples survived the burst "
            f"trim; rerun on a quieter host")
    if med_ratio > 1.01 + allowance:
        raise SystemExit(
            f"--slo-ab: fleet-health-on serve is {med_ratio - 1.0:+.1%} vs "
            f"off (pooled median of {len(t_on)} paired min-of-2 samples "
            f"after burst trim) while the off-vs-off control shows "
            f"{null_ratio - 1.0:+.1%} instrument drift — attribution + SLO "
            f"ticking cost >1% beyond the demonstrated noise floor; gate "
            f"is <= {1.01 + allowance - 1.0:.1%}")

    # ---- arm 4: the fleet-health layer compiled nothing
    compiles_after = compile_counts()
    if compiles_after != compiles_before:
        diff = {k: (compiles_before.get(k), v)
                for k, v in compiles_after.items()
                if compiles_before.get(k) != v}
        raise SystemExit(f"--slo-ab: the fleet-health layer compiled new "
                         f"executables (name: before -> after): {diff}")

    flood_tps = useful_tokens / dt_flood
    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "num_slots": slots,
        "decode_window": window,
        "new_tokens_per_request": new_tokens,
        "useful_tokens": useful_tokens,
        "flood_wall_s": round(dt_flood, 3),
        "inproc_wall_s": round(dt_inproc, 3),
        "inproc_tokens_per_s": round(useful_tokens / dt_inproc, 2),
        "tenants": {
            "labels": list(tenants),
            "counter_keys_checked": sorted(keys),
            "sums_exact": True,                 # hard-checked above
            "ttft_observations": ttft_total,
        },
        "burn": {
            "ttft_threshold_s": round(thr, 4),
            "stall_ms": round(stall_s * 1e3, 1),
            "faults_injected": int(fired),
            "bundles": 1,                       # hard-checked above
            "fast_burn": round(verdict["fast_burn"], 1),
            "timeseries_samples": len(series),
        },
        "off": {
            "pairs": len(t_on),
            "outputs_token_identical": True,    # hard-checked above
            "on_best_s": round(min(t_on), 4),
            "off_best_s": round(min(t_off), 4),
            "on_vs_off_median": round(med_ratio, 4),
            "off_vs_off_control_median": round(null_ratio, 4),
            "gate": round(1.01 + allowance, 4),
            "new_executables": 0,               # hard-checked above
        },
    }
    return {
        "metric": "tenant_attributed_serving_tokens_per_sec",
        "value": round(flood_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(flood_tps / (useful_tokens / dt_inproc), 3),
        "detail": detail,
    }


def _hier_ab_bench(args, model, cfg, params, preset):
    """Hierarchical prefix cache A/B: host-RAM spill tier on vs off.

    The workload is grouped shared-prefix traffic whose distinct-prefix
    working set is ~10x the device-tier budget (``prefix_cache_mb`` holds ~1
    cached prefix, the rounds cycle through 10): without the host tier the
    device LRU thrashes and every returning group re-prefills its prefix from
    scratch; with it the evicted prefix spills to host RAM and each return is
    an H2D promotion enqueued behind the in-flight decode window.  Every
    check is HARD (SystemExit on failure):

    * greedy outputs token-identical between the arms (promotions land
      mid-decode under ``async_depth=1`` and must be invisible);
    * the on-arm actually serves prefix tokens from the host tier
      (``prefix_hit_tokens_host`` and ``serve/prefix_hit_rate_host`` > 0);
    * tokens/s >= 1.25x the spill-off arm and mean TTFT improved — the spill
      tier must BUY something on the oversubscribed mix, not just not lose;
    * promotion is overlapped, not serial: ``serve/host_overlap_ratio``
      stays > 0 and at least one ``serve/promote_h2d`` flight event carries
      ``behind_window=True`` (no synchronous fetch at admission);
    * zero new blocking readbacks on the hot path: in-process atpu-lint over
      the repo surface stays clean;
    * the compiled-executable budget grows by EXACTLY the documented set —
      one ``spill_<bucket>`` D2H gather + one ``promote_<bucket>`` H2D
      install per prefill bucket, each compiled at most once.
    """
    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.serving.paging import PagedKVPool
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)
    window = args.decode_window
    mp_full = max(16, min(args.seq, cfg.max_seq_len) // 2)
    page = max(4, mp_full // 4)
    buckets = (page, 4 * page)
    prefix_len = 4 * page              # exactly one full cacheable chunk
    mp = prefix_len + page             # room for a partial (uncached) suffix
    max_len = min(
        (cfg.max_seq_len // page) * page,
        ((mp + 4 * window) // page + 1) * page,
    )
    # few slots + a deep queue: decode windows stay in flight across every
    # admission (promotions genuinely overlap) and TTFT is queue-dominated,
    # so it tracks throughput instead of per-request scheduling jitter
    slots = min(args.batch, 4)

    groups = 10
    rounds = max(6, args.requests // groups)
    r = np.random.default_rng(args.serve_seed)
    prefixes = [
        r.integers(1, cfg.vocab_size, (prefix_len,)).astype(np.int32)
        for _ in range(groups)
    ]
    # round-robin across groups: by the time a group returns, the 9 prefixes
    # in between have thrashed it out of the 1-node device tier
    prompts = [
        np.concatenate(
            [prefixes[g],
             r.integers(1, cfg.vocab_size, (int(r.integers(2, page)),))
             .astype(np.int32)]
        )
        for _ in range(rounds) for g in range(groups)
    ]
    n = len(prompts)
    gens = [GenerationConfig(max_new_tokens=window) for _ in range(n)]
    useful_tokens = n * window

    # size the device tier from the pool's own accounting (a prefix node
    # costs 2 pages' data + scale slabs): ~1 resident node -> 10x working set
    probe = PagedKVPool(cfg, 1, page, page, 2, registry=MetricsRegistry())
    node_bytes = (prefix_len // page) * probe.page_kv_bytes
    del probe
    dev_mb = 1.05 * node_bytes / 2**20
    host_mb = 4.0 * groups * node_bytes / 2**20
    num_pages = slots * (max_len // page) + 4 * (prefix_len // page) + 1

    def run_arm(arm_host_mb):
        registry = MetricsRegistry()
        eng = ServingEngine(
            model, params, num_slots=slots, max_len=max_len,
            max_prompt_len=mp, prefill_buckets=buckets, decode_window=window,
            paged=True, page_size=page, num_pages=num_pages,
            prefix_cache_mb=dev_mb, prefix_host_mb=arm_host_mb,
            async_depth=1, registry=registry,
        )
        # warmup compiles every executable the timed region touches: both
        # prefill buckets + insert + decode (A, B), the spill gather (B's
        # insert evicts A), and the promote install (A's return hits its
        # spilled node)
        wa = r.integers(1, cfg.vocab_size, (prefix_len + 2,)).astype(np.int32)
        wb = r.integers(1, cfg.vocab_size, (prefix_len + 2,)).astype(np.int32)
        eng.serve([wa, wb, wa.copy()], GenerationConfig(max_new_tokens=window))
        if eng.prefix_cache is not None:
            eng.prefix_cache.flush()
        for k in eng.stats:
            eng.stats[k] = 0
        registry.reset()
        eng.recorder.clear()
        # best-of-N walls: the timed region is sub-second, so a single OS
        # scheduling stall swamps the ratio — transient noise only ever
        # inflates a wall, so min is the stable estimator.  Repeats start
        # from the steady tier state the previous pass left (exactly the
        # long-running-service shape this bench models) and double as a
        # no-retrace check: the compiled-budget gate still requires <= 1
        # compile per executable across every pass.
        dt = float("inf")
        for _ in range(max(3, args.iters)):
            t0 = time.perf_counter()
            reqs = eng.serve(prompts, gens)
            dt = min(dt, time.perf_counter() - t0)
        # snapshot now: the recorder is process-global and the other arm's
        # clear() would wipe these events
        events = list(eng.recorder.tail())
        return eng, reqs, dt, registry, events

    eng_on, reqs_on, dt_on, reg_on, events_on = run_arm(host_mb)
    eng_off, reqs_off, dt_off, reg_off, _ = run_arm(0.0)

    if [q.tokens for q in reqs_on] != [q.tokens for q in reqs_off]:
        raise SystemExit(
            "--hier-ab identity: host spill tier changed greedy outputs vs "
            "the spill-off arm on the same workload"
        )
    host_hit_tokens = eng_on.stats["prefix_hit_tokens_host"]
    host_hit_rate = float(reg_on.get("serve/prefix_hit_rate_host").value)
    if host_hit_tokens <= 0 or host_hit_rate <= 0:
        raise SystemExit(
            f"--hier-ab: no prefix tokens were served from the host tier "
            f"(hit tokens {host_hit_tokens}, rate {host_hit_rate}) on a "
            "10x-oversubscribed mix — the spill tier never engaged"
        )
    tps_on = useful_tokens / dt_on
    tps_off = useful_tokens / dt_off
    speedup = tps_on / tps_off
    if speedup < 1.25:
        raise SystemExit(
            f"--hier-ab: spill tier bought only {speedup:.3f}x tokens/s "
            f"({tps_on:.2f} vs {tps_off:.2f}) — gate is >= 1.25x on the "
            "oversubscribed shared-prefix mix"
        )
    ttft_on = reg_on.get("serve/ttft_s").snapshot()["mean"]
    ttft_off = reg_off.get("serve/ttft_s").snapshot()["mean"]
    if ttft_on >= ttft_off:
        raise SystemExit(
            f"--hier-ab: mean TTFT did not improve with the host tier "
            f"({1e3 * ttft_on:.2f}ms vs {1e3 * ttft_off:.2f}ms spill-off)"
        )
    overlap = float(reg_on.get("serve/host_overlap_ratio").value)
    if overlap <= 0:
        raise SystemExit(
            "--hier-ab: serve/host_overlap_ratio is 0 — the promotion path "
            "serialized the async loop"
        )
    promote_events = [e for e in events_on
                      if e.get("kind") == "serve/promote_h2d"]
    if not any(e.get("behind_window") for e in promote_events):
        raise SystemExit(
            "--hier-ab: no promotion was enqueued behind an in-flight decode "
            "window — promotions ran serially at admission"
        )

    import io
    from tools.atpu_lint.cli import main as atpu_lint_main
    buf = io.StringIO()
    if atpu_lint_main([], stdout=buf, stderr=buf) != 0:
        raise SystemExit(
            "--hier-ab: atpu-lint found new hot-path violations (blocking "
            f"readbacks / host syncs):\n{buf.getvalue()}"
        )

    counts_on = eng_on.compiled_executable_counts()
    counts_off = eng_off.compiled_executable_counts()
    expected_extra = ({f"spill_{b}" for b in buckets}
                      | {f"promote_{b}" for b in buckets})
    extra = set(counts_on) - set(counts_off)
    if extra != expected_extra:
        raise SystemExit(
            f"--hier-ab: compiled-executable budget grew by {sorted(extra)}, "
            f"expected exactly {sorted(expected_extra)}"
        )
    over = {k: v for k, v in counts_on.items() if v > 1}
    if over or counts_on[f"spill_{prefix_len}"] != 1 \
            or counts_on[f"promote_{prefix_len}"] != 1:
        raise SystemExit(
            f"--hier-ab: spill/install executables retraced or never "
            f"compiled: over-budget {over}, "
            f"spill_{prefix_len}={counts_on[f'spill_{prefix_len}']}, "
            f"promote_{prefix_len}={counts_on[f'promote_{prefix_len}']}"
        )

    def arm_detail(eng, dt, reg):
        ttft = reg.get("serve/ttft_s").snapshot()
        return {
            "wall_s": round(dt, 3),
            "tokens_per_s": round(useful_tokens / dt, 2),
            "ttft_mean_ms": round(1e3 * ttft["mean"], 2),
            "ttft_p99_ms": round(1e3 * ttft["p99"], 2),
            "prefix_hit_tokens": eng.stats["prefix_hit_tokens"],
            "prefix_hit_tokens_host": eng.stats["prefix_hit_tokens_host"],
            "prefix_cache": eng.prefix_cache_stats(),
            "compiled_executables": eng.compiled_executable_counts(),
        }

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": n,
        "groups": groups,
        "rounds": rounds,
        "prefix_len": prefix_len,
        "page_size": page,
        "prefill_buckets": list(buckets),
        "prefix_cache_mb": round(dev_mb, 5),
        "prefix_host_mb": round(host_mb, 5),
        "working_set_over_device_budget": round(
            groups * node_bytes / (dev_mb * 2**20), 2),
        "useful_tokens": useful_tokens,
        "outputs_token_identical": True,
        "host_hit_rate": round(host_hit_rate, 4),
        "host_overlap_ratio": round(overlap, 4),
        "promotions_behind_window": sum(
            1 for e in promote_events if e.get("behind_window")),
        "atpu_lint_clean": True,
        "spill_on": arm_detail(eng_on, dt_on, reg_on),
        "spill_off": arm_detail(eng_off, dt_off, reg_off),
    }
    return {
        "metric": "serving_hier_cache_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "detail": detail,
    }


def _disagg_ab_bench(args, model, cfg, params, preset):
    """Disaggregated prefill/decode A/B: role split + live KV page migration.

    Four arms over greedy/sampled workloads, every check HARD (SystemExit):

    * identity — the same submission order served by one monolithic engine
      and by a ``policy="disaggregated"`` router (prefill replica + decode
      replica, every lane handed off after its last prefill chunk) must
      return bit-identical tokens, greedy AND sampled (the live RNG row
      rides the migration), with one ``serve/prefill_handoffs_total`` per
      request and ZERO decode steps on the prefill replica;
    * crossover — migrate-vs-replay on a ladder of context lengths: move a
      2-token-deep lane to a warm peer either by page migration or by the
      failover replay path (export + adopt + re-prefill) and time until the
      next token lands.  Replay cost grows with the context it re-prefills;
      migration moves bytes.  The bench reports the crossover context
      length and HARD-requires migration to win at the top of the ladder —
      the regime ``migrate_lane()`` and failover-upgrade exist for;
    * chat TTFT — the adversarial mix: a flood of long bulk prefills, then
      short chat requests behind them.  Monolithic baseline: two
      ``role="both"`` replicas under the affinity router, each interleaving
      bulk prefill chunks with its decode windows.  Disaggregated arm: the
      same two-engine footprint split prefill/decode (the decode replica
      runs wider slots — it needs no prefill headroom; page pools are
      unchanged).  Chat p99 TTFT must IMPROVE: that is the one number the
      role split is for — decode windows never stall behind a bulk chunk,
      prefill drains at full duty, and prefill-replica slots recycle at
      handoff instead of being held through decode;
    * kill — a prefill replica is poisoned mid-handoff with a spare
      prefill-capable replica attached.  Zero failed requests: every
      request must finish with tokens identical to the monolithic greedy
      reference (readable pages migrate off the corpse; the rest replay).

    ``value``/``vs_baseline`` is the chat-p99-TTFT improvement (monolithic
    over disaggregated, > 1 is a win).  The compiled budget is gated too:
    the migration pair appears ONLY on engines that migrated, at most once
    each.
    """
    from accelerate_tpu.models.generation import GenerationConfig
    from accelerate_tpu.serving import PageMigrator, ReplicaRouter, ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)
    window = args.decode_window
    page = 4
    mp = -(-max(16, min(args.seq, cfg.max_seq_len) * 3 // 4) // page) * page
    buckets = tuple(sorted({max(8, -(-(mp // 4) // page) * page), mp}))
    max_len = min((cfg.max_seq_len // page) * page,
                  -(-(mp + 6 * window) // page) * page)
    slots = max(2, min(args.batch, 4))
    r = np.random.default_rng(args.serve_seed)

    def build(role, n_slots, registry, win=None, **kw):
        return ServingEngine(
            model, params, num_slots=n_slots, max_len=max_len,
            max_prompt_len=mp, prefill_buckets=buckets,
            decode_window=window if win is None else win,
            paged=True, page_size=page,
            num_pages=2 * n_slots * (max_len // page) + 1,
            prefix_cache_mb=0, async_depth=1, role=role, registry=registry,
            max_queue=max(64, 8 * args.requests),
            prefill_token_budget=buckets[0], **kw,
        )

    def prompt(n):
        return r.integers(1, cfg.vocab_size, (int(n),)).astype(np.int32)

    def gen(sampled, n):
        if sampled:
            return GenerationConfig(max_new_tokens=n, do_sample=True,
                                    temperature=0.8, top_k=50,
                                    eos_token_id=None)
        return GenerationConfig(max_new_tokens=n, do_sample=False,
                                eos_token_id=None)

    # ---- arm 1: token identity vs the monolithic baseline, greedy + sampled
    # fresh engines, no warmup: rid sequences must align between the mono
    # engine and the prefill replica so the sampled streams fold identically
    n_id = 6
    id_prompts = [prompt(int(r.integers(4, mp))) for _ in range(n_id)]
    id_gens = [gen(sampled=bool(k % 2), n=2 * window) for k in range(n_id)]
    mono = build("both", 2 * slots, MetricsRegistry())
    mono_reqs = mono.serve(id_prompts, id_gens)

    reg_id = MetricsRegistry()
    pre = build("prefill", slots, reg_id)
    dec = build("decode", 2 * slots, reg_id)
    dis = ReplicaRouter([pre, dec], policy="disaggregated", registry=reg_id)
    dis_reqs = [dis.submit(p, config=g) for p, g in zip(id_prompts, id_gens)]
    dis.run()
    for k, (qm, qd) in enumerate(zip(mono_reqs, dis_reqs)):
        if [int(t) for t in qm.tokens] != [int(t) for t in qd.tokens]:
            raise SystemExit(
                f"--disagg-ab identity: request {k} "
                f"({'sampled' if k % 2 else 'greedy'}) diverged between the "
                f"monolithic engine and the disaggregated split — migration "
                f"is not bit-transparent"
            )
    handoffs = int(reg_id.get("serve/prefill_handoffs_total").value)
    if handoffs != n_id:
        raise SystemExit(
            f"--disagg-ab identity: expected {n_id} prefill handoffs, "
            f"recorded {handoffs} — lanes are not leaving the prefill replica"
        )
    if pre.stats["decode_steps"] != 0:
        raise SystemExit(
            f"--disagg-ab identity: the prefill replica ran "
            f"{pre.stats['decode_steps']} decode steps; role='prefill' must "
            "never decode"
        )
    for e, name, expect in ((pre, "prefill", "migrate_extract"),
                            (dec, "decode", "migrate_install")):
        counts = e.compiled_executable_counts()
        if counts.get(expect) != 1:
            raise SystemExit(
                f"--disagg-ab budget: {name} replica compiled "
                f"{expect}={counts.get(expect)} (want exactly 1 across "
                f"{n_id} handoffs — fixed-width executables must not retrace)"
            )
    if set(mono.compiled_executable_counts()) & {"migrate_extract",
                                                 "migrate_install"}:
        raise SystemExit(
            "--disagg-ab budget: the monolithic engine compiled migration "
            "executables without ever migrating"
        )

    # ---- arm 2: migrate-vs-replay crossover over context length
    ladder = sorted({4 * page, mp // 4, mp // 2, mp})
    ladder = [-(-v // page) * page for v in ladder if v >= 2 * page]
    migrator = PageMigrator(MetricsRegistry())
    # a 2-token window keeps the lane shallow at migration time so the
    # timed differential is transfer-vs-re-prefill, not decode headroom
    src_m, dst_m, rep = (build("both", 2, MetricsRegistry(), win=2)
                         for _ in range(3))
    warm = [prompt(b) for b in buckets]
    wgen = gen(False, window)

    def slot_of(eng, req):
        return next(s for s in range(eng.num_slots)
                    if eng._slot_req[s] is req)

    def migrate_time(L):
        """Wall seconds from initiating the migration of a shallow lane with
        ``L`` prompt tokens until its next token lands on ``dst_m``."""
        req = src_m.submit(prompt(L), config=gen(False, 12))
        while len(req.tokens) < 2:
            src_m.step()
        t0 = time.perf_counter()
        migrator.migrate(src_m, dst_m, slot_of(src_m, req))
        before = len(req.tokens)  # in-flight windows land during the drain
        while len(req.tokens) <= before:
            dst_m.step()
        dt = time.perf_counter() - t0
        dst_m.run()
        src_m.run()
        return dt

    def replay_time(L):
        """The failover-replay cost for the same lane: ``adopt`` re-prefills
        ``prompt + generated`` (``Request.prefill_tokens``) on the survivor,
        so time a fresh (L+2)-token submission until its first token —
        identical work, without needing a corpse to export from."""
        t0 = time.perf_counter()
        req = rep.submit(prompt(min(L + 2, mp)), config=gen(False, 4))
        while len(req.tokens) < 1:
            rep.step()
        dt = time.perf_counter() - t0
        rep.run()
        return dt

    for e in (src_m, dst_m, rep):
        e.serve(warm, wgen)
    migrate_time(ladder[0])  # warm the migrate pair end to end

    curve = []
    for L in ladder:
        dt_m = min(migrate_time(L) for _ in range(max(3, args.iters)))
        dt_r = min(replay_time(L) for _ in range(max(3, args.iters)))
        curve.append({"context": L + 2, "migrate_ms": round(1e3 * dt_m, 3),
                      "replay_ms": round(1e3 * dt_r, 3)})
    if curve[-1]["migrate_ms"] >= curve[-1]["replay_ms"]:
        raise SystemExit(
            f"--disagg-ab crossover: migration never beat replay — at "
            f"context {curve[-1]['context']} migrate took "
            f"{curve[-1]['migrate_ms']}ms vs replay "
            f"{curve[-1]['replay_ms']}ms.  Curve: {curve}"
        )
    crossover = next(p["context"] for p in curve
                     if p["migrate_ms"] < p["replay_ms"])

    # ---- arm 3: chat p99 TTFT on the adversarial bulk-prefill + chat mix
    # mix-local geometry: the disaggregation scenario is a chat arriving
    # while bulk lanes are mid-decode, so bulk decode must be LONG relative
    # to its prefill — a short window with all remaining slot capacity spent
    # on decode.  The monolithic replicas hold a slot through prefill AND
    # that whole decode; the split recycles prefill slots at handoff.
    mw = min(4, window)
    mpx = min(-(-max(4 * page, mp // 2) // page) * page, max_len - 8 * mw)
    bx = tuple(sorted({max(8, -(-(mpx // 2) // page) * page), mpx}))
    bulk_new = max_len - mpx - mw

    def build_mix(role, n_slots, registry, budget=None):
        # the prefill-token budget exists to protect decode latency from
        # prefill interference; a prefill-only replica has no decode to
        # protect, so it runs the full bucket per step
        return ServingEngine(
            model, params, num_slots=n_slots, max_len=max_len,
            max_prompt_len=mpx, prefill_buckets=bx, decode_window=mw,
            paged=True, page_size=page,
            num_pages=2 * n_slots * (max_len // page) + 1,
            prefix_cache_mb=0, async_depth=1, role=role, registry=registry,
            max_queue=max(64, 8 * args.requests),
            prefill_token_budget=bx[0] if budget is None else budget,
        )

    n_chat = 6
    n_bulk = max(6, args.requests - n_chat)
    bulk_prompts = [prompt(mpx) for _ in range(n_bulk)]
    chat_prompts = [prompt(8) for _ in range(n_chat)]
    bulk_gen, chat_gen = gen(False, bulk_new), gen(False, mw)
    warm_x = [prompt(b) for b in bx]
    wgen_x = gen(False, mw)
    reps = max(2, args.iters // 2)

    def run_mix(router, registry, engines):
        for e in engines:  # compile everything outside the timed region
            if getattr(e, "role", "both") != "prefill":
                e.serve(warm_x, wgen_x)
        if any(getattr(e, "role", "both") == "prefill" for e in engines):
            for w in warm_x:
                router.submit(w, config=wgen_x)
            router.run()
        for e in engines:
            for k in e.stats:
                e.stats[k] = 0
        registry.reset()
        toks = []
        t0 = time.perf_counter()
        for _ in range(reps):
            qs = [router.submit(p, config=bulk_gen, request_class="bulk")
                  for p in bulk_prompts]
            # chats arrive mid-burst, once half the bulk lanes are decoding
            while sum(1 for q in qs if len(q.tokens) > 0) < n_bulk // 2:
                router.step()
            qs += [router.submit(p, config=chat_gen, request_class="chat")
                   for p in chat_prompts]
            router.run()
            toks.append([[int(t) for t in q.tokens] for q in qs])
        dt = time.perf_counter() - t0
        p99 = registry.get("serve/ttft_s_class_chat").snapshot()["p99"]
        return toks, dt, p99

    reg_m = MetricsRegistry()
    mono_engines = [build_mix("both", slots, reg_m) for _ in range(2)]
    mono_router = ReplicaRouter(mono_engines, registry=reg_m)
    mono_toks, dt_mono, p99_mono = run_mix(mono_router, reg_m, mono_engines)

    reg_d = MetricsRegistry()
    pre2 = build_mix("prefill", slots, reg_d, budget=bx[-1])
    dec2 = build_mix("decode", 4 * slots, reg_d)
    dis2 = ReplicaRouter([pre2, dec2], policy="disaggregated",
                         registry=reg_d)
    dis_toks, dt_dis, p99_dis = run_mix(dis2, reg_d, (pre2, dec2))

    if dis_toks != mono_toks:
        raise SystemExit(
            "--disagg-ab mix: greedy tokens diverged between the "
            "disaggregated split and the monolithic router on the same "
            "workload"
        )
    improvement = p99_mono / p99_dis if p99_dis > 0 else float("inf")
    if p99_dis >= p99_mono:
        raise SystemExit(
            f"--disagg-ab TTFT: chat p99 TTFT did not improve under the "
            f"disaggregated split — {1e3 * p99_dis:.2f}ms vs "
            f"{1e3 * p99_mono:.2f}ms monolithic on the bulk-prefill + chat "
            "mix"
        )

    # ---- arm 4: prefill replica killed mid-handoff — zero failed requests
    n_k = max(4, min(8, args.requests // 2))
    k_prompts = [prompt(mp) for _ in range(n_k)]
    k_gen = gen(False, 2 * window)
    ref = [[int(t) for t in q.tokens]
           for q in mono.serve(k_prompts, [k_gen] * n_k)]

    reg_k = MetricsRegistry()
    kills = [build("prefill", slots, reg_k), build("prefill", slots, reg_k),
             build("decode", 2 * slots, reg_k)]
    kr = ReplicaRouter(kills, policy="disaggregated", registry=reg_k,
                       breaker_base_s=3600.0)
    kr.migrator  # materialize the migration counters before polling them
    kreqs = [kr.submit(p, config=k_gen) for p in k_prompts]
    victim, steps = None, 0
    while victim is None:
        kr.step()
        steps += 1
        # mid-handoff: at least one lane already crossed to the decode
        # replica and the victim still owns work (mid-prefill lanes, lanes
        # awaiting the sweep, or queue) — the full failover ladder fires
        if int(reg_k.get("serve/prefill_handoffs_total").value) >= 1:
            busy = [e for e in kills[:2] if e.has_work]
            if busy:
                victim = max(busy, key=lambda e: sum(
                    q is not None for q in e._slot_req))
        if victim is None and steps > 300:
            raise SystemExit("--disagg-ab kill: never caught a prefill "
                             "replica mid-handoff; workload too small")
    victim.kill("disagg-ab: injected prefill replica loss")
    kr.run()
    got = [[int(t) for t in q.tokens] for q in kreqs]
    failed = [k for k, (g, want) in enumerate(zip(got, ref)) if g != want]
    if failed:
        raise SystemExit(
            f"--disagg-ab kill: {len(failed)}/{n_k} requests failed or "
            f"diverged after the prefill replica died mid-handoff "
            f"(first: request {failed[0]}, got {got[failed[0]][:6]}... want "
            f"{ref[failed[0]][:6]}...)"
        )
    k_migrated = int(reg_k.get("serve/migrations_total").value)
    k_replayed = kr.stats().get("requests_replayed", 0)

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "page_size": page,
        "prefill_buckets": list(buckets),
        "decode_window": window,
        "slots_monolithic": [slots, slots],
        "slots_disaggregated": {"prefill": slots, "decode": 2 * slots},
        "identity_requests": n_id,
        "prefill_handoffs": handoffs,
        "outputs_token_identical": True,
        "crossover_context_tokens": crossover,
        "migrate_vs_replay_curve": curve,
        "mix": {
            "bulk_requests": reps * n_bulk, "chat_requests": reps * n_chat,
            "bulk_prompt_len": mpx, "bulk_new_tokens": bulk_new,
            "decode_window": mw, "chat_prompt_len": 8,
            "decode_slots": 4 * slots,
            "chat_ttft_p99_ms_monolithic": round(1e3 * p99_mono, 2),
            "chat_ttft_p99_ms_disaggregated": round(1e3 * p99_dis, 2),
            "wall_s_monolithic": round(dt_mono, 3),
            "wall_s_disaggregated": round(dt_dis, 3),
        },
        "kill": {"requests": n_k, "failed": 0, "migrated_off": k_migrated,
                 "replayed": k_replayed, "steps_before_kill": steps},
    }
    return {
        "metric": "serving_disagg_chat_ttft_p99_improvement",
        "value": round(improvement, 3),
        "unit": "x",
        "vs_baseline": round(improvement, 3),
        "detail": detail,
    }


def _serve_bench(args, model, cfg, params, preset):
    """Continuous batching vs static ``generate`` on one mixed-length workload.

    Both sides decode greedily and both get credited only the USEFUL tokens
    (each request's own output length).  The static baseline runs the
    requests FCFS in groups of ``--batch``, every group padded to the
    workload's max prompt / max output — ONE compiled shape, warmed up before
    timing, exactly how ``generate`` would serve this queue.  The engine
    serves the same queue through the slot pool with chunked prefill and
    in-flight admission.

    ``--shared-prefix N`` switches to the prefix-caching workload: every
    prompt is one common N-token system prefix plus a per-request log-normal
    suffix.  The baseline becomes the SAME engine with the prefix cache off
    (``vs_baseline`` = cache-on tokens/s over cache-off tokens/s on identical
    requests), outputs are asserted token-identical between the two runs, and
    ``detail.prefix_hit_rate`` records the reuse the radix cache found.
    """
    if sum([bool(getattr(args, "paged_ab", False)),
            bool(getattr(args, "kernel_ab", False)),
            bool(getattr(args, "tp_ab", False)),
            bool(getattr(args, "async_ab", False)),
            bool(getattr(args, "http_ab", False)),
            bool(getattr(args, "chaos_ab", False)),
            bool(getattr(args, "trace_ab", False)),
            bool(getattr(args, "slo_ab", False)),
            bool(getattr(args, "prefill_ab", False)),
            bool(getattr(args, "hier_ab", False)),
            bool(getattr(args, "disagg_ab", False)),
            bool(args.shared_prefix)]) > 1:
        raise SystemExit("--paged-ab, --kernel-ab, --tp-ab, --async-ab, "
                         "--http-ab, --chaos-ab, --trace-ab, --slo-ab, "
                         "--prefill-ab, --hier-ab, --disagg-ab and "
                         "--shared-prefix are separate serve workloads; "
                         "pick one")
    if getattr(args, "paged_ab", False):
        return _paged_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "disagg_ab", False):
        return _disagg_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "hier_ab", False):
        return _hier_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "http_ab", False):
        return _http_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "chaos_ab", False):
        return _chaos_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "trace_ab", False):
        return _trace_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "slo_ab", False):
        return _slo_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "kernel_ab", False):
        return _kernel_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "prefill_ab", False):
        return _prefill_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "tp_ab", False):
        return _tp_ab_bench(args, model, cfg, params, preset)
    if getattr(args, "async_ab", False):
        return _async_ab_bench(args, model, cfg, params, preset)

    from accelerate_tpu.models.generation import GenerationConfig, generate
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry import MetricsRegistry

    params = jax.device_put(params)  # HBM-resident: serving is not an offload bench
    slots = args.batch
    window = args.decode_window
    max_len = cfg.max_seq_len
    mp = max(8, min(args.seq, max_len) // 2)          # longest admissible prompt
    buckets = tuple(sorted({max(8, mp // 4), max(8, mp // 2)}))

    # log-normal mixed lengths — the serving-paper workload shape (most
    # requests short, a heavy tail; ShareGPT-like sigma ~1), clipped to the
    # slot capacity
    r = np.random.default_rng(args.serve_seed)
    out_cap = min(max_len - window - mp, 2 * mp)
    shared = int(args.shared_prefix or 0)
    if shared:
        if shared > mp - 4:
            raise SystemExit(
                f"--shared-prefix {shared} leaves no room for per-request "
                f"suffixes (max admissible prompt is {mp})"
            )
        common = r.integers(1, cfg.vocab_size, (shared,)).astype(np.int32)
        suffix_lens = np.clip(
            np.rint(r.lognormal(np.log(max(4, (mp - shared) // 3)), 0.8, args.requests)),
            2, mp - shared,
        ).astype(int)
        prompt_lens = shared + suffix_lens
        prompts = [
            np.concatenate([common, r.integers(1, cfg.vocab_size, (int(n),)).astype(np.int32)])
            for n in suffix_lens
        ]
    else:
        prompt_lens = np.clip(
            np.rint(r.lognormal(np.log(max(8, mp // 3)), 0.8, args.requests)), 4, mp
        ).astype(int)
        prompts = [r.integers(1, cfg.vocab_size, (int(n),)).astype(np.int32) for n in prompt_lens]
    out_lens = np.clip(
        np.rint(r.lognormal(np.log(max(8, out_cap // 8)), 1.0, args.requests)), 4, out_cap
    ).astype(int)
    gens = [GenerationConfig(max_new_tokens=int(n)) for n in out_lens]
    useful_tokens = int(out_lens.sum())

    # slot capacity sized to the workload (like the static baseline's cache:
    # prompt + new tokens), not the model's full context — attention cost per
    # decode step scales with slot width
    slot_len = min(
        max_len,
        int(max(p + o for p, o in zip(prompt_lens, out_lens))) + window,
    )

    def run_engine(prefix_mb):
        """One warmed, timed engine pass over the workload.

        A private registry per run: the telemetry percentiles must cover the
        timed workload only, so warmup observations are wiped with the stats.
        """
        registry = MetricsRegistry()
        eng = ServingEngine(
            model, params, num_slots=slots, max_len=slot_len,
            prefill_buckets=buckets, max_prompt_len=mp, decode_window=window,
            registry=registry, prefix_cache_mb=prefix_mb,
        )
        # warmup: one request per bucket length compiles every executable
        # (each prefill bucket, insert, the decode window); with the cache on,
        # a duplicate of each drives one hit through every copy executable so
        # the timed region never pays a compile
        warm = [r.integers(1, cfg.vocab_size, (b,)).astype(np.int32) for b in buckets]
        if prefix_mb:
            warm = warm + [w.copy() for w in warm]
        eng.serve(warm, GenerationConfig(max_new_tokens=window))
        for k in eng.stats:
            eng.stats[k] = 0
        registry.reset()

        stamps = {}

        def on_token(req, tok):
            stamps.setdefault(req.rid, []).append(time.perf_counter())

        t0 = time.perf_counter()
        reqs = eng.serve(prompts, gens, on_token=on_token)
        dt = time.perf_counter() - t0
        # per-token latency samples at decode-window granularity, queue wait
        # included (what a caller actually observes)
        samples = np.concatenate(
            [np.diff(np.asarray([t0] + stamps[req.rid])) for req in reqs]
        )
        return eng, reqs, dt, registry, samples

    eng, reqs, dt_engine, registry, samples = run_engine(
        args.prefix_cache_mb if shared else 0
    )
    engine_tps = useful_tokens / dt_engine

    if shared:
        return _shared_prefix_result(
            args, preset, shared, prompt_lens, out_lens, useful_tokens,
            run_engine, eng, reqs, dt_engine, registry, samples, buckets, slots,
            window,
        )

    # static baseline: FCFS groups of `slots`, padded to the workload max —
    # one compiled (prompt, new_tokens) shape for every group
    P, N = int(prompt_lens.max()), int(out_lens.max())
    static_gen = GenerationConfig(max_new_tokens=N)
    batch = np.zeros((slots, P), np.int32)

    def run_group(idx):
        batch[:] = 0
        for row, i in enumerate(idx):
            batch[row, : len(prompts[i])] = prompts[i]
        seqs, _ = generate(model, params, jnp.asarray(batch), static_gen)
        return jax.block_until_ready(seqs)

    run_group(range(min(slots, len(prompts))))  # warmup / compile
    t0 = time.perf_counter()
    for start in range(0, len(prompts), slots):
        run_group(range(start, min(start + slots, len(prompts))))
    dt_static = time.perf_counter() - t0
    static_tps = useful_tokens / dt_static

    detail = {
        "preset": preset,
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "num_slots": slots,
        "decode_window": window,
        "prefill_buckets": list(buckets),
        "prompt_len_p50_max": [int(np.median(prompt_lens)), int(prompt_lens.max())],
        "out_len_p50_max": [int(np.median(out_lens)), int(out_lens.max())],
        "useful_tokens": useful_tokens,
        "engine_wall_s": round(dt_engine, 3),
        "static_wall_s": round(dt_static, 3),
        "static_tokens_per_s": round(static_tps, 2),
        "token_latency_p50_ms": round(1e3 * float(np.percentile(samples, 50)), 2),
        "token_latency_p99_ms": round(1e3 * float(np.percentile(samples, 99)), 2),
        "mean_slot_occupancy": round(eng.mean_slot_occupancy(), 3),
        "compiled_executables": eng.compiled_executable_counts(),
    }
    detail.update(_cost_detail(eng, dt_engine))
    # Engine-side telemetry (ISSUE: TTFT + per-token percentiles and compile
    # counts in the bench contract).  TTFT here includes queue wait — it is
    # submit-to-first-token as a caller observes it, not prefill time alone.
    ttft = registry.get("serve/ttft_s").snapshot()
    tok = registry.get("serve/token_latency_s").snapshot()
    detail["telemetry"] = {
        "ttft_ms": {k: round(1e3 * ttft[k], 2) for k in ("p50", "p90", "p99", "mean")},
        "token_latency_ms": {k: round(1e3 * tok[k], 2) for k in ("p50", "p90", "p99", "mean")},
        "compile_counts": {
            wd.name: wd.compile_count
            for wd in [eng._decode, eng._insert, *eng._prefill.values()]
        },
        "watchdog_over_budget": any(
            wd.over_budget()
            for wd in [eng._decode, eng._insert, *eng._prefill.values()]
        ),
    }
    return {
        "metric": "serving_tokens_per_sec",
        "value": round(engine_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(engine_tps / static_tps, 3),
        "detail": detail,
    }


def main():
    presets = _presets()
    parser = argparse.ArgumentParser()
    parser.add_argument("--task", choices=["decode", "prefill", "serve", "spec"],
                        default="decode")
    parser.add_argument("--requests", type=int, default=16,
                        help="serve task: total queued requests (depth > --batch slots)")
    parser.add_argument("--decode_window", type=int, default=8,
                        help="serve task: decode steps fused per engine iteration")
    parser.add_argument("--serve_seed", type=int, default=0,
                        help="serve task: workload RNG seed")
    parser.add_argument("--shared-prefix", dest="shared_prefix", type=int, default=0,
                        help="serve task: common system-prompt length shared by "
                             "every request (0 = off); benches the prefix KV "
                             "cache against a cache-off run of the same workload")
    parser.add_argument("--paged-ab", dest="paged_ab", action="store_true",
                        help="--task serve: A/B the paged KV allocator against "
                             "the legacy slab pool at the same KV HBM budget "
                             "on a heavy-tail workload (token-identical check)")
    parser.add_argument("--kernel-ab", dest="kernel_ab", action="store_true",
                        help="--task serve: A/B decode kernels and KV dtypes on "
                             "the paged engine (xla vs pallas, native vs "
                             "--kv-dtype) — token-identity and logit-divergence "
                             "hard checks, plus a byte-equal capacity probe")
    parser.add_argument("--tp-ab", dest="tp_ab", action="store_true",
                        help="--task serve: multi-chip A/B — tp=2 vs tp=1 "
                             "(token-identity, per-device KV bytes, and "
                             "executable-budget hard checks) plus router "
                             "affinity vs round-robin on a shared-prefix "
                             "workload; writes MULTICHIP_r06.json on success")
    parser.add_argument("--async-ab", dest="async_ab", action="store_true",
                        help="--task serve: A/B the depth-1 pipelined serve "
                             "loop (async_depth=1) against the synchronous "
                             "loop — token-identity across greedy/sampled/"
                             "speculative/paged/int8-KV arms, >= 10% tokens/s "
                             "on the streaming greedy arm, overlap gauge > 0, "
                             "and an unchanged compiled-executable budget")
    parser.add_argument("--http-ab", dest="http_ab", action="store_true",
                        help="--task serve: drive the OpenAI front door over "
                             "the wire — token-identity vs in-process submit, "
                             "per-request SSE TTFT < completion, a 429 flood "
                             "with zero engine errors, and a mid-bench weight "
                             "hot-swap with zero failed or mixed-weight "
                             "in-flight requests (all hard checks)")
    parser.add_argument("--chaos-ab", dest="chaos_ab", action="store_true",
                        help="--task serve: chaos the serving stack — kill a "
                             "replica mid-generation (zero failed requests, "
                             "token-identical replay on the survivor), soak "
                             "a seeded fault mix (>=99%% completion, zero "
                             "driver crashes), then prove faults-off costs "
                             "nothing (<=1%% A/B, zero new executables; all "
                             "hard checks)")
    parser.add_argument("--trace-ab", dest="trace_ab", action="store_true",
                        help="--task serve: gate per-request tracing — kill a "
                             "replica mid-generation and require every "
                             "response's X-Request-Id to resolve to a "
                             "waterfall whose phase sum matches its TTFT "
                             "within 5%%, a failover trace spanning both "
                             "replicas, populated slowest-K retention, "
                             "token-identity traces on vs off, <=1%% paired "
                             "overhead, and an unchanged compiled-executable "
                             "budget (all hard checks)")
    parser.add_argument("--slo-ab", dest="slo_ab", action="store_true",
                        help="--task serve: gate the fleet-health layer — a "
                             "two-tenant HTTP flood whose per-tenant counter "
                             "and TTFT-histogram deltas must sum EXACTLY to "
                             "the globals, a fetch_slow-forced SLO fast-burn "
                             "that must capture exactly one diagnostics "
                             "bundle containing the offending window, <=1%% "
                             "null-calibrated paired overhead with the layer "
                             "on, and an unchanged compiled-executable "
                             "budget (all hard checks)")
    parser.add_argument("--prefill-ab", dest="prefill_ab", action="store_true",
                        help="--task serve: A/B the flash-prefill kernel and "
                             "decode-interleaved chunked prefill against the "
                             "admit-then-decode gather/scatter base on an "
                             "adversarial long-prompt-tenant + chat mix — "
                             "token-identity, executable-budget, and chat "
                             "p99-TTFT >= 1.3x hard checks; prefill tokens/s "
                             "gated on TPU")
    parser.add_argument("--hier-ab", dest="hier_ab", action="store_true",
                        help="--task serve: A/B the hierarchical prefix cache "
                             "(host-RAM spill tier + decode-overlapped H2D "
                             "promotion) against spill-off on a shared-prefix "
                             "mix whose working set is ~10x prefix_cache_mb — "
                             "token-identity, host hit rate > 0, tokens/s >= "
                             "1.25x, mean-TTFT, overlap, atpu-lint, and "
                             "executable-budget hard checks")
    parser.add_argument("--disagg-ab", dest="disagg_ab", action="store_true",
                        help="--task serve: A/B disaggregated prefill/decode "
                             "(role split + live KV page migration) against "
                             "the monolithic router — token identity greedy "
                             "AND sampled, a migrate-vs-replay crossover "
                             "curve (migration must win at the top), chat "
                             "p99 TTFT improvement on the adversarial "
                             "bulk-prefill + chat mix, zero failed requests "
                             "when a prefill replica dies mid-handoff, and "
                             "executable-budget hard checks")
    parser.add_argument("--kv-dtype", dest="kv_dtype", choices=["int8", "fp8"],
                        default="int8",
                        help="--kernel-ab: quantized KV page format for the "
                             "quantized arms")
    parser.add_argument("--kv-quant-tol", dest="kv_quant_tol", type=float,
                        default=1.5,
                        help="--kernel-ab: max tolerated logit divergence on "
                             "the quantized replay oracle (the bench exits "
                             "nonzero above it)")
    parser.add_argument("--prefix-cache-mb", dest="prefix_cache_mb", type=float,
                        default=64.0,
                        help="serve task: prefix KV cache byte budget (MiB) for "
                             "the --shared-prefix run")
    parser.add_argument("--speculate-k", dest="speculate_k", type=int, default=8,
                        help="spec task: draft tokens verified per cycle")
    parser.add_argument("--tree-ab", dest="tree_ab", action="store_true",
                        help="--task spec: A/B tree speculation with an "
                             "on-device draft model — token-identity across "
                             "{slab, paged} x {bf16, int8 KV} x {tp=1, tp=2}, "
                             ">= 1.4x tokens/s over speculation-off on a "
                             "non-repetitive workload (n-gram accept < 0.05 "
                             "in the same run), an acceptance-vs-speedup "
                             "curve in the JSON, and an executable budget "
                             "that grows by exactly {draft_forward, "
                             "tree_verify_window} with zero retraces "
                             "(all hard checks)")
    parser.add_argument("--spec_new_tokens", type=int, default=384,
                        help="spec task: generated tokens per request (long "
                             "enough for greedy decode to settle into the "
                             "repetitive pattern drafting exploits)")
    parser.add_argument("--preset", choices=list(presets), default=None,
                        help="default: small on TPU, tiny elsewhere (gpt2-xl = parity geometry)")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=512,
                        help="prefill length (decode task: prompt length = seq)")
    parser.add_argument("--new_tokens", type=int, default=4,
                        help="decode task: timed generated tokens (each token "
                             "streams the full weight set; size the count to "
                             "the host link)")
    parser.add_argument("--iters", type=int, default=4)
    parser.add_argument("--bits", type=int, choices=[8, 4], default=None,
                        help="stream int-quantized weights")
    parser.add_argument("--layers_per_stage", type=int, default=None,
                        help="layers streamed per chunk (default: ~6 chunks)")
    parser.add_argument("--checkpoint", type=str, default=None,
                        help="REAL checkpoint dir (raw HF gpt2/llama snapshot or "
                             "converted native): streams actual weights instead "
                             "of a synthetic preset")
    args = parser.parse_args()

    from accelerate_tpu import StreamingTransformer
    from accelerate_tpu.models.transformer import Transformer, TransformerConfig

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if args.checkpoint is not None:
        # real-weights path: HF-dir auto-convert (models/hf_compat) + host load
        from accelerate_tpu.big_modeling import _checkpoint_files, _read_tensors
        from accelerate_tpu.models.hf_compat import (
            config_from_hf, convert_hf_checkpoint, is_hf_checkpoint,
        )
        from accelerate_tpu.utils.modeling import unflatten_tree

        ckpt = args.checkpoint
        t_ckpt_load = time.perf_counter()
        if is_hf_checkpoint(ckpt):
            cfg = config_from_hf(ckpt, dtype=jnp.bfloat16)
            ckpt = convert_hf_checkpoint(ckpt, dtype=jnp.bfloat16)
        elif os.path.isfile(os.path.join(ckpt, "atpu_conversion.json")):
            # already-converted native dir: the stamp carries the source config
            cfg = config_from_hf(ckpt, dtype=jnp.bfloat16)
        else:
            raise SystemExit(
                f"--checkpoint {ckpt}: neither a supported raw HF model dir nor "
                "a converted _atpu_native dir"
            )
        files = _checkpoint_files(ckpt)
        params = unflatten_tree(_read_tensors(files, list(files)))  # host numpy
        # the reference's published pairs are (load time, s/token) —
        # benchmarks/README.md:31-37; conversion is cached so steady-state
        # load time is the disk -> host read
        checkpoint_load_s = time.perf_counter() - t_ckpt_load
        preset = f"checkpoint:{os.path.basename(os.path.abspath(args.checkpoint))}"
        model = Transformer(cfg)
        seq = min(args.seq, cfg.max_seq_len)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (args.batch, seq)).astype(np.int32)
    else:
        # Default: "small" (~0.53 GB) even on TPU — through the tunneled transport a
        # single gpt2-xl (4.25 GB) weight stream plus its ~14 remote stage
        # compiles exceeds half an hour, which no bench budget survives.  The
        # measured metric (stream GB/s, s/token) is model-size-normalized; run
        # `--preset gpt2-xl` explicitly on rigs with direct PCIe/DMA host links.
        preset = args.preset or ("small" if on_tpu else "tiny")
        cfg = presets[preset](dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
        seq = min(args.seq, cfg.max_seq_len)
        model = Transformer(cfg)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (args.batch, seq)).astype(np.int32)

        # abstract init, then materialize straight to HOST numpy — the weights
        # must not be HBM-resident for this benchmark to mean anything.
        params = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), jnp.ones((1, seq), jnp.int32)))["params"]
        leaves, treedef = jax.tree_util.tree_flatten(params)
        host_leaves = []
        for i, leaf in enumerate(leaves):
            # cheap deterministic host-side init (no device round-trip for huge models)
            r = np.random.default_rng(i)
            host_leaves.append((r.standard_normal(leaf.shape, dtype=np.float32) * 0.02).astype(jnp.bfloat16))
        params = jax.tree_util.tree_unflatten(treedef, host_leaves)

    if args.task in ("serve", "spec"):
        if args.bits is not None:
            raise SystemExit(f"--task {args.task} benches HBM-resident decode; "
                             "--bits applies to the streaming tasks")
        bench = _serve_bench if args.task == "serve" else _spec_bench
        result = bench(args, model, cfg, params, preset)
        print(json.dumps(result))
        return

    # parameter count BEFORE quantization (int4 packing halves the element
    # count, which would skew the analytic-FLOPs MFU below)
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params)
    )

    stream_cfg = cfg
    if args.bits is not None:
        from accelerate_tpu import Int4Config, Int8Config, quantize_model_params

        qconf = Int8Config() if args.bits == 8 else Int4Config()
        # quantize on the host CPU backend: on the default (TPU) device this
        # would round-trip the whole fp model through the transport first
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = quantize_model_params(params, qconf)
        params = jax.tree_util.tree_map(np.asarray, params)
        stream_cfg = dataclasses.replace(cfg, quantization=args.bits)

    model_bytes = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params)
    )

    def force(x):
        # block_until_ready is unreliable over tunneled TPU transports; a small
        # D2H materialization is the portable completion barrier.
        return float(jnp.asarray(x).ravel()[0])

    lps = args.layers_per_stage or max(1, cfg.num_layers // 6)
    streamer = StreamingTransformer(stream_cfg, params, layers_per_stage=lps)

    detail = {
        "preset": preset,
        "model_gb": round(model_bytes / 1e9, 2),
        "baseline_stream_gbps": REFERENCE_STREAM_GBPS,
        "batch": args.batch,
        "seq": seq,
        "bits": args.bits or 16,
        "layers_per_stage": lps,
        **({"checkpoint_load_s": round(checkpoint_load_s, 2)} if args.checkpoint else {}),
        "platform": jax.devices()[0].platform,
    }

    if args.task == "decode":
        # the reference's published workload: per-token generation with every
        # token streaming the whole weight set (AlignDevicesHook offload loop)
        prompt = ids
        t_load = time.perf_counter()
        cache = streamer.init_cache(args.batch, prompt.shape[1] + args.new_tokens + 1)
        logits, cache = streamer.forward_with_cache(prompt, cache)  # prefill + compile
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        # warmup decode step (compiles the S=1 executables)
        logits, cache = streamer.forward_with_cache(tok[:, None], cache)
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        force(tok)
        prefill_s = time.perf_counter() - t_load

        t0 = time.perf_counter()
        for _ in range(args.new_tokens):
            logits, cache = streamer.forward_with_cache(tok[:, None], cache)
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        force(tok)
        dt = time.perf_counter() - t0

        s_per_token = dt / args.new_tokens
        tokens_per_s = args.batch * args.new_tokens / dt
        stream_gbps = model_bytes * args.new_tokens / dt / 1e9
        # Streaming dispatches per-stage executables, so there is no single
        # lowered callable to ask XLA about — analytic 2N FLOPs/token.  For
        # offload decode MFU is dominated by the host link, not the MXU.
        from accelerate_tpu.telemetry import detect_device_peaks

        peaks = detect_device_peaks()
        mfu = 2.0 * n_params * args.batch * args.new_tokens / dt / peaks.flops_per_s
        detail.update(
            {
                "s_per_token": round(s_per_token, 4),
                "new_tokens": args.new_tokens,
                "prefill_and_warmup_s": round(prefill_s, 2),
                "effective_stream_gbps": round(stream_gbps, 2),
                "mfu": round(min(1.0, mfu), 6),
                "mfu_source": "analytic_2N",
            }
        )
        result = {
            "metric": "streaming_decode_tokens_per_sec",
            "value": round(tokens_per_s, 2),
            "unit": "tokens/s",
            "vs_baseline": round(stream_gbps / REFERENCE_STREAM_GBPS, 3),
            "detail": detail,
        }
    else:
        force(streamer(ids))  # warmup: compiles the 3 stage executables
        t0 = time.perf_counter()
        for _ in range(args.iters):
            force(streamer(ids))
        dt = time.perf_counter() - t0

        tokens = args.batch * seq * args.iters
        stream_gbps = model_bytes * args.iters / dt / 1e9
        from accelerate_tpu.telemetry import detect_device_peaks

        peaks = detect_device_peaks()
        detail.update(
            {
                "iters": args.iters,
                "effective_stream_gbps": round(stream_gbps, 2),
                "forward_ms": round(1e3 * dt / args.iters, 1),
                "mfu": round(min(1.0, 2.0 * n_params * tokens / dt / peaks.flops_per_s), 6),
                "mfu_source": "analytic_2N",
            }
        )
        result = {
            "metric": "streaming_prefill_tokens_per_sec",
            "value": round(tokens / dt, 1),
            "unit": "tokens/s",
            "vs_baseline": round(stream_gbps / REFERENCE_STREAM_GBPS, 3),
            "detail": detail,
        }

    print(json.dumps(result))


if __name__ == "__main__":
    main()

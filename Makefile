# Developer targets (reference Makefile:25-72 test split analog).

.PHONY: test test_fast test_slow test_core test_big_modeling test_cli test_examples \
        test_multiprocess test_kernels native bench bench-serve chaos quality lint-json

test:
	python -m pytest tests/ -q

# the developer loop: everything not marked slow (< 2 min; see tests/conftest.py)
test_fast:
	python -m pytest tests/ -q -m "not slow"

test_slow:
	python -m pytest tests/ -q -m "slow"

# split targets for CI sharding
test_core:
	python -m pytest tests/ -q --ignore=tests/test_examples.py \
	    --ignore=tests/test_big_modeling.py --ignore=tests/test_cli.py \
	    --ignore=tests/test_multiprocess.py --ignore=tests/test_flash_attention.py \
	    --ignore=tests/test_ring_attention.py --ignore=tests/test_fp8.py \
	    --ignore=tests/test_quantization.py

test_big_modeling:
	python -m pytest tests/test_big_modeling.py tests/test_quantization.py -q

test_cli:
	python -m pytest tests/test_cli.py -q

test_examples:
	python -m pytest tests/test_examples.py -q

test_multiprocess:
	python -m pytest tests/test_multiprocess.py -q

test_kernels:
	python -m pytest tests/test_flash_attention.py tests/test_ring_attention.py tests/test_fp8.py -q

native:
	$(MAKE) -C accelerate_tpu/native

bench:
	python bench.py
	python bench_inference.py

# serving-engine A/Bs: continuous batching vs static generate, prefix-cache
# on/off, and speculative decoding on/off (the spec run hard-fails unless
# greedy outputs are token-identical between the two arms)
bench-serve:
	python bench_inference.py --task serve
	python bench_inference.py --task serve --shared-prefix 16
	python bench_inference.py --task serve --paged-ab
	python bench_inference.py --task serve --kernel-ab
	python bench_inference.py --task serve --prefill-ab
	python bench_inference.py --task serve --hier-ab
	python bench_inference.py --task serve --tp-ab
	python bench_inference.py --task serve --async-ab
	python bench_inference.py --task serve --http-ab
	python bench_inference.py --task serve --chaos-ab
	python bench_inference.py --task serve --trace-ab
	python bench_inference.py --task serve --slo-ab
	python bench_inference.py --task serve --disagg-ab
	python bench_inference.py --task spec
	python bench_inference.py --task spec --tree-ab

# fault-tolerance gate: the deterministic fault-injection test suite plus the
# chaos A/B (replica kill -> token-identical replay, seeded fault soak, and a
# faults-off overhead check; every check in the bench is a hard SystemExit)
chaos:
	python -m pytest tests/test_fault_tolerance.py -q
	python bench_inference.py --task serve --chaos-ab

# one process, one AST load per file, all ten rules (tools/atpu_lint/rules/);
# the lint surface includes the linter itself (docs/development/static-analysis.md)
quality:
	python -m compileall -q accelerate_tpu
	python -m tools.atpu_lint accelerate_tpu tests tools bench.py bench_inference.py

# machine-readable report for CI artifacts / editor integration
lint-json:
	@python -m tools.atpu_lint accelerate_tpu tests tools bench.py bench_inference.py --format json

"""Real HF-checkpoint interop: key mapping + logits parity vs torch transformers.

The reference's flagship capability is loading actual HF checkpoints
(``/root/reference/src/accelerate/utils/modeling.py:1608-1830``).  These tests
build REAL HF-format checkpoints (torch ``save_pretrained`` — genuine GPT-2 /
Llama key naming, Conv1D vs Linear layouts, tied embeddings, safetensors and
torch-bin serialization) and assert the converted flax model reproduces the
torch implementation's logits.  The rig has no network egress, so weights are
randomly initialized — parity over random weights exercises every mapped
tensor (any wrong split/transpose/norm placement shows up as divergence).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from accelerate_tpu.models.hf_compat import (
    config_from_hf,
    convert_hf_checkpoint,
    is_hf_checkpoint,
    load_hf_checkpoint,
)
from accelerate_tpu.models.transformer import Transformer


def _save_tiny_gpt2(tmp_path, safe_serialization=True):
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=safe_serialization)
    return model


def _save_tiny_llama(tmp_path, tie=False):
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=160,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=tie,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def _flax_logits(checkpoint, ids: np.ndarray) -> np.ndarray:
    cfg = config_from_hf(checkpoint, dtype=jnp.float32, param_dtype=jnp.float32)
    native = convert_hf_checkpoint(checkpoint)
    from accelerate_tpu.big_modeling import checkpoint_shapes, _checkpoint_files, _read_tensors
    from accelerate_tpu.utils.modeling import unflatten_tree

    files = _checkpoint_files(native)
    params = unflatten_tree(_read_tensors(files, list(files)))
    model = Transformer(cfg)
    return np.asarray(model.apply({"params": params}, jnp.asarray(ids)))


def _torch_logits(model, ids: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        return model(torch.from_numpy(ids)).logits.float().numpy()


class TestGPT2Parity:
    def test_logits_match_torch(self, tmp_path):
        model = _save_tiny_gpt2(tmp_path)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, size=(2, 17)).astype(np.int64)
        ours = _flax_logits(str(tmp_path), ids)
        ref = _torch_logits(model, ids)
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_torch_bin_serialization(self, tmp_path):
        """Old-style pytorch_model.bin shards go through the same mapping."""
        model = _save_tiny_gpt2(tmp_path, safe_serialization=False)
        ids = np.arange(10, dtype=np.int64)[None, :]
        ours = _flax_logits(str(tmp_path), ids)
        ref = _torch_logits(model, ids)
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_config_mapping(self, tmp_path):
        _save_tiny_gpt2(tmp_path)
        cfg = config_from_hf(str(tmp_path))
        assert cfg.norm_type == "layernorm"
        assert cfg.positional == "learned"
        assert cfg.mlp_variant == "gelu"
        assert cfg.use_bias and cfg.tie_word_embeddings
        assert cfg.intermediate_size == 4 * 64


class TestLlamaParity:
    def test_logits_match_torch_gqa(self, tmp_path):
        model = _save_tiny_llama(tmp_path)
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 128, size=(2, 13)).astype(np.int64)
        ours = _flax_logits(str(tmp_path), ids)
        ref = _torch_logits(model, ids)
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_tied_embeddings(self, tmp_path):
        model = _save_tiny_llama(tmp_path, tie=True)
        ids = np.arange(8, dtype=np.int64)[None, :]
        ours = _flax_logits(str(tmp_path), ids)
        ref = _torch_logits(model, ids)
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


class TestDispatchIntegration:
    def test_auto_detect_and_dispatch(self, tmp_path):
        """load_checkpoint_and_dispatch pointed at the RAW HF dir: detects,
        converts (cached), places, and the placed tree runs the model."""
        from accelerate_tpu.big_modeling import load_checkpoint_and_dispatch

        model_t = _save_tiny_gpt2(tmp_path)
        assert is_hf_checkpoint(str(tmp_path))
        cfg = config_from_hf(str(tmp_path), dtype=jnp.float32, param_dtype=jnp.float32)
        model = Transformer(cfg)
        params, device_map, loader = load_checkpoint_and_dispatch(
            model, str(tmp_path), device_map="auto", max_memory={0: 1 << 30}
        )
        assert set(device_map) == set(params)
        assert set(device_map.values()) == {0}
        ids = np.arange(9, dtype=np.int64)[None, :]
        logits = model.apply({"params": params}, jnp.asarray(ids))
        np.testing.assert_allclose(
            np.asarray(logits), _torch_logits(model_t, ids), rtol=2e-4, atol=2e-4
        )
        # conversion is cached: second call reuses _atpu_native
        stamp = os.path.join(str(tmp_path), "_atpu_native", "atpu_conversion.json")
        mtime = os.path.getmtime(stamp)
        load_checkpoint_and_dispatch(model, str(tmp_path), device_map="auto")
        assert os.path.getmtime(stamp) == mtime

    def test_load_hf_checkpoint_streaming(self, tmp_path):
        """The one-call flow feeds StreamingTransformer (the big-model
        inference engine) and matches the monolithic logits."""
        from accelerate_tpu.big_modeling import StreamingTransformer

        model_t = _save_tiny_gpt2(tmp_path)
        model, params, device_map, loader = load_hf_checkpoint(
            str(tmp_path),
            device_map={"embed_tokens": "cpu", "pos_embed": "cpu",
                        "layers_0": "cpu", "layers_1": "cpu", "final_norm": "cpu"},
            config_overrides=dict(dtype=jnp.float32, param_dtype=jnp.float32),
        )
        streamer = StreamingTransformer(
            model.config, params, device_map=device_map, weights_loader=loader
        )
        ids = np.arange(7, dtype=np.int64)[None, :]
        logits = streamer(jnp.asarray(ids))
        np.testing.assert_allclose(
            np.asarray(logits), _torch_logits(model_t, ids), rtol=2e-4, atol=2e-4
        )

    def test_unsupported_arch_raises(self, tmp_path):
        with open(os.path.join(tmp_path, "config.json"), "w") as f:
            json.dump({"model_type": "mamba"}, f)
        assert not is_hf_checkpoint(str(tmp_path))
        with pytest.raises(NotImplementedError, match="mamba"):
            config_from_hf(str(tmp_path))


class TestScanLayout:
    def test_restacked_params_match(self, tmp_path):
        """Converted layers_{i} layout restacks into scan_layers=True and
        reproduces the same logits — the fine-tune-a-real-checkpoint path."""
        import dataclasses

        from accelerate_tpu.big_modeling import _checkpoint_files, _read_tensors
        from accelerate_tpu.models.hf_compat import to_scan_layout
        from accelerate_tpu.utils.modeling import unflatten_tree

        model_t = _save_tiny_gpt2(tmp_path)
        cfg = config_from_hf(str(tmp_path), dtype=jnp.float32, param_dtype=jnp.float32)
        native = convert_hf_checkpoint(str(tmp_path))
        files = _checkpoint_files(native)
        params = unflatten_tree(_read_tensors(files, list(files)))
        scan_params = to_scan_layout(params, cfg.num_layers)
        scan_cfg = dataclasses.replace(cfg, scan_layers=True)
        ids = np.arange(11, dtype=np.int64)[None, :]
        logits = Transformer(scan_cfg).apply({"params": scan_params}, jnp.asarray(ids))
        np.testing.assert_allclose(
            np.asarray(logits), _torch_logits(model_t, ids), rtol=2e-4, atol=2e-4
        )


class TestSharding:
    def test_reconversion_clears_stale_outputs(self, tmp_path):
        """A multi-shard conversion followed by a single-shard re-conversion
        must not leave the old index.json shadowing the new model.safetensors
        (checkpoint discovery prefers the index)."""
        from accelerate_tpu.big_modeling import _checkpoint_files

        _save_tiny_gpt2(tmp_path)
        out = str(tmp_path / "native")
        convert_hf_checkpoint(str(tmp_path), out_dir=out, max_shard_bytes=64 << 10)
        assert os.path.isfile(os.path.join(out, "model.safetensors.index.json"))
        convert_hf_checkpoint(str(tmp_path), out_dir=out, force=True)  # default: 1 shard
        assert not os.path.isfile(os.path.join(out, "model.safetensors.index.json"))
        files = _checkpoint_files(out)
        assert set(files.values()) == {os.path.join(out, "model.safetensors")}
        assert not [f for f in os.listdir(out) if f.endswith(".part")]

    def test_config_from_converted_dir(self, tmp_path):
        """The conversion stamp carries the source config: a native dir alone
        (no raw HF snapshot around) rebuilds the TransformerConfig."""
        _save_tiny_gpt2(tmp_path)
        out = convert_hf_checkpoint(str(tmp_path), out_dir=str(tmp_path / "native"))
        cfg = config_from_hf(out)
        assert cfg.norm_type == "layernorm" and cfg.num_layers == 2

    def test_conversion_shards_and_bf16(self, tmp_path):
        """Tiny max_shard_bytes forces the sharded+index output path; bf16
        cast halves the bytes en route."""
        _save_tiny_gpt2(tmp_path)
        out = convert_hf_checkpoint(
            str(tmp_path), out_dir=str(tmp_path / "sharded"),
            dtype=jnp.bfloat16, max_shard_bytes=64 << 10,
        )
        index = os.path.join(out, "model.safetensors.index.json")
        assert os.path.isfile(index)
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        assert len(set(weight_map.values())) > 1
        from safetensors import safe_open

        fname = weight_map["embed_tokens.embedding"]
        with safe_open(os.path.join(out, fname), framework="np") as f:
            t = f.get_tensor("embed_tokens.embedding")
        assert t.dtype == jnp.bfloat16
